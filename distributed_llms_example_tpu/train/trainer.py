"""The unified Trainer — one SPMD core, three launch modes.

The reference maintains three near-duplicate ~120-line ModelTrainer classes
(reference train-torchrun.py:24, train-accelerator.py:29, train-task.py:72)
because each distribution mechanism (torchrun-DDP / Accelerate / raw
torch.distributed) imposes its own ceremony.  Under SPMD they are the same
program at different mesh shapes, so this Trainer covers all three:

- single process, many chips  (≈ torchrun / accelerate single host)
- multi-host                  (≈ train-task; ``initialize_distributed``
                                consumes the same Valohai triple).
                                ``output_dir`` must be one SHARED
                                filesystem path (GCS / NFS / Valohai
                                outputs): checkpoints are written
                                collaboratively — every process commits
                                its own shards and orbax's finalize
                                barrier waits for all of them
- single chip / CPU           (local dev)

Capabilities the reference has that live here: epoch training loop with
JSON-line loss logging (train-accelerator.py:217-232), periodic +
end-of-epoch ROUGE eval (train-accelerator.py:237-268 — plus the
``--evaluation-steps`` cadence the reference only honors in variant A),
final save with Valohai sidecars (helpers.py).  Capabilities it lacks that
live here too: periodic checkpointing with resume, bf16 policy, gradient
accumulation everywhere, deterministic multi-host data sharding.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Sequence

import jax
import numpy as np

from distributed_llms_example_tpu.core.config import TrainConfig
from distributed_llms_example_tpu.core.mesh import build_mesh, device_report
from distributed_llms_example_tpu.core.precision import parse_dtype
from distributed_llms_example_tpu.data.batching import LABEL_PAD, BatchIterator
from distributed_llms_example_tpu.data.dataset import (
    CausalLMDataset,
    SummarizationDataset,
    host_batch_slices,
)
from distributed_llms_example_tpu.data.prefetch import Prefetcher
from distributed_llms_example_tpu.data.tokenizer import get_tokenizer
from distributed_llms_example_tpu.evaluation.evaluate import Evaluator
from distributed_llms_example_tpu.io.checkpoint import (
    Checkpointer,
    ReshardError,
    abstract_like,
    describe_factorization,
    mesh_layout_array,
    parse_mesh_layout,
)
from distributed_llms_example_tpu.io.valohai_meta import save_valohai_metadata
from distributed_llms_example_tpu.models.registry import load_model
from distributed_llms_example_tpu.parallel.sharding import shard_params
from distributed_llms_example_tpu.train.optim import make_optimizer_bundle
from distributed_llms_example_tpu.train.step import (
    create_train_state,
    make_train_step,
    put_batch,
    state_shardings,
)
from distributed_llms_example_tpu.utils.backoff import sleep_backoff
from distributed_llms_example_tpu.utils.jsonlog import MetricLogger, log_json


class Trainer:
    def __init__(
        self,
        cfg: TrainConfig,
        *,
        train_records: Sequence[dict],
        val_records: Sequence[dict] | None = None,
        mesh: Any | None = None,
    ):
        self.cfg = cfg
        # sink first: every log_json below (device_report included) must
        # already flow through the --obs channel
        from distributed_llms_example_tpu.obs.sink import build_sink, install_sink

        install_sink(build_sink(getattr(cfg, "obs", "stdout"), cfg.output_dir))
        self.mesh = mesh if mesh is not None else build_mesh(cfg.mesh)
        log_json({"event": "device_report", **device_report()})

        self.tokenizer = get_tokenizer(cfg.tokenizer, cfg.model_ckpt)
        compute_dtype = parse_dtype(cfg.compute_dtype)
        self.loaded = load_model(
            cfg.model_ckpt, dtype=compute_dtype, remat=cfg.remat, remat_policy=cfg.remat_policy,
            moe_capacity_factor=cfg.moe_capacity_factor,
            attention_impl=cfg.attention_impl or None,
            fused_ce=cfg.fused_ce or None,
        )
        self.model, self.config = self.loaded.module, self.loaded.config

        if self.loaded.is_seq2seq:
            mk_ds = lambda recs: SummarizationDataset(  # noqa: E731
                recs,
                self.tokenizer,
                max_source_length=cfg.max_source_length,
                max_target_length=cfg.max_target_length,
                source_column=cfg.source_column,
                target_column=cfg.target_column,
            )
        else:
            # decoder-only: prompt+target concatenated, loss masked on prompt
            mk_ds = lambda recs: CausalLMDataset(  # noqa: E731
                recs,
                self.tokenizer,
                max_length=cfg.max_source_length,
                max_target_length=cfg.max_target_length,
                source_column=cfg.source_column,
                target_column=cfg.target_column,
            )
        self.train_ds = mk_ds(train_records)
        self.val_ds = mk_ds(val_records) if val_records else None

        # For causal LM, input and labels share one width: cap both at
        # max_source_length so the bucket widths agree.
        tgt_cap = cfg.max_target_length if self.loaded.is_seq2seq else cfg.max_source_length
        self._tgt_cap = tgt_cap  # the topology-change rebuild re-derives the plan
        self.batches = BatchIterator(
            self.train_ds,
            global_batch=cfg.batch_size,
            process_count=jax.process_count(),
            process_index=jax.process_index(),
            seed=cfg.shuffle_seed,
            bucket_multiple=cfg.pad_to_multiple,
            max_source_length=cfg.max_source_length,
            max_target_length=tgt_cap,
        )
        steps_per_epoch = self.batches.steps_per_epoch()
        if steps_per_epoch == 0:
            raise ValueError(
                f"dataset of {len(self.train_ds)} examples is smaller than one "
                f"global batch ({cfg.batch_size})"
            )
        self.total_steps = steps_per_epoch * cfg.num_epochs

        self.tx, self.schedule, self.optim_spec = make_optimizer_bundle(
            learning_rate=cfg.learning_rate,
            weight_decay=cfg.weight_decay,
            warmup_steps=cfg.warmup_steps,
            total_steps=self.total_steps,
            max_grad_norm=cfg.max_grad_norm,
        )

        params = self.loaded.params
        if params is None:
            params = jax.device_get(self.loaded.init_params(cfg.shuffle_seed))

        # Pipeline parallelism: stage>1 swaps in the family's GPipe adapter
        # — blocks stacked (leading layer dim sharded over ``stage``),
        # training + teacher-forced scoring only.
        self.pipelined = self.mesh.shape.get("stage", 1) > 1
        self._rules = None  # None → default FSDP/TP rules everywhere below
        if self.pipelined:
            from distributed_llms_example_tpu.parallel.pipeline import stack_for_family
            from distributed_llms_example_tpu.parallel.sharding import pipeline_rules

            adapter_kw = dict(
                dtype=compute_dtype,
                num_microbatches=cfg.pipeline_microbatches,
                remat=cfg.remat,
            )
            if cfg.pipeline_schedule in ("1f1b", "interleaved"):
                # the adapters re-validate at construction; checking the
                # composition table here too fails before the stacking work
                from distributed_llms_example_tpu.analysis.composition import (
                    validate_composition,
                )

                validate_composition(
                    family=self.loaded.family,
                    schedule=cfg.pipeline_schedule,
                    mesh_axes=dict(self.mesh.shape),
                    flags=("pipelined",),
                )
                adapter_kw["schedule"] = cfg.pipeline_schedule
                if cfg.pipeline_schedule == "interleaved":
                    adapter_kw["virtual_stages"] = cfg.pipeline_virtual_stages
            if self.loaded.family == "llama":
                from distributed_llms_example_tpu.models.llama import PipelinedLlama as Adapter
            elif self.loaded.family == "bart":
                from distributed_llms_example_tpu.models.bart import PipelinedBart as Adapter
            elif self.loaded.family == "t5":
                from distributed_llms_example_tpu.models.t5 import PipelinedT5 as Adapter
            else:
                raise ValueError(
                    f"pipeline parallelism (stage>1) does not support family "
                    f"{self.loaded.family!r}"
                )
            params = stack_for_family(self.loaded.family, params)
            if cfg.pipeline_schedule == "interleaved" and cfg.pipeline_virtual_stages > 1:
                # interleaved storage order: device s's stage shard holds
                # its v non-contiguous chunks contiguously (host-side
                # permutation, before sharding; checkpoints store this
                # layout — resume with the same schedule flags.  v == 1 is
                # the identity: standard layout, no permutation)
                from distributed_llms_example_tpu.parallel.interleave import (
                    interleave_tree,
                )

                params["stacked_blocks"] = interleave_tree(
                    params["stacked_blocks"],
                    self.mesh.shape["stage"],
                    cfg.pipeline_virtual_stages,
                )
            self.model = Adapter(self.config, self.mesh, **adapter_kw)
            self._rules = pipeline_rules()
            log_json({
                "event": "pipeline_enabled",
                "family": self.loaded.family,
                "stages": self.mesh.shape["stage"],
                "num_microbatches": self.model.num_microbatches,
                "schedule": getattr(self.model, "pipeline_schedule", "gpipe"),
            })

        params = shard_params(params, self.mesh, self._rules)
        # gradient-collective compression (--grad-compression int8,
        # ops/quant_collectives.py): per-worker partial grads tiled over
        # the replica axes, s8 wire, error-feedback tree in TrainState —
        # validate the batch regrouping divisibility against the actual
        # mesh before any compile, like the grad-accum check below
        self._grad_workers = 1
        if cfg.grad_compression == "int8":
            from distributed_llms_example_tpu.ops.quant_collectives import (
                GRAD_WORKER_AXES,
                worker_count,
            )

            self._grad_workers = worker_count(dict(self.mesh.shape))
            if self._grad_workers <= 1:
                raise ValueError(
                    f"--grad-compression int8 needs a replica axis > 1 "
                    f"(mesh axes {GRAD_WORKER_AXES} on "
                    f"{dict(self.mesh.shape)} give 1 worker group): with "
                    "no cross-replica leg there is nothing to compress — "
                    "every step would pay quantization noise and a "
                    "params-sized fp32 residual for zero wire savings"
                )
            # the stochastic-rounding bits are drawn over the worker-tiled
            # gradient shapes; without partitionable threefry the lowering
            # computes them through cross-device u32 collectives as large
            # as the gradient traffic the compression removes (measured)
            jax.config.update("jax_threefry_partitionable", True)
            denom = cfg.grad_accum_steps * self._grad_workers
            if cfg.batch_size % denom:
                raise ValueError(
                    f"--grad-compression int8 cuts each microbatch into "
                    f"{self._grad_workers} worker group(s) (mesh axes "
                    f"{GRAD_WORKER_AXES}): --batch-size {cfg.batch_size} "
                    f"must be divisible by grad-accum-steps x workers = "
                    f"{denom}"
                )
            log_json({
                "event": "grad_compression",
                "mode": cfg.grad_compression,
                "workers": self._grad_workers,
                "worker_axes": list(GRAD_WORKER_AXES),
            })
        self.state = create_train_state(params, self.tx)
        self.state_sh = state_shardings(self.state, self.mesh, self._rules)
        if cfg.grad_compression == "int8":
            # EF allocated DIRECTLY into the tiled layout (sharded at
            # birth): a default-device zeros tree before the device_put
            # would sit W x params x 4B whole on chip 0 at 7B scale
            from distributed_llms_example_tpu.ops.quant_collectives import (
                attach_error_feedback,
            )

            self.state, self.state_sh = attach_error_feedback(
                self.state, self.state_sh, self.mesh, self._grad_workers,
            )
        self.state = jax.tree.map(lambda x, s: jax.device_put(x, s), self.state, self.state_sh)

        # Sequence (context) parallelism needs every bucket width divisible
        # by the axis: widths are multiples of pad_to_multiple capped at the
        # max lengths, so checking those three covers all batch shapes.  A
        # non-divisible setup falls back to unsharded lengths (the model
        # then picks XLA attention per shape) instead of crashing in
        # device_put/jit dispatch.
        seq_axis = self.mesh.shape.get("sequence", 1)
        self.sequence_sharded = seq_axis > 1 and all(
            dim % seq_axis == 0
            for dim in (cfg.pad_to_multiple, cfg.max_source_length, tgt_cap)
        )
        if seq_axis > 1 and not self.sequence_sharded:
            if self.pipelined:
                # the stage×sequence pipeline hard-shards hidden over the
                # sequence axis (shard_map in_specs) — there is no graceful
                # unsharded fallback, so a non-divisible setup must fail at
                # startup, not at first dispatch
                raise ValueError(
                    f"pipeline stage×sequence needs pad_to_multiple="
                    f"{cfg.pad_to_multiple}, max_source_length="
                    f"{cfg.max_source_length} and target cap {tgt_cap} all "
                    f"divisible by the sequence axis ({seq_axis})"
                )
            log_json({
                "event": "sequence_sharding_disabled",
                "reason": f"pad_to_multiple={cfg.pad_to_multiple}/"
                          f"max_source_length={cfg.max_source_length}/"
                          f"target_cap={tgt_cap} not all divisible by sequence={seq_axis}",
            })

        # --fused-ce / forced-attention misconfigurations must fail HERE,
        # loudly, before any compile: the known-bad combos are rows in the
        # composition matrix (analysis/composition.py) — fused-ce on
        # seq2seq or tensor/stage/sequence meshes, ring on pipelined
        # seq2seq, forced xla/flash on a stage×sequence llama mesh.
        if cfg.attention_impl == "ring" and self.mesh.shape.get("sequence", 1) <= 1:
            # not a combo — ring simply has nothing to shard over
            raise ValueError(
                "--attention-impl ring requires a mesh with a sequence axis > 1 "
                f"(got {dict(self.mesh.shape)})"
            )
        from distributed_llms_example_tpu.analysis.composition import (
            config_flags,
            validate_composition,
        )

        validate_composition(
            family=self.loaded.family,
            schedule=cfg.pipeline_schedule if self.pipelined else None,
            mesh_axes=dict(self.mesh.shape),
            flags=config_flags(
                pipelined=self.pipelined,
                fused_ce=cfg.fused_ce,
                attention_impl=cfg.attention_impl,
                num_experts=int(getattr(self.config, "num_experts", 0) or 0),
                grad_accum_steps=cfg.grad_accum_steps,
                optim_impl=cfg.optim_impl,
                grad_compression=cfg.grad_compression,
            ),
        )

        # In-step gradient accumulation: batch_size stays the EFFECTIVE
        # optimizer batch (one iterator batch = one optimizer step, so the
        # epoch/resume contract is untouched); the compiled step cuts it
        # into N shard-local microbatches.  Validate the divisibility the
        # regrouping needs against the actual mesh, before any compile.
        if cfg.grad_accum_steps > 1:
            from distributed_llms_example_tpu.data.batching import microbatch_size

            batch_shards = 1
            for ax in ("data", "fsdp", "expert"):
                batch_shards *= self.mesh.shape.get(ax, 1)
            micro = microbatch_size(
                cfg.batch_size,
                cfg.grad_accum_steps,
                batch_shards=batch_shards,
                process_count=jax.process_count(),
            )
            log_json({
                "event": "grad_accum",
                "grad_accum_steps": cfg.grad_accum_steps,
                "effective_batch": cfg.batch_size,
                "microbatch": micro,
            })

        # attn_dropout_rate alone (e.g. an HF checkpoint with
        # attention_dropout > 0 but dropout 0, or a llama recipe enabling
        # probs dropout on the dropout-free architecture) must also thread
        # the rng — otherwise the configured dropout silently never fires
        self.use_dropout = (
            self.config.dropout_rate > 0.0
            or float(getattr(self.config, "attn_dropout_rate", 0.0) or 0.0) > 0.0
        )
        # dropout path (--dropout-impl): the process default the shared
        # helper (ops/fused_dropout.py) reads at trace time — "auto" =
        # fused Pallas kernel on TPU, XLA bernoulli elsewhere
        from distributed_llms_example_tpu.ops.fused_dropout import (
            set_default_impl,
        )

        set_default_impl(cfg.dropout_impl)
        # optimizer-apply path (--optim-impl): process default for the
        # fused Pallas clip+AdamW kernel (ops/fused_optim.py) — "auto" =
        # fused on TPU, optax chain elsewhere; the resolved value is
        # logged below so post-hoc analysis knows which path ran
        from distributed_llms_example_tpu.ops.fused_optim import (
            resolve_impl as resolve_optim_impl,
            set_default_impl as set_optim_impl,
        )

        set_optim_impl(cfg.optim_impl)
        # pipelined runs stay on the optax chain (make_train_step gates
        # the fused plan on the adapter; log the EFFECTIVE impl)
        self.optim_impl = (
            "xla" if self.pipelined else resolve_optim_impl(cfg.optim_impl)
        )
        log_json({"event": "optim_config", "optim_impl": self.optim_impl})
        # training health: the in-graph numerics ride the compiled step
        # itself (extra metrics entries, no extra syncs) when the
        # watchdog will consume them
        from distributed_llms_example_tpu.obs.health import health_enabled

        self.health_on = health_enabled(cfg)
        self._build_train_step()
        # deterministic fault injection (obs/chaos.py --chaos): the ONE
        # injection point for faulted numerics, checkpoint corruption,
        # transient data errors and signals; the legacy
        # ``_poison_nan_at_step`` test hook is a thin alias that arms a
        # nan_grad injection here
        from distributed_llms_example_tpu.obs.chaos import parse_chaos

        self.chaos = parse_chaos(cfg.chaos)

        ckpt_dir = os.path.join(cfg.output_dir, "checkpoints")
        self.checkpointer = Checkpointer(
            ckpt_dir,
            save_every_steps=cfg.checkpoint.save_every_steps,
            keep=cfg.checkpoint.keep,
            async_save=cfg.checkpoint.async_save,
        )
        # in-run rewind-and-retry recovery (train/recovery.py): the state
        # machine is always constructed (its quarantine check is a dict
        # lookup per batch); only --on-anomaly rewind ever drives it
        from distributed_llms_example_tpu.train.recovery import RecoveryController

        self.recovery = RecoveryController(max_rewinds=cfg.max_rewinds)
        self._save_ordinal = 0  # chaos ckpt_corrupt ticks on save ordinals
        # Stacked-block STORAGE ORDER is schedule-dependent (interleaved
        # packs each device's v non-contiguous chunks contiguously) but
        # invisible to array shapes — resuming a checkpoint under a
        # different layout would silently train a layer-permuted model.
        # Record the layout next to the checkpoints and hard-fail on
        # mismatch instead.
        # v == 1 is the IDENTITY permutation (interleave_order(L, S, 1) is
        # ascending), so only v > 1 is a distinct storage layout — and the
        # permutation is f(L, stages, v): the STAGE COUNT matters too (the
        # same v on a resized stage axis packs different chunks per shard),
        # so it is part of the guarded identity
        permuted = (
            self.pipelined
            and cfg.pipeline_schedule == "interleaved"
            and cfg.pipeline_virtual_stages > 1
        )
        self._ckpt_layout = {
            "interleaved": permuted,
            "virtual_stages": cfg.pipeline_virtual_stages if permuted else 1,
            "stages": self.mesh.shape.get("stage", 1) if permuted else 1,
        }
        # the same identity ALSO rides inside the checkpoint payload as an
        # array leaf (ADVICE r4: the sidecar can be separated from the
        # arrays — a copy that drops the small JSON silently yields a
        # layer-permuted model, which nothing else can catch since shapes
        # are permutation-invariant).  Saved with the state, checked on
        # restore; the sidecar stays for pre-restore refusal + humans.
        self._layout_leaf = np.asarray(
            [
                int(permuted),
                self._ckpt_layout["virtual_stages"],
                self._ckpt_layout["stages"],
            ],
            np.int32,
        )
        # the TOPOLOGY identity rides the payload the same way: mesh axis
        # sizes + process count + EF worker count (io/checkpoint.py
        # mesh_layout_array) — what the resharding restore's fail-fast
        # check and the spec-lint reshard pass judge a live mesh against
        self._mesh_layout_leaf = mesh_layout_array(
            dict(self.mesh.shape),
            jax.process_count(),
            self._grad_workers if cfg.grad_compression == "int8" else 0,
        )
        # THE single storage→true-order map (None: storage is already in
        # layer order).  Every consumer — eval unstack, HF export, the
        # val-loss un-permute — reads this one attribute, so the layout
        # identity cannot drift between them.
        self._storage_row_order = None
        if permuted:
            from distributed_llms_example_tpu.parallel.interleave import (
                uninterleave_order,
            )

            self._storage_row_order = uninterleave_order(
                self.config.num_hidden_layers,
                self.mesh.shape["stage"],
                cfg.pipeline_virtual_stages,
            )
        self._ckpt_layout_path = os.path.join(ckpt_dir, "stacked_layout.json")
        self.start_step = 0
        if self.checkpointer.latest_step() is not None:
            stored = {"interleaved": False, "virtual_stages": 1, "stages": 1}
            if os.path.exists(self._ckpt_layout_path):
                with open(self._ckpt_layout_path) as f:
                    stored = json.load(f)
            if stored != self._ckpt_layout:
                # refuse MIXED-layout dirs even with resume=False: this
                # run's saves would not erase the old run's higher steps,
                # and rewriting the sidecar would mislabel them for a
                # later resume (restore_latest takes the HIGHEST step)
                raise ValueError(
                    f"checkpoint dir {ckpt_dir} stores stacked blocks in "
                    f"layout {stored}, but this run uses "
                    f"{self._ckpt_layout} — resume with the same "
                    "--pipeline-schedule/--pipeline-virtual-stages flags "
                    "AND stage-axis size, or point --output-dir at a fresh "
                    "directory (array shapes match under any row "
                    "permutation, so restoring across layouts would "
                    "silently permute the model's layers)"
                )
        # per-step resharding plans, populated by _restore_target_for as
        # restore_latest's walk consults it (cleared before every walk)
        self._reshard_plan: dict[int, dict] = {}
        # test hook: the topology-change path's next mesh (a MeshSpec /
        # MeshConfig); None = re-resolve the configured shape against the
        # surviving device count (core/mesh.py elastic_mesh_spec)
        self._next_mesh_override = None
        if cfg.checkpoint.resume and self.checkpointer.latest_step() is not None:
            # THE RESHARDING RESTORE (ISSUE 14): the abstract target is
            # built PER CANDIDATE STEP from the saved payload's orbax
            # metadata — its STRUCTURE (legacy bare-TrainState vs layout
            # payload, error-feedback tree present or not, the EF worker
            # dim as saved) matches the disk, its SHARDINGS come from the
            # LIVE mesh — so a checkpoint written under a different
            # data×fsdp factorization or process count restores directly
            # onto this mesh.  A mixed flag-flip dir needs no candidate
            # ladder anymore: every step gets the target its own payload
            # shape requires, so the newest verified step always wins.
            t0 = time.perf_counter()
            self._reshard_plan = {}
            restored = self.checkpointer.restore_latest(
                None, target_for=self._restore_target_for
            )
            if restored is None:
                # checkpoints EXIST but none passed verification:
                # training silently from step 0 would let this run's
                # retention garbage-collect the (possibly salvageable)
                # corrupt steps — refuse loudly instead
                self._refuse_unverifiable_resume(ckpt_dir)
            payload, self.start_step = restored
            self.state, plan = self._finish_restore(payload, self.start_step)
            log_json({
                "event": "resumed", "step": self.start_step,
                **({"legacy_payload": True} if plan["legacy"] else {}),
            })
            if plan["resharded"]:
                self._emit_reshard_restore(
                    plan, self.start_step,
                    reshard_wall_s=round(time.perf_counter() - t0, 4),
                )
        # cross-run recovery state: the (epoch, pos) cursor and the
        # quarantine set ride a sidecar next to the restored step —
        # after a quarantine skip the cursor drifts from step %
        # steps_per_epoch, so the arithmetic fallback would re-train one
        # batch and shift the rest of the epoch
        self._resume_cursor: tuple[int, int] | None = None
        if self.start_step:
            side = self._load_recovery_sidecar(self.start_step)
            if side is not None:
                self._resume_cursor = (int(side["epoch"]), int(side["pos"]))
                for e, s, rec in side.get("quarantined", []):
                    self.recovery.quarantined[(int(e), int(s))] = rec
                log_json({
                    "event": "recovery_cursor_restored",
                    "step": self.start_step,
                    "epoch": self._resume_cursor[0],
                    "pos": self._resume_cursor[1],
                    "quarantined": len(self.recovery.quarantined),
                })
        # Written at init, AFTER the mismatch guard: a mixed dir has
        # already been refused above, and deferring to the first save
        # would leave a crash window (preemption save lands, SIGKILL
        # before the sidecar write → interleaved checkpoints unlabeled,
        # and a later same-flags resume would be refused as a "mismatch").
        # Only written when storage is actually permuted — the guard's
        # missing-sidecar default IS the standard layout, so a sidecar for
        # it would add nothing (and litter every plain run's output dir)
        if permuted and jax.process_index() == 0:  # pod-agreed: p0-only LOCAL sidecar write; no collectives in branch
            os.makedirs(ckpt_dir, exist_ok=True)
            with open(self._ckpt_layout_path, "w") as f:
                json.dump(self._ckpt_layout, f)

        # Generation-based ROUGE under stage>1 unstacks each layer onto the
        # FSDP/TP rule shardings — but on a PURE-stage mesh (fsdp×tensor==1,
        # the canonical too-big-for-one-chip config) those rules resolve to
        # fully replicated, i.e. a whole-model copy per device: exactly the
        # cliff the pipeline exists to avoid.  Auto-skip ROUGE there (the
        # stage-sharded teacher-forced val_loss is always reported); an
        # explicit --no-pipeline-eval-rouge skips it on any mesh.
        self._pipeline_rouge_ok = self.cfg.pipeline_eval_rouge and (
            self.mesh.shape.get("fsdp", 1) * self.mesh.shape.get("tensor", 1) > 1
        )
        if self.pipelined and self.cfg.pipeline_eval_rouge and not self._pipeline_rouge_ok:
            log_json({
                "event": "pipeline_rouge_disabled",
                "reason": "fsdp*tensor == 1: unstacked eval params would be "
                          "fully replicated (one whole-model copy per device); "
                          "reporting stage-sharded val_loss only",
            })
        # Eval always uses the STANDARD (per-layer) module: under pipeline
        # parallelism evaluate() unstacks the stacked blocks first (layer
        # params then live replicated across stage groups for the eval pass
        # — generation needs the KV-cache path the pipeline adapter lacks).
        self.evaluator = (
            Evaluator(
                self.loaded.module,
                self.config,
                self.tokenizer,
                self.mesh,
                num_beams=cfg.num_beams,
                max_new_tokens=cfg.eval_max_new_tokens,
                is_seq2seq=self.loaded.is_seq2seq,
            )
            if self.val_ds
            else None
        )
        # dropout stream: --prng-impl auto resolves to the TPU hardware
        # RNG on TPU backends (threefry's counter math can cost ~20% of a
        # dropout-on step) and bit-reproducible threefry elsewhere
        self.set_prng_impl(cfg.prng_impl)
        if self.use_dropout:
            from distributed_llms_example_tpu.ops.fused_dropout import (
                resolve_impl,
            )

            log_json({
                "event": "rng_config",
                "prng_impl": self.prng_impl,
                # RESOLVED value ("fused"/"xla", never "auto") — the whole
                # point of the event is telling post-hoc which path ran
                "dropout_impl": resolve_impl(cfg.dropout_impl),
            })
        # telemetry bundle (obs/): span recorder, profiler controller,
        # heartbeat, and — under --obs jsonl / --obs-gauges on — the
        # startup AOT gauge compile (MFU FLOPs numerator + the static
        # collective-traffic account).  stage>1 skips the gauge compile:
        # the shared recipe, like the IR lint, does not cover pipelined
        # shard_map programs yet (ROADMAP open item).
        from distributed_llms_example_tpu.obs import TrainerObs

        self.obs = TrainerObs(cfg, start_step=self.start_step, manage_sink=False)
        if not self.pipelined:
            self.obs.startup_gauges(self.mesh, tgt_cap=tgt_cap)

    # ------------------------------------------------------------------

    def _build_train_step(self) -> None:
        """(Re)build the jitted train step against ``self.mesh`` — the
        step closes over the mesh, so the topology-change path calls
        this again after swapping it.  Also resets the lazily-built
        optimizer-apply probe (same closure problem)."""
        cfg = self.cfg
        build = make_train_step(
            self.model,
            self.config,
            self.tx,
            self.schedule,
            self.mesh,
            grad_accum_steps=cfg.grad_accum_steps,
            label_smoothing=cfg.label_smoothing,
            with_dropout=self.use_dropout,
            is_seq2seq=self.loaded.is_seq2seq,
            sequence_sharded=self.sequence_sharded,
            rules=self._rules,
            health=self.health_on,
            optim_spec=self.optim_spec,
            optim_impl=cfg.optim_impl,
            grad_compression=cfg.grad_compression,
        )
        self.train_step, _ = build(self.state)
        # lazily-built jitted optimizer-apply probe (budget layer): the
        # cadenced optimizer_apply_ms sample — see _optimizer_probe_output
        self._opt_probe = None

    def set_prng_impl(self, impl: str) -> None:
        """(Re)seed the dropout stream with the given PRNG implementation
        ("auto" / "threefry" / "rbg") — the ONE home for the key wiring
        AND the auto resolution (rbg on TPU backends, threefry elsewhere),
        used by __init__ and by bench A/B passes, so the two cannot drift.
        The resolved impl lands in ``self.prng_impl`` so bench/obs can
        stamp it into their records."""
        if impl == "auto":
            impl = "rbg" if jax.default_backend() == "tpu" else "threefry"
        self.prng_impl = impl
        self._rng = (
            jax.random.PRNGKey(self.cfg.shuffle_seed)
            if impl == "threefry"
            else jax.random.key(self.cfg.shuffle_seed, impl=impl)
        )

    def _refuse_unverifiable_resume(self, ckpt_dir: str) -> None:
        raise ValueError(
            f"resume: checkpoints exist under {ckpt_dir} "
            f"(steps {self.checkpointer.all_steps()}) but none passed "
            "integrity verification — see the ckpt_verify_failed events "
            "for per-file detail; inspect/restore the step dirs against "
            "their integrity-<step>.json manifests, or pass --no-resume "
            "to train from scratch (which will eventually retention-"
            "delete the corrupt steps)"
        )

    @property
    def _poison_nan_at_step(self) -> int | None:
        """Legacy test hook, kept as a thin alias over the chaos harness:
        reading returns the first armed-but-unfired nan_grad step (None =
        never), assigning arms a ``nan_grad@step`` injection."""
        armed = self.chaos.armed_at("nan_grad")
        return armed[0] if armed else None

    @_poison_nan_at_step.setter
    def _poison_nan_at_step(self, step: int | None) -> None:
        # assignment REPLACES the armed injection, exactly like the plain
        # attribute it used to be: None disarms, a step re-arms
        self.chaos.disarm("nan_grad")
        if step is not None:
            self.chaos.arm("nan_grad", int(step))

    def _save_checkpoint(
        self,
        step: int,
        *,
        epoch: int | None = None,
        pos: int | None = None,
        force: bool = False,
    ) -> bool:
        """THE checkpoint save path — every save (cadence, rewind anchor,
        anomaly, preemption, final) goes through here so the recovery
        snapshot (RNG + data cursor, needed for a bit-exact in-process
        rewind) and the chaos ``ckpt_corrupt`` ordinal counter cannot
        miss one."""
        saved = self.checkpointer.save(step, self._with_layout(self.state), force=force)
        if not saved:
            return False
        self._save_ordinal += 1
        if epoch is not None and pos is not None:
            self.recovery.note_save(step, rng=self._rng, epoch=epoch, pos=pos)
            self._write_recovery_sidecar(step, epoch, pos)
        if self.chaos.take("ckpt_corrupt", self._save_ordinal):
            # finalize the data AND its checksum manifest first: the
            # corruption must be caught by integrity verification, not by
            # an unluckily torn write orbax happens to notice
            self.checkpointer.wait()
            if jax.process_index() == 0:  # pod-agreed: chaos injection corrupts p0's local file only; no collectives in branch
                from distributed_llms_example_tpu.obs.chaos import corrupt_checkpoint

                corrupt_checkpoint(self.checkpointer.step_dir(step))
        return True

    def _recovery_sidecar_path(self, step: int) -> str:
        from distributed_llms_example_tpu.io.checkpoint import RECOVERY_PREFIX

        return os.path.join(
            self.checkpointer.directory, f"{RECOVERY_PREFIX}{int(step)}.json"
        )

    def _write_recovery_sidecar(self, step: int, epoch: int, pos: int) -> None:
        """Persist the host-side recovery state orbax's payload cannot
        hold — the (epoch, pos) data cursor and the quarantine set — next
        to the checkpoint (atomic, p0).  Quarantine skips make the cursor
        drift from ``step % steps_per_epoch``, so a CROSS-RUN resume that
        reconstructed it arithmetically would re-train one batch and
        shift the rest of the epoch; with the sidecar, resume is exact
        and the quarantine survives the restart (the dropout-RNG snapshot
        stays in-memory only: bit-exact replay is a same-process
        property).  GC'd with the step by io/checkpoint.py."""
        if jax.process_index() != 0:  # pod-agreed: p0-only LOCAL sidecar write; no collectives after the early return
            return
        payload = {
            "step": int(step),
            "epoch": int(epoch),
            "pos": int(pos),
            "quarantined": [
                [e, s, rec] for (e, s), rec in self.recovery.quarantined.items()
            ],
            # the saving topology, readable WITHOUT a restore: the
            # resharding path's fail-fast pre-check and obs.report's
            # old→new mesh rows both read it from here
            "mesh_layout": self._live_mesh_layout(),
        }
        path = self._recovery_sidecar_path(step)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            # best-effort, like the manifest write: resume falls back to
            # the arithmetic cursor when the sidecar is missing
            log_json({
                "event": "recovery_sidecar_write_failed",
                "step": int(step),
                "error": str(e)[:200],
            })

    def _load_recovery_sidecar(self, step: int) -> dict | None:
        try:
            with open(self._recovery_sidecar_path(step)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _with_data_retries(self, batches: Any):
        """Wrap the epoch's batch stream with the chaos ``data_error``
        injection point and its retry (capped backoff, ``data_retry``
        events).  The injected error is raised BEFORE touching the
        iterator, so the retry cleanly re-fetches.  A real error from
        the iterator propagates immediately: a generator or Prefetcher
        that raised is dead (the producer latches the error), so
        retrying could only emit phantom ``data_retry`` events and sleep
        before failing with the same exception — transient FILE errors
        are retried where the read is actually restartable, inside
        ``data/dataset.py``."""
        class _Injected(OSError):
            pass  # raised BEFORE next(it): the iterator is untouched

        it = iter(batches)
        while True:
            attempt, delay = 0, 0.05
            while True:
                try:
                    if self.chaos.take("data_error", self._last_step + 1):
                        raise _Injected("chaos: injected transient data-read error")
                    batch = next(it)
                    break
                except StopIteration:
                    return
                except _Injected as e:
                    attempt += 1
                    log_json({
                        "event": "data_retry",
                        "step": self._last_step + 1,
                        "attempt": attempt,
                        "backoff_s": round(delay, 3),
                        "error": str(e)[:200],
                    })
                    delay = sleep_backoff(delay, cap_s=2.0)
            yield batch

    def _saved_ef_workers(self, meta: Any) -> int:
        """The error-feedback worker count a payload was SAVED with, read
        from its orbax metadata (0 = no EF tree in the payload).  The
        worker dim is a function of the saving mesh's replica axes, so
        this is the one state shape a topology change moves."""
        state_meta = meta.get("state", meta) if isinstance(meta, dict) else meta
        ef_meta = (
            state_meta.get("ef") if isinstance(state_meta, dict)
            else getattr(state_meta, "ef", None)
        )
        shapes = [
            tuple(x.shape)
            for x in jax.tree.leaves(ef_meta)
            if hasattr(x, "shape") and len(tuple(x.shape))
        ]
        return int(shapes[0][0]) if shapes else 0

    def _ef_restore_target(self, abstract, saved_workers: int):
        """The EF half of the per-step restore target — PR 12's flag-flip
        ladder generalized to ARBITRARY saved worker counts (ISSUE 14),
        shared by resume, anomaly-rewind and the topology path so none
        can drift.  Returns ``(target, ef_mode)``:

        - saved 0, live on   → ef-less target, then ZERO-FILL ("fill")
        - saved W, live off  → restore at W, then DROP ("drop")
        - saved W == live W  → unchanged ("")
        - saved W != live W  → restore at the SAVED W (worker dim laid
          over the live replica axes when divisible, replicated
          otherwise), then RE-TILE when the live count divides the saved
          one ("retile": merged groups' residuals sum, preserving the
          total deferred error) or ZERO-FILL otherwise ("zero")."""
        live_ef = getattr(self.state, "ef", None) is not None
        live_workers = self._grad_workers if live_ef else 0
        if saved_workers == 0:
            return (abstract.replace(ef=None), "fill") if live_ef else (abstract, "")
        from distributed_llms_example_tpu.parallel.sharding import divisible_spec
        from distributed_llms_example_tpu.ops.quant_collectives import tiled_spec
        from jax.sharding import NamedSharding

        def one(p, sh):
            shape = (int(saved_workers),) + tuple(p.shape)
            spec = divisible_spec(tiled_spec(sh.spec), shape, self.mesh)
            return jax.ShapeDtypeStruct(
                shape, np.float32, sharding=NamedSharding(self.mesh, spec)
            )

        param_sh = (
            self.state_sh.params if hasattr(self.state_sh, "params") else self.state_sh
        )
        target = abstract.replace(
            ef=jax.tree.map(one, abstract.params, param_sh)
        )
        if not live_ef:
            return target, "drop"
        if saved_workers == live_workers:
            # same worker count: the payload's EF tree restores directly
            # (the target must still CARRY it — `abstract` is ef-less)
            return target, ""
        return target, ("retile" if saved_workers % live_workers == 0 else "zero")

    def _apply_ef_mode(self, state, ef_mode: str, step: int, saved_workers: int = 0):
        """Finish a flag-flip or reshard restore: zero-fill the EF tree
        (sharded at birth), drop the restored residual, or re-tile it
        onto the new worker count — with the event log."""
        if ef_mode == "fill":
            from distributed_llms_example_tpu.ops.quant_collectives import (
                sharded_zero_error_feedback,
            )

            state = state.replace(ef=sharded_zero_error_feedback(
                state.params, self._grad_workers, self.state_sh.ef,
            ))
            log_json({
                "event": "grad_compression_ef_zero_filled",
                "step": int(step),
                "reason": "checkpoint carries no error-feedback tree "
                          "(written before --grad-compression, or with "
                          "it off); resuming with a zero residual",
            })
        elif ef_mode == "drop":
            state = state.replace(ef=None)
            log_json({
                "event": "grad_compression_ef_dropped",
                "step": int(step),
                "reason": "checkpoint was written under --grad-compression "
                          "int8 but this run has it off; the error-feedback "
                          "residual is dropped (its deferred quantization "
                          "error is lost once — the uncompressed run does "
                          "not need it)",
            })
        elif ef_mode == "retile":
            from distributed_llms_example_tpu.ops.quant_collectives import (
                retile_error_feedback,
            )

            state = state.replace(ef=retile_error_feedback(
                state.ef, self._grad_workers, self.state_sh.ef,
            ))
            log_json({
                "event": "grad_compression_ef_reshaped",
                "step": int(step),
                "mode": "retile",
                "from_workers": int(saved_workers),
                "to_workers": int(self._grad_workers),
                "reason": "topology change: the new worker count divides "
                          "the saved one, so each new worker group absorbs "
                          "the summed residuals of the groups it merges "
                          "(total deferred quantization error preserved)",
            })
        elif ef_mode == "zero":
            from distributed_llms_example_tpu.ops.quant_collectives import (
                sharded_zero_error_feedback,
            )

            state = state.replace(ef=sharded_zero_error_feedback(
                state.params, self._grad_workers, self.state_sh.ef,
            ))
            log_json({
                "event": "grad_compression_ef_reshaped",
                "step": int(step),
                "mode": "zero_fill",
                "from_workers": int(saved_workers),
                "to_workers": int(self._grad_workers),
                "reason": "topology change: the new worker count does not "
                          "divide the saved one — no residual regrouping "
                          "preserves the per-worker error, so it restarts "
                          "from zero (step-0 semantics, one residual's "
                          "worth of deferred error dropped)",
            })
        return state

    def _live_mesh_layout(self) -> dict:
        return {
            "axes": {a: int(s) for a, s in self.mesh.shape.items()},
            "processes": int(jax.process_count()),
            "ef_workers": (
                int(self._grad_workers)
                if getattr(self.state, "ef", None) is not None else 0
            ),
        }

    def _check_reshardable(self, saved_layout: dict, step: int) -> None:
        """Fail FAST, with both factorizations named, when a recorded
        topology cannot map onto the live mesh (``analysis/spec_lint.py
        lint_reshard_layout`` is the shared judge) — instead of the
        opaque orbax structure error the walk-back used to surface."""
        live = self._live_mesh_layout()
        axes = saved_layout.get("axes", {})
        if axes == live["axes"] and saved_layout.get("processes") == live["processes"]:
            return  # same topology: nothing to judge
        from distributed_llms_example_tpu.analysis.spec_lint import (
            lint_reshard_layout,
        )

        abstract_params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self.state.params
        )
        errors = [
            f for f in lint_reshard_layout(
                saved_layout, dict(self.mesh.shape), abstract_params,
                rules=self._rules,
            )
            if f.severity == "error"
        ]
        if errors:
            raise ReshardError(
                f"checkpoint step {step} was saved under "
                f"{describe_factorization(saved_layout)} and cannot restore "
                f"onto the live {describe_factorization(live)}: "
                + "; ".join(f.message for f in errors[:3])
            )

    def _restore_target_for(self, step: int):
        """Per-step abstract restore target for the resharding path:
        structure from the SAVED payload's orbax metadata, shardings from
        the LIVE mesh.  Records the step's plan (legacy?, ef mode, saved
        layout) in ``self._reshard_plan`` for ``_finish_restore``."""
        step = int(step)
        abstract = abstract_like(
            self.state.replace(ef=None), self.state_sh.replace(ef=None)
        )
        meta = self.checkpointer.payload_metadata(step)
        side = self._load_recovery_sidecar(step)
        saved_layout = (side or {}).get("mesh_layout")
        if saved_layout:
            # the sidecar names the saving topology WITHOUT a restore —
            # the fail-fast seam (sidecar-less dirs are judged after the
            # restore lands, from the payload's own mesh_layout leaf)
            self._check_reshardable(saved_layout, step)
        legacy = False
        structure_unknown = False
        has_mesh_leaf = False
        if isinstance(meta, dict) and "state" in meta:
            has_mesh_leaf = "mesh_layout" in meta
            saved_workers = self._saved_ef_workers(meta)
        elif meta is not None:
            # bare-TrainState payload (pre-layout-leaf checkpoints)
            legacy = True
            saved_workers = self._saved_ef_workers(meta)
        else:
            # no metadata (foreign/ancient dir): the structure cannot be
            # classified — assume the live EF shape and try BOTH payload
            # structures (layout payload first, legacy bare state as the
            # fallback, exactly the pre-reshard candidate ladder's order)
            structure_unknown = True
            saved_workers = (
                self._grad_workers
                if getattr(self.state, "ef", None) is not None else 0
            )
        target, ef_mode = self._ef_restore_target(abstract, saved_workers)
        resharded = bool(saved_layout) and (
            saved_layout.get("axes") != self._live_mesh_layout()["axes"]
            or saved_layout.get("processes") != jax.process_count()
        )
        self._reshard_plan[step] = {
            "legacy": legacy,
            "structure_unknown": structure_unknown,
            "ef_mode": ef_mode,
            "saved_workers": int(saved_workers),
            "saved_layout": saved_layout,
            "resharded": resharded or ef_mode in ("retile", "zero"),
        }
        if legacy:
            return target
        payload: dict[str, Any] = {
            "state": target,
            "stacked_layout": jax.ShapeDtypeStruct(
                self._layout_leaf.shape, self._layout_leaf.dtype
            ),
        }
        if has_mesh_leaf:
            payload["mesh_layout"] = jax.ShapeDtypeStruct(
                self._mesh_layout_leaf.shape, self._mesh_layout_leaf.dtype
            )
        if not structure_unknown:
            return payload
        # the pre-reshard candidate ladder's order for an unclassifiable
        # step: layout payload first (mesh-leaf-carrying — the modern
        # save format — then the pre-mesh-leaf shape, live EF structure
        # then the --grad-compression flag-flip shape), legacy bare
        # state last — _finish_restore classifies structure AND EF
        # transition from what actually landed
        from distributed_llms_example_tpu.ops.quant_collectives import (
            worker_count,
        )

        live_ef = getattr(self.state, "ef", None) is not None
        flip, _ = self._ef_restore_target(
            abstract, 0 if live_ef else worker_count(dict(self.mesh.shape))
        )
        flip_payload = dict(payload)
        flip_payload["state"] = flip

        def with_mesh_leaf(p: dict) -> dict:
            q = dict(p)
            q["mesh_layout"] = jax.ShapeDtypeStruct(
                self._mesh_layout_leaf.shape, self._mesh_layout_leaf.dtype
            )
            return q

        return [
            with_mesh_leaf(payload), payload,
            with_mesh_leaf(flip_payload), flip_payload,
            target, flip,
        ]

    def _finish_restore(self, payload: Any, step: int) -> tuple[Any, dict]:
        """Unwrap a restored payload per its recorded plan: layout-leaf
        guard, mesh-layout cross-check (the sidecar-less fail path), EF
        fill/drop/retile/zero-fill.  Returns ``(state, plan)``."""
        plan = self._reshard_plan.pop(int(step), None) or {
            "legacy": not isinstance(payload, dict),
            "ef_mode": "", "saved_workers": 0,
            "saved_layout": None, "resharded": False,
        }
        if plan.get("structure_unknown"):
            # a metadata-less step offered several candidate structures
            # — classify the payload shape AND the EF transition by what
            # actually restored
            plan["legacy"] = not isinstance(payload, dict)
            inner = payload if plan["legacy"] else payload["state"]
            restored_ef = getattr(inner, "ef", None)
            live_ef = self.cfg.grad_compression == "int8"
            if live_ef and restored_ef is None:
                plan["ef_mode"] = "fill"
            elif not live_ef and restored_ef is not None:
                plan["ef_mode"] = "drop"
                plan["saved_workers"] = int(
                    jax.tree.leaves(restored_ef)[0].shape[0]
                )
            else:
                plan["ef_mode"] = ""
        if plan["legacy"]:
            state = payload
        else:
            stored_leaf = np.asarray(jax.device_get(payload["stacked_layout"]))
            if not np.array_equal(stored_leaf, self._layout_leaf):
                raise ValueError(
                    f"checkpoint payload records stacked-block layout "
                    f"[interleaved, virtual_stages, stages] = "
                    f"{stored_leaf.tolist()}, but this run uses "
                    f"{self._layout_leaf.tolist()} — resume with the same "
                    "--pipeline-schedule/--pipeline-virtual-stages flags "
                    "and stage-axis size (restoring across layouts would "
                    "silently permute the model's layers)"
                )
            if "mesh_layout" in payload and plan["saved_layout"] is None:
                # no sidecar named the topology pre-restore: the payload
                # leaf is authoritative — judge it now (still a NAMED
                # error, just after the arrays landed)
                saved = parse_mesh_layout(jax.device_get(payload["mesh_layout"]))
                self._check_reshardable(saved, step)
                plan["saved_layout"] = saved
                plan["resharded"] = plan["resharded"] or (
                    saved["axes"] != self._live_mesh_layout()["axes"]
                    or saved["processes"] != jax.process_count()
                )
            state = payload["state"]
        state = self._apply_ef_mode(
            state, plan["ef_mode"], step, saved_workers=plan["saved_workers"]
        )
        return state, plan

    def _emit_reshard_restore(self, plan: dict, step: int, **extra: Any) -> None:
        """The ``reshard_restore`` obs event: a checkpoint crossed a
        topology boundary on its way back in (old → new factorization,
        EF handling, wall clock) — what ``obs.report``'s recovery
        timeline and the MTTR account consume."""
        from distributed_llms_example_tpu.obs import sink as sink_mod

        saved = plan.get("saved_layout") or {}
        sink_mod.emit({
            "event": "reshard_restore",
            "step": int(step),
            "old_mesh": saved.get("axes"),
            "old_processes": saved.get("processes"),
            "new_mesh": {a: int(s) for a, s in self.mesh.shape.items()},
            "new_processes": int(jax.process_count()),
            "ef_mode": plan.get("ef_mode") or "none",
            **extra,
        }, local=True)

    def _with_layout(self, state: Any, abstract: bool = False) -> dict:
        """Checkpoint payload: the TrainState plus the stacked-block
        layout identity AND the mesh topology (axis sizes, process
        count, EF workers) as ARRAY leaves, so neither identity can be
        separated from the arrays it describes (a sidecar JSON can)."""
        if abstract:
            return {
                "state": state,
                "stacked_layout": jax.ShapeDtypeStruct(
                    self._layout_leaf.shape, self._layout_leaf.dtype
                ),
                "mesh_layout": jax.ShapeDtypeStruct(
                    self._mesh_layout_leaf.shape, self._mesh_layout_leaf.dtype
                ),
            }
        return {
            "state": state,
            "stacked_layout": self._layout_leaf,
            "mesh_layout": self._mesh_layout_leaf,
        }

    def evaluate(
        self, epoch: int | None = None, step: int | None = None
    ) -> dict[str, float]:
        if self.val_ds is None:
            return {}
        scores: dict[str, float] = {}
        if self.pipelined:
            # teacher-forced val loss through the PIPELINED module: params
            # stay stage-sharded, nothing is unstacked — the eval path that
            # works for models too big to replicate (VERDICT r2 weak #4)
            scores["val_loss"] = self._pipelined_val_loss()
        run_rouge = self.evaluator is not None and (
            not self.pipelined or self._pipeline_rouge_ok
        )
        if run_rouge:
            eval_params = self.state.params
            if self.pipelined:
                from distributed_llms_example_tpu.parallel.pipeline import (
                    unstack_for_family_resharded,
                )

                # unstack to the standard per-layer layout with each layer
                # device_put onto the default FSDP/TP shardings AS it is
                # unstacked (at most one replicated layer live at a time) —
                # generation then needs params/(fsdp·tensor) per device,
                # the normal FSDP story instead of a whole-model cliff
                eval_params = unstack_for_family_resharded(
                    self.loaded.family, eval_params, self.mesh,
                    row_order=self._storage_row_order,
                )
            eval_batch = self.cfg.eval_batch_size or self.cfg.batch_size
            pc = jax.process_count()
            eval_batch = min(eval_batch, max(pc, len(self.val_ds)))
            # host_batch_slices requires divisibility by process count; a
            # tiny val set (e.g. 3 examples, 2 processes) would otherwise
            # crash mid-eval after the clamp above
            eval_batch = max(pc, eval_batch - eval_batch % pc)
            scores.update(self.evaluator.run(
                eval_params,
                self.val_ds,
                global_batch=eval_batch,
                bucket_multiple=self.cfg.pad_to_multiple,
                max_source_length=self.cfg.max_source_length,
            ))
        if epoch is not None:
            scores["epoch"] = float(epoch)
        # eval events carry the global step under the SAME field name as
        # the train cadence lines, so report-side timeline joins need no
        # special-casing (val_loss lands at the step that produced it)
        event = {"event": "eval", **({"step": step} if step is not None else {})}
        log_json({**event, **scores})
        return scores

    def _pipelined_val_loss(self) -> float:
        """Mean teacher-forced CE over the val set, computed with the
        stage-sharded pipelined module (no unstacking; peak memory is the
        training footprint, not a replicated copy of the model)."""
        from distributed_llms_example_tpu.train.step import make_loss_fn

        interleaved_storage = self._storage_row_order is not None
        if not hasattr(self, "_val_loss_fn"):
            from distributed_llms_example_tpu.parallel.activation import activation_mesh
            from distributed_llms_example_tpu.parallel.sharding import batch_sharding

            # same objective as training (incl. label smoothing) so the
            # train-vs-val gap measures generalization, not a formula skew.
            # Under interleaved STORAGE, score through a gpipe-VIEW adapter
            # fed a true-order tree instead (built once per evaluate below)
            # — the interleaved adapter's apply() would re-gather the whole
            # stacked tree on every batch
            model_for_val = self.model
            if interleaved_storage:
                from distributed_llms_example_tpu.models.llama import PipelinedLlama

                model_for_val = PipelinedLlama(
                    self.config, self.mesh, dtype=self.model.dtype,
                    num_microbatches=self.model.num_microbatches,
                    remat=self.cfg.remat, schedule="gpipe",
                )
            loss_sums = make_loss_fn(
                model_for_val, self.config, self.cfg.label_smoothing,
                is_seq2seq=self.loaded.is_seq2seq,
            )
            bsh = batch_sharding(self.mesh)
            jitted = jax.jit(
                lambda p, b: loss_sums(p, b),
                in_shardings=(
                    self.state_sh.params,
                    {"input_ids": bsh, "attention_mask": bsh, "labels": bsh},
                ),
            )

            def run(p, b):
                with activation_mesh(self.mesh):
                    return jitted(p, b)

            self._val_loss_fn = run
        val_params = self.state.params
        if interleaved_storage:
            # ONE stacked-tree un-permute per evaluate, not per batch —
            # and JITTED with sharded outputs, so the partitioner emits a
            # cross-shard row permutation instead of an eager per-leaf
            # take() that would gather the whole stack replicated (the
            # memory cliff this stage-sharded val path exists to avoid)
            if not hasattr(self, "_val_unpermute"):
                import jax.numpy as _jnp

                inv = self._storage_row_order  # THE storage→true-order map
                self._val_unpermute = jax.jit(
                    lambda t: jax.tree.map(lambda a: _jnp.take(a, inv, axis=0), t),
                    out_shardings=self.state_sh.params["stacked_blocks"],
                )
            val_params = dict(val_params)
            val_params["stacked_blocks"] = self._val_unpermute(
                val_params["stacked_blocks"]
            )

        # eval batch rounded to the pipeline quantum: batch shards ×
        # microbatches (and the host slice divisibility)
        shards = 1
        for ax in ("data", "fsdp", "expert"):
            shards *= self.mesh.shape.get(ax, 1)
        quantum = shards * getattr(self.model, "num_microbatches", 1)
        if quantum % jax.process_count():  # pod-agreed: arithmetic on the pod-uniform process count
            quantum *= jax.process_count()
        eval_batch = max(self.cfg.eval_batch_size or self.cfg.batch_size, quantum)
        eval_batch -= eval_batch % quantum
        val_batches = BatchIterator(
            self.val_ds,
            global_batch=eval_batch,
            process_count=jax.process_count(),
            process_index=jax.process_index(),
            seed=0,
            shuffle=False,
            drop_last=False,
            bucket_multiple=self.cfg.pad_to_multiple,
            max_source_length=self.cfg.max_source_length,
            max_target_length=(
                self.cfg.max_target_length if self.loaded.is_seq2seq else self.cfg.max_source_length
            ),
        )
        # the final batch wraps around to the epoch start to keep shapes
        # fixed (iter_global_batches drop_last=False); loss-mask those
        # duplicate rows so each example is counted exactly once — the
        # same trim the ROUGE evaluator applies to its generations
        n_batches = val_batches.steps_per_epoch()
        rem = len(self.val_ds) % eval_batch
        sl = host_batch_slices(eval_batch, jax.process_count(), jax.process_index())
        total_loss, total_tokens = 0.0, 0.0
        for i, batch in enumerate(val_batches.epoch(0)):
            if rem and i == n_batches - 1:
                local_pos = np.arange(sl.start, sl.stop)
                batch = dict(batch)
                batch["labels"] = np.where(
                    (local_pos >= rem)[:, None], LABEL_PAD, batch["labels"]
                )
            gb = put_batch(batch, self.mesh, sequence_sharded=False)
            lsum, tokens = self._val_loss_fn(val_params, gb)
            total_loss += float(lsum)
            total_tokens += float(tokens)
        return total_loss / max(total_tokens, 1.0)

    def _batch_tokens(self, batch: dict) -> int:
        """Non-pad tokens processed in one host-local batch — source plus
        target for seq2seq; for causal LM the attention mask already covers
        prompt+target, so counting labels again would double-count.  Must
        stay consistent with bench.py so "tokens/sec" means one thing."""
        tokens = int(np.sum(batch["attention_mask"]))
        if self.loaded.is_seq2seq:
            tokens += int(np.sum(batch["labels"] != LABEL_PAD))
        return tokens

    def _optimizer_probe_output(self):
        """The budget layer's cadenced optimizer-apply sample: run a
        stand-alone jitted ``optimizer_apply_block`` (same impl dispatch
        as the train step, zeros gradients built in-program) on the live
        state and return its reduction scalar for the caller to block
        on.  Built LAZILY at the first log cadence so runs that never
        reach a cadence pay no extra compile; only ever invoked by
        ``TrainerObs.optimizer_probe`` at the log cadence — zero new
        off-cadence syncs."""
        if self._opt_probe is None:
            from distributed_llms_example_tpu.train.step import (
                make_optimizer_probe,
            )

            self._opt_probe = make_optimizer_probe(
                self.tx, self.schedule, self.state_sh, self.mesh,
                optim_spec=self.optim_spec,
                optim_impl="xla" if self.pipelined else self.cfg.optim_impl,
                health=self.health_on,
                abstract_params=jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                    self.state.params,
                ),
            )
        return self._opt_probe(self.state)

    def _install_preemption_handler(self) -> None:
        """SIGTERM/SIGINT → finish the in-flight step, checkpoint, exit
        cleanly.  TPU pods get preempted; the reference's answer is losing
        the run (its only save is end-of-training).  With this handler plus
        resume, a preempted execution restarts where it stopped.  No-op
        outside the main thread (signal module restriction)."""
        import signal

        self._preempted = False
        self._prev_handlers = {}

        def on_signal(signum, frame):
            self._preempted = True
            log_json({"event": "preemption_signal", "signal": int(signum)})
            # one graceful chance: restore the previous handler so a SECOND
            # signal terminates (a hung collective can't be flag-broken)
            prev = self._prev_handlers.get(signum)
            if prev is not None:
                try:
                    signal.signal(signum, prev)
                except (ValueError, TypeError):
                    pass

        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(sig, on_signal)
            except ValueError:  # not the main thread
                return

    def _restore_signal_handlers(self) -> None:
        import signal

        for sig, handler in getattr(self, "_prev_handlers", {}).items():
            try:
                signal.signal(sig, handler)
            except ValueError:
                pass

    def _preemption_agreed(self) -> bool:
        """Multi-host: every process must take the same branch at the same
        step — a host-local flag would leave host A saving while host B
        issues the next step's collectives (pod-wide deadlock).  All hosts
        agree via an allgather of the local flag (any host signaled →
        everyone stops).  Single-process: just the flag."""
        if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform; single-host fast path
            return self._preempted
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.asarray([self._preempted]))
        return bool(np.asarray(flags).any())

    def _check_preemption(self, step: int) -> bool:
        """Preemption check for the step loop.  Single-process: the local
        flag, every step (free).  Multi-host: the allgather only at a
        bounded cadence (every ``log_every_steps``) — a per-step blocking
        host collective would serialize JAX's async dispatch and put a DCN
        round-trip on every step's critical path.  The step counter is
        identical on all hosts, so they always enter the allgather
        together; a SIGTERM is acted on at most ``log_every_steps`` steps
        late, well inside any preemption grace period (tens of seconds)."""
        if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform; single-host fast path
            return self._preempted
        if step % self._preempt_sync_every != 0:
            return False
        return self._preemption_agreed()

    def _handle_rewind(
        self, step: int, epoch: int, pos: int
    ) -> tuple[int, int, int] | None:
        """The agreed ``rewind`` anomaly action: run the escalation
        (rewind / skip-batch / halt) through the recovery controller and
        execute it.  Returns the (epoch, pos, step) cursor the loop
        resumes at, or None to stop (``self._anomaly_action`` set).

        Every input here is pod-agreed — the anomaly record's step/code,
        the deterministic fingerprint plan position, the shared
        checkpoint dir — so all processes execute the same branch and
        enter the (collective) orbax restore together."""
        from distributed_llms_example_tpu.obs import sink as sink_mod

        t0 = time.perf_counter()
        anomaly = self.obs.last_anomaly or {"step": step, "code": "unknown"}
        a_step = int(anomaly.get("step", step))
        fingerprint = (
            self.obs.recorder.fingerprint_for(a_step)
            if self.obs.recorder is not None
            else None
        )
        decision = self.recovery.decide(anomaly, fingerprint=fingerprint)
        action, reason = decision.action, decision.reason
        if action != "halt" and fingerprint is not None:
            # quarantine FIRST (for rewind and skip_batch alike): even if
            # the restore below fails and we halt, the quarantine record
            # is evidence for the post-mortem
            self.recovery.quarantine(
                fingerprint["epoch"],
                fingerprint["epoch_step"],
                fingerprint,
                reason=f"anomaly:{anomaly.get('code')}@{a_step}",
            )
        if action == "skip_batch":
            sink_mod.emit({
                "event": "recovery", "action": "skip_batch",
                "step": a_step, "detected_at_step": int(step),
                "code": anomaly.get("code"), "reason": reason,
            }, local=True)
            sink_mod.flush(fsync=True)
            return epoch, pos, step
        if action == "rewind":
            # the rewind target can sit on the far side of a
            # --grad-compression flip OR a topology change (a run that
            # resharded can rewind past its own reshard boundary): the
            # per-step metadata-driven target builder — the SAME one the
            # resume and topology paths use — matches each candidate
            # step's saved shapes, so the walk never skips a newer step
            # over a shape mismatch
            self._reshard_plan = {}
            restored, rewind_err = None, None
            try:
                restored = self.checkpointer.restore_before(
                    a_step, None, target_for=self._restore_target_for
                )
            except Exception as e:
                rewind_err = e
            if restored is None:
                action = "halt"
                reason = (
                    f"no verified checkpoint older than anomaly step {a_step}"
                    + (f" ({str(rewind_err)[:160]})" if rewind_err else "")
                )
            else:
                payload, rstep = restored
                self.state, rplan = self._finish_restore(payload, rstep)
                if rplan["resharded"]:
                    self._emit_reshard_restore(rplan, rstep)
                # checkpoints newer than the restore target may hold the
                # poisoned state (saved between anomaly and detection)
                # with CLEAN checksums — drop them so the replay re-saves
                # from recovered state and no later rewind/resume can
                # pick them (collective, like the restore above)
                self.checkpointer.delete_after(rstep)
                snap = self.recovery.snapshot_for(rstep)
                if snap is not None:
                    # bit-exact replay: the dropout key and the data
                    # cursor exactly as they stood when this checkpoint
                    # was saved
                    self._rng = snap["rng"]
                    r_epoch, r_pos = snap["epoch"], snap["pos"]
                else:
                    # checkpoint predates this process (resume-then-
                    # rewind): its recovery sidecar carries the exact
                    # cursor even across prior-run quarantine skips; the
                    # arithmetic cursor is the last resort.  The dropout
                    # stream continues from the current key (bit-replay
                    # is a same-process property)
                    side = self._load_recovery_sidecar(rstep)
                    if side is not None:
                        r_epoch, r_pos = int(side["epoch"]), int(side["pos"])
                    else:
                        spe = self.batches.steps_per_epoch()
                        r_epoch, r_pos = rstep // spe, rstep % spe
                sink_mod.emit({
                    "event": "recovery", "action": "rewind",
                    "step": a_step, "detected_at_step": int(step),
                    "code": anomaly.get("code"),
                    "restored_step": int(rstep),
                    "steps_lost": int(step - rstep),
                    "rewind_index": self.recovery.rewinds_done,
                    "max_rewinds": self.recovery.max_rewinds,
                    "quarantined": fingerprint is not None,
                    "recovery_wall_s": round(time.perf_counter() - t0, 4),
                    "reason": reason,
                }, local=True)
                sink_mod.flush(fsync=True)
                return r_epoch, r_pos, int(rstep)
        # halt (decided, or a rewind that found nothing to restore)
        self._anomaly_action = "halt"
        sink_mod.emit({
            "event": "recovery", "action": "halt",
            "step": a_step, "detected_at_step": int(step),
            "code": anomaly.get("code"), "reason": reason,
        }, local=True)
        sink_mod.flush(fsync=True)
        return None

    def _check_topology(self, step: int) -> bool:
        """Topology-change (host-loss) check for the step loop — the
        same cadence/agreement discipline as ``_check_preemption``:
        single-process reads the local flag every step; multi-host
        agrees over an allgather at the bounded cadence so every rank
        takes the teardown branch at the same step.  (The injected
        ``host_loss@K`` schedule is deterministic across ranks, so the
        allgather is the same belt the preemption flag wears, not the
        mechanism.)"""
        if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform; single-host fast path
            return self._host_lost
        if step % self._preempt_sync_every != 0:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(np.asarray([self._host_lost]))
        return bool(np.asarray(flags).any())

    def _rebuild_for_mesh(self, mesh: Any) -> None:
        """Swap in a NEW mesh and rebuild everything derived from it —
        the trainer half of topology-change recovery.  Validates first
        (named errors, nothing torn down on failure), then replaces:
        shardings, the abstract state template (EF worker dim follows
        the new replica axes), the batch iterator (global batch
        PRESERVED — only the per-host slice and the shard layout move),
        the jitted train step, the evaluator, the topology payload leaf.
        ``self.state`` becomes an ABSTRACT template: the caller MUST
        follow with the resharding restore (a lost host's shards are
        gone — topology recovery is a restore, not a migration)."""
        cfg = self.cfg
        new_shape = {a: int(s) for a, s in mesh.shape.items()}
        workers = 1
        if cfg.grad_compression == "int8":
            from distributed_llms_example_tpu.ops.quant_collectives import (
                GRAD_WORKER_AXES,
                worker_count,
            )

            workers = worker_count(new_shape)
            if workers <= 1:
                raise ValueError(
                    f"--grad-compression int8 cannot continue on the new "
                    f"mesh {new_shape}: the replica axes "
                    f"{GRAD_WORKER_AXES} give 1 worker group — resume on "
                    "the new slice with compression off instead"
                )
        from distributed_llms_example_tpu.data.batching import validate_batch_mesh

        validate_batch_mesh(
            cfg.batch_size, new_shape,
            process_count=jax.process_count(),
            grad_accum_steps=cfg.grad_accum_steps,
        )
        seq_axis = new_shape.get("sequence", 1)
        sequence_sharded = seq_axis > 1 and all(
            dim % seq_axis == 0
            for dim in (cfg.pad_to_multiple, cfg.max_source_length, self._tgt_cap)
        )
        self.mesh = mesh
        self._grad_workers = workers
        self.sequence_sharded = sequence_sharded
        # abstract state template at the NEW topology: params/opt-state
        # shapes are mesh-invariant, only the EF worker dim moves
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self.state.replace(ef=None),
        )
        if cfg.grad_compression == "int8":
            template = template.replace(ef=jax.tree.map(
                lambda p: jax.ShapeDtypeStruct(
                    (workers,) + tuple(p.shape), np.float32
                ),
                template.params,
            ))
        self.state = template
        self.state_sh = state_shardings(template, mesh, self._rules)
        self._mesh_layout_leaf = mesh_layout_array(
            new_shape, jax.process_count(),
            workers if cfg.grad_compression == "int8" else 0,
        )
        # the batch PLAN is a deterministic function of (seed, epoch,
        # global batch) — all preserved — so the loss trajectory stays
        # comparable across the change; only this host's slice moves
        self.batches = BatchIterator(
            self.train_ds,
            global_batch=cfg.batch_size,
            process_count=jax.process_count(),
            process_index=jax.process_index(),
            seed=cfg.shuffle_seed,
            bucket_multiple=cfg.pad_to_multiple,
            max_source_length=cfg.max_source_length,
            max_target_length=self._tgt_cap,
        )
        self._build_train_step()
        # the startup obs gauges (MFU FLOPs numerator, the static
        # collective-traffic account, devprof's instruction→bucket index)
        # were compiled against the OLD mesh — recompute them from the
        # rebuilt step so post-reshard windows stop reporting a stale MFU
        # and the byte account matches the live program (the PR 14
        # caveat).  Same gating/failure-isolation as startup: an
        # obs_gauges_skipped event, never a failed recovery.
        if not self.pipelined:
            self.obs.startup_gauges(mesh, tgt_cap=self._tgt_cap)
        for attr in ("_val_loss_fn", "_val_unpermute"):
            if hasattr(self, attr):
                delattr(self, attr)
        if self.val_ds:
            self.evaluator = Evaluator(
                self.loaded.module,
                self.config,
                self.tokenizer,
                mesh,
                num_beams=cfg.num_beams,
                max_new_tokens=cfg.eval_max_new_tokens,
                is_seq2seq=self.loaded.is_seq2seq,
            )

    def _handle_topology_change(
        self, step: int, epoch: int, pos: int
    ) -> tuple[int, int, int] | None:
        """The agreed host-loss action (ISSUE 14), on top of PR 6's
        escalation: tear down collectives, re-run the ``jax.distributed``
        bootstrap on the surviving slice, rebuild mesh / shardings /
        train step / batch plan, restore the newest verified checkpoint
        through the RESHARDING path, and resume from the recovery
        sidecar's (epoch, pos) cursor with the quarantine set intact.
        Returns the cursor the loop resumes at, or None to stop
        (``self._anomaly_action`` set — the evidence-preserving
        checkpoint policy, like a final-window rewind)."""
        from distributed_llms_example_tpu.obs import sink as sink_mod

        t0 = time.perf_counter()
        self._host_lost = False
        old_layout = self._live_mesh_layout()
        halt_reason: str | None = None
        if self.cfg.on_host_loss != "reshard":
            halt_reason = "--on-host-loss halt: leaving recovery to a resumed run"
        elif self.pipelined:
            # the composition table's row IS the message (deep-guard
            # discipline: the text cannot drift from the table)
            from distributed_llms_example_tpu.analysis.composition import (
                reason_for,
            )

            halt_reason = reason_for("reshard-pipelined")
        sink_mod.emit({
            "event": "topology_change",
            "step": int(step),
            "old_mesh": old_layout["axes"],
            "old_processes": old_layout["processes"],
            "policy": "halt" if halt_reason else "reshard",
            **({"reason": halt_reason} if halt_reason else {}),
        }, local=True)
        sink_mod.flush(fsync=True)
        if halt_reason:
            self._anomaly_action = "checkpoint"
            return None
        # nothing in flight may straddle the teardown
        self.checkpointer.wait()
        if old_layout["processes"] > 1:
            # the ONE owner of the re-init path (core/mesh.py): shutdown
            # + fresh bootstrap from the re-read rendezvous facts of the
            # surviving slice
            from distributed_llms_example_tpu.core.mesh import (
                reinitialize_distributed,
            )

            reinitialize_distributed()
        try:
            if self._next_mesh_override is not None:
                new_mesh = build_mesh(self._next_mesh_override)
                self._next_mesh_override = None
            else:
                from distributed_llms_example_tpu.core.mesh import elastic_mesh_spec

                new_mesh = build_mesh(
                    elastic_mesh_spec(self.cfg.mesh, jax.device_count())
                )
            self._rebuild_for_mesh(new_mesh)
            self._reshard_plan = {}
            restored = self.checkpointer.restore_latest(
                None, target_for=self._restore_target_for
            )
        except Exception as e:
            sink_mod.emit({
                "event": "recovery", "action": "halt", "step": int(step),
                "code": "host_loss",
                "reason": f"topology rebuild/restore failed: {str(e)[:240]}",
            }, local=True)
            sink_mod.flush(fsync=True)
            self._anomaly_action = "halt"
            return None
        if restored is None:
            sink_mod.emit({
                "event": "recovery", "action": "halt", "step": int(step),
                "code": "host_loss",
                "reason": "no verified checkpoint to reshard from",
            }, local=True)
            sink_mod.flush(fsync=True)
            self._anomaly_action = "halt"
            return None
        payload, rstep = restored
        self.state, plan = self._finish_restore(payload, rstep)
        # exact cursor + quarantine, same ladder as rewind: the in-memory
        # save snapshot (restores the dropout key too, so an in-process
        # reshard replays the surviving steps on the same RNG stream),
        # then the recovery sidecar (cross-run: pos can drift from
        # step % steps_per_epoch after a quarantine skip), then arithmetic
        snap = self.recovery.snapshot_for(rstep)
        side = self._load_recovery_sidecar(rstep)
        if side is not None:
            for e, s, rec in side.get("quarantined", []):
                self.recovery.quarantined.setdefault((int(e), int(s)), rec)
        if snap is not None:
            self._rng = snap["rng"]
            r_epoch, r_pos = snap["epoch"], snap["pos"]
        elif side is not None:
            r_epoch, r_pos = int(side["epoch"]), int(side["pos"])
        else:
            spe = self.batches.steps_per_epoch()
            r_epoch, r_pos = rstep // spe, rstep % spe
        self._emit_reshard_restore(
            plan, rstep,
            detected_at_step=int(step),
            steps_lost=int(step - rstep),
            reshard_wall_s=round(time.perf_counter() - t0, 4),
        )
        sink_mod.flush(fsync=True)
        return r_epoch, r_pos, int(rstep)

    def train(self) -> dict[str, Any]:
        # handlers restored in a finally: a raising train step must not
        # leave the flag-setting handler installed process-wide (it would
        # swallow Ctrl-C forever after); on the preempted path the finally
        # runs AFTER the graceful checkpoint, so a second SIGTERM during
        # the save terminates instead of being silently re-flagged
        self._install_preemption_handler()
        try:
            return self._train_loop()
        except Exception as e:
            # a crashing step must still leave the post-mortem evidence:
            # dump the flight recorder (ring → atomic bundle) — and, when
            # the crash is a RESOURCE_EXHAUSTED, the memory postmortem
            # (last static account + watermark history + live-buffer
            # top-N) — then push the JSONL channel to disk before the
            # traceback propagates
            crash_step = int(getattr(self, "_last_step", self.start_step))
            if self.obs.recorder is not None:
                self.obs.recorder.dump(
                    self.cfg.output_dir,
                    reason="exception",
                    step=crash_step,
                )
            if self.obs.memory is not None:
                self.obs.memory.maybe_dump_postmortem(
                    self.cfg.output_dir, step=crash_step, error=e
                )
            from distributed_llms_example_tpu.obs import sink as sink_mod

            sink_mod.flush(fsync=True)
            raise
        finally:
            self._restore_signal_handlers()

    def _train_loop(self) -> dict[str, Any]:
        from distributed_llms_example_tpu.obs.recorder import batch_fingerprint

        cfg = self.cfg
        obs = self.obs
        obs.set_start_step(self.start_step)
        logger = MetricLogger(every=cfg.log_every_steps)
        self._preempt_sync_every = max(1, cfg.log_every_steps)
        step = self.start_step
        self._last_step = step
        self._anomaly_action: str | None = None
        self._host_lost = False
        t0 = time.perf_counter()
        last_eval: dict[str, float] = {}
        last_metrics: dict[str, Any] | None = None
        steps_per_epoch = self.batches.steps_per_epoch()
        # (epoch, pos) is the DATA cursor: ``pos`` counts iterator items
        # consumed this epoch INCLUDING quarantine-skipped batches, so it
        # can drift ahead of ``step % steps_per_epoch`` after a recovery
        # skip.  The global ``step`` stays the optimizer-step counter
        # (checkpoints, LR schedule, resume contract); only the cursor
        # knows about skips, and rewinds restore both together.
        if self._resume_cursor is not None:
            # exact cursor from the recovery sidecar (survives quarantine
            # skips); arithmetic otherwise
            epoch, pos = self._resume_cursor
        else:
            epoch = step // steps_per_epoch
            pos = step - epoch * steps_per_epoch
        report_epoch = epoch
        if cfg.on_anomaly == "rewind" and self.checkpointer.latest_step() is None:
            # the rewind anchor: an anomaly before the first periodic save
            # must still find a verified step to restore to — without it
            # the very first recovery attempt could only halt
            self._save_checkpoint(step, epoch=epoch, pos=pos, force=True)
            self.checkpointer.wait()
        while epoch < cfg.num_epochs:
            report_epoch = epoch
            # assemble host batches (tokenize/pad/bucket) on a background
            # thread, prefetch_batches ahead, so input work overlaps the
            # device step instead of sitting on the critical path.  A
            # resumed (or rewound) epoch fast-forwards at the INDEX level
            # (the batch plan is deterministic per (seed, epoch)): no
            # skipped batch is ever tokenized or padded.
            epoch_batches = self.batches.epoch(epoch, start_step=pos)
            if cfg.prefetch_batches > 0:
                epoch_batches = Prefetcher(epoch_batches, depth=cfg.prefetch_batches)
            rewind_cursor: tuple[int, int, int] | None = None
            topology_cursor: tuple[int, int, int] | None = None
            try:
                for batch in obs.wrap_batches(self._with_data_retries(epoch_batches)):
                    pos += 1
                    if self.recovery.should_skip(epoch, pos - 1, batch):
                        continue  # quarantined batch: the retry skips it
                    obs.profiler.before_step(step + 1)
                    if self.chaos.take("oom", step + 1):
                        # RESOURCE_EXHAUSTED-shaped so the memprof
                        # tripwire (train()'s except hook) fires exactly
                        # like a real XLA OOM: postmortem bundle, then
                        # the raise propagates
                        raise RuntimeError(
                            "RESOURCE_EXHAUSTED: chaos-injected out of "
                            f"memory before step {step + 1}"
                        )
                    if self.chaos.take("nan_grad", step + 1):
                        # chaos (or the legacy test hook): corrupt one
                        # param element (lazy device op — the NaN surfaces
                        # in this step's in-graph numerics, nowhere on the
                        # host)
                        flat, treedef = jax.tree.flatten(self.state.params)
                        flat[0] = flat[0].at[(0,) * flat[0].ndim].set(float("nan"))
                        self.state = self.state.replace(
                            params=jax.tree.unflatten(treedef, flat)
                        )
                    with obs.host_span():
                        # host bookkeeping charged to the budget account's
                        # host_overhead component (the fingerprint's crc32
                        # is the loop's main non-span host cost)
                        fingerprint = (
                            batch_fingerprint(
                                batch,
                                epoch=epoch,
                                epoch_step=pos - 1,
                            )
                            if obs.recorder is not None
                            else None
                        )
                    with obs.step_span():
                        gb = put_batch(batch, self.mesh, sequence_sharded=self.sequence_sharded)
                        if self.use_dropout:
                            self._rng, sub = jax.random.split(self._rng)
                            self.state, metrics = self.train_step(self.state, gb, sub)
                        else:
                            self.state, metrics = self.train_step(self.state, gb)
                    step += 1
                    self._last_step = step
                    last_metrics = metrics
                    tokens = self._batch_tokens(batch) * jax.process_count()
                    # budget layer: at the log cadence ONLY, time the
                    # device-queue drain before the logger's fetch — the
                    # measured block is the un-overlapped device tail
                    # (step_budget's device_busy); off-cadence this is two
                    # comparisons and returns
                    obs.budget_probe(step, metrics["loss"])
                    # pass DEVICE scalars: converting here (float(...)) would
                    # block on the step every iteration and serialize JAX's
                    # async dispatch — the logger converts only on emit (the
                    # device_sync span times exactly that cadenced readback)
                    with obs.sync_span():
                        logger.step(
                            step,
                            metrics["loss"],
                            lr=metrics["learning_rate"],
                            tokens=tokens,
                            epoch=epoch,
                        )
                    # per-step obs bookkeeping: step-time ring, profiler
                    # stop, flight-recorder append, cadenced heartbeat +
                    # health check + window summary — before
                    # checkpoint/eval so their wall time rides their own
                    # spans, not this step's duration
                    action = obs.on_step(step, epoch, metrics, fingerprint)
                    if action in ("halt", "checkpoint"):
                        # agreed across hosts inside the health cadence
                        # (same allgather discipline as preemption) — every
                        # process takes this branch at the same step
                        self._anomaly_action = action
                        break
                    if action == "rewind":
                        # agreed like halt/checkpoint; the escalation
                        # (rewind / skip-batch / halt) derives only from
                        # pod-agreed inputs, so every process computes the
                        # same cursor (or the same halt)
                        rewind_cursor = self._handle_rewind(step, epoch, pos)
                        break
                    # cadenced optimizer-apply wall sample (budget layer:
                    # optimizer_apply_ms in the step_budget account) —
                    # runs AFTER the window closed, alongside ckpt/eval,
                    # so mark_step_start below excludes its wall from the
                    # next step's duration like theirs
                    obs.optimizer_probe(step, self._optimizer_probe_output)
                    if self.checkpointer.should_save(step):
                        with obs.checkpoint_span():
                            self._save_checkpoint(step, epoch=epoch, pos=pos)
                    if cfg.evaluation_steps > 0 and step % cfg.evaluation_steps == 0:
                        with obs.eval_span():
                            last_eval = self.evaluate(epoch, step=step)
                    # re-anchor the step clock: checkpoint/eval time is on
                    # their own spans and must not inflate the NEXT step's
                    # ring-buffer duration (false straggler flags)
                    obs.spans.mark_step_start()
                    if self.chaos.take("sigterm", step):
                        # chaos: a real signal through the real handler —
                        # the graceful-preemption path, not a shortcut
                        import signal as _signal

                        os.kill(os.getpid(), _signal.SIGTERM)
                    if self.chaos.take("host_loss", step):
                        # chaos: the agreed topology-change signal — the
                        # deterministic schedule raises it on every rank
                        # at the same step; _check_topology's allgather
                        # is the same belt the preemption flag wears
                        self._host_lost = True
                    if self._check_topology(step):
                        topology_cursor = self._handle_topology_change(
                            step, epoch, pos
                        )
                        break
                    if self._check_preemption(step):
                        self._preempted = True  # agreed across hosts
                        break
            finally:
                # stop the producer thread even when the loop body raises
                if isinstance(epoch_batches, Prefetcher):
                    epoch_batches.close()
                    # the per-run "is the input pipeline on the critical
                    # path?" answer (host counters, once per epoch): a
                    # consumer_wait_s near the first batch's assembly time
                    # means the thread hid everything (device-bound loop —
                    # BENCH_r05's prefetch2 ≈ prefetch0); wait growing with
                    # items means the producer cannot keep up
                    s = epoch_batches.stats()
                    log_json({
                        "event": "prefetch_stats",
                        "epoch": epoch,
                        "depth": cfg.prefetch_batches,
                        "items": s["items"],
                        "consumer_wait_s": round(s["consumer_wait_s"], 4),
                    })
            if rewind_cursor is not None:
                # resume the loop at the restored (epoch, pos, step) —
                # same-process, no recompilation, no weight reload; the
                # replay re-runs the surviving steps bit-identically and
                # skips the quarantined batch
                epoch, pos, step = rewind_cursor
                self._last_step = step
                obs.spans.mark_step_start()
                continue
            if topology_cursor is not None:
                # resume on the NEW mesh at the resharded checkpoint's
                # cursor: the epoch re-enters at the top of this loop, so
                # the batch plan is re-derived from the rebuilt iterator
                # (same global batch, new per-host slice) and the next
                # step dispatch compiles the rebuilt program
                epoch, pos, step = topology_cursor
                self._last_step = step
                obs.spans.mark_step_start()
                continue
            # Epoch boundary: a SIGTERM that landed between sync steps may
            # have set only the LOCAL flag (the cadence check above skipped
            # it) — acting on it here un-agreed would desynchronize the
            # pod (this host saves/exits while peers enter eval's
            # collectives).  Every host reaches this point at the same
            # step, so an unconditional agreement round is collectively
            # safe; mid-epoch agreed breaks re-agree here (still true).
            if jax.process_count() > 1:  # pod-agreed: pod-uniform guard; the branch body IS the agreement (_preemption_agreed)
                self._preempted = self._preemption_agreed()
            if self._preempted or self._anomaly_action is not None:
                break
            # epoch boundary: emit the partial metric window (the fix for
            # the lost-final-window cadence bug) before the eval resets
            # the wall clocks
            logger.flush(step, epoch=epoch)
            with obs.eval_span():
                # per-epoch eval, reference parity
                last_eval = self.evaluate(epoch, step=step)
            epoch += 1
            pos = 0
        logger.flush(step, epoch=report_epoch)
        # close any open trace window (flushed, not lost) and emit the
        # final obs window (plus the final partial-window health check)
        final_action = obs.finalize(
            step, report_epoch, sync_leaf=last_metrics["loss"] if last_metrics else None
        )
        if self._anomaly_action is None and final_action in (
            "halt", "checkpoint", "rewind"
        ):
            # a rewind agreed in the FINAL partial window has no loop left
            # to replay: degrade to the checkpoint policy (preserve the
            # evidence, stop with the anomaly marker) — never fall through
            # to save_final() exporting possibly-poisoned params as a
            # successful run
            self._anomaly_action = (
                "checkpoint" if final_action == "rewind" else final_action
            )
        if self._anomaly_action is not None:
            wall = time.perf_counter() - t0
            if self._anomaly_action == "checkpoint":
                # a RESUMABLE checkpoint of the (possibly already
                # poisoned) state: post-mortem work restores it next to
                # the flight-recorder bundle — resuming a diverged run
                # from here is the operator's explicit call
                self._save_checkpoint(step, epoch=epoch, pos=pos, force=True)
                self.checkpointer.wait()
            log_json({
                "event": "anomaly_stop", "step": step,
                "policy": self._anomaly_action, "wall_seconds": wall,
            })
            return {
                "steps": step, "wall_seconds": wall, "final_eval": last_eval,
                "anomaly": self._anomaly_action,
            }
        if self._preempted:
            # the last steps' evidence first (the bundle is what a
            # post-mortem of the preempted run reads)...
            if obs.recorder is not None:
                obs.recorder.dump(
                    self.cfg.output_dir, reason="preemption", step=step
                )
            # ...then save where we stopped and get out; resume restarts
            # from here (cursor + quarantine ride the recovery sidecar)
            self._save_checkpoint(step, epoch=epoch, pos=pos, force=True)
            self.checkpointer.wait()
            wall = time.perf_counter() - t0
            log_json({"event": "preempted", "step": step, "wall_seconds": wall})
            return {
                "steps": step, "wall_seconds": wall, "final_eval": last_eval,
                "preempted": True,
            }
        self._save_checkpoint(self.total_steps, epoch=epoch, pos=pos, force=True)
        self.checkpointer.wait()
        self.save_final()
        wall = time.perf_counter() - t0
        log_json({"event": "done", "steps": step, "wall_seconds": wall})
        return {"steps": step, "wall_seconds": wall, "final_eval": last_eval}

    def save_final(self) -> None:
        """Final artifact: an HF-format checkpoint (``config.json`` +
        ``model.safetensors``) — parity with the reference's
        ``model.save_pretrained(output_dir)`` (reference helpers.py:13), so
        the trained model loads in transformers, back into this framework
        (``load_model(out_dir)``), or any downstream HF consumer — plus the
        TrainConfig (``train_config.json``) and Valohai sidecars."""
        from distributed_llms_example_tpu.models.export import save_hf_checkpoint

        out = os.path.join(self.cfg.output_dir, "model")
        final_params = self.state.params
        if self.pipelined:
            # export in the standard per-layer layout so the artifact loads
            # anywhere (eval, conversion, non-pipelined resume), gathering
            # each layer STRAIGHT to host as it is unstacked — on a
            # pure-pipeline mesh (fsdp=tensor=1) any device-side unstack
            # would replicate the whole model; this caps HBM at the
            # training footprint plus one layer
            from distributed_llms_example_tpu.parallel.pipeline import (
                unstack_for_family_to_host,
            )

            final_params = unstack_for_family_to_host(
                self.loaded.family, final_params, writer_only=True,
                row_order=self._storage_row_order,
            )
        else:
            # multi-host shards live on other hosts' devices; gather each
            # leaf to host, kept only on the writing process — a whole-tree
            # allgather would materialize the full fp32 model in EVERY
            # host's RAM simultaneously (~27 GB/host for llama-2-7b) when
            # only process 0 writes
            from distributed_llms_example_tpu.parallel.pipeline import gather_tree_to_host

            final_params = gather_tree_to_host(final_params, writer_only=True)
        if jax.process_index() == 0:  # pod-agreed: p0-only LOCAL export; gather_tree_to_host above ran on every rank
            os.makedirs(out, exist_ok=True)
            save_hf_checkpoint(out, self.loaded.family, self.config, final_params)
            with open(os.path.join(out, "train_config.json"), "w") as f:
                f.write(self.cfg.to_json())
            save_valohai_metadata(out)
