"""distributed_llms_example_tpu — a TPU-native distributed LLM fine-tuning framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of
valohai/distributed-llms-example (reference mounted at /root/reference):
the reference's three CUDA+NCCL data-parallel fine-tuning paths
(`train-torchrun.py`, `train-accelerator.py`, `train-task.py`) are
re-expressed as a single SPMD training core jitted over a
`jax.sharding.Mesh` with named axes ("stage", "data", "fsdp",
"sequence", "tensor") — pipeline, data, ZeRO-3, ring-attention context,
and tensor/expert parallelism respectively — Flax model definitions, an
Optax optimizer, and XLA collectives over ICI/DCN instead of NCCL.

Package layout (see SURVEY.md section 7 for the build plan):

- ``core``       — config, device mesh, multi-host init, precision policy
- ``utils``      — pytree helpers, JSON-line metric logging, Valohai facts
- ``parallel``   — sharding rules, activation constraints, GPipe pipeline
- ``ops``        — attention (XLA + Pallas flash + ring), MoE, norms
- ``models``     — T5 / BART / LLaMA / Mixtral in flax.linen + HF converters
- ``data``       — tokenizers, JSON datasets, deterministic host sharding
- ``train``      — the pjit train step, optimizer factory, Trainer
- ``evaluation`` — jitted greedy/beam generation, ROUGE, metric aggregation
- ``io``         — Orbax checkpointing + Valohai metadata sidecars
- ``launch``     — CLI entry points and multi-host rendezvous
- ``native``     — C++ runtime components (data loader) with Python fallbacks
"""

__version__ = "0.1.0"
