"""Mixed-precision policy.

The reference runs fp32 everywhere (its only precision awareness is an fp16
gate on collator padding, reference train-accelerator.py:158).  On TPU the
native fast path is bfloat16 on the MXU: parameters and optimizer state stay
float32, matmul/activation compute runs bf16, and loss/grad reductions are
fp32.  This module is the single place that policy lives.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}

# How the compiler IR spells each policy dtype (HLO/StableHLO element-type
# names).  The analysis/ IR lint derives its precision-smell patterns from
# the ACTIVE policy through this table rather than hardcoding "bf16"/"f32",
# so a policy change re-targets the lint automatically.
_HLO_NAMES = {
    "float32": "f32",
    "bfloat16": "bf16",
    "float16": "f16",
}


def hlo_dtype_name(dtype: jnp.dtype) -> str:
    name = jnp.dtype(dtype).name
    try:
        return _HLO_NAMES[name]
    except KeyError:
        raise ValueError(f"no HLO name known for dtype {name!r}") from None


def parse_dtype(name: str) -> jnp.dtype:
    try:
        return _DTYPES[name]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r}; choose from {sorted(_DTYPES)}") from None


@dataclasses.dataclass(frozen=True)
class Policy:
    """What dtype each class of tensor uses.

    - ``param_dtype``: dtype parameters are stored in (fp32 master weights)
    - ``compute_dtype``: dtype activations/matmuls run in (bf16 on TPU)
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16

    @classmethod
    def from_names(cls, param: str = "float32", compute: str = "bfloat16") -> "Policy":
        return cls(param_dtype=parse_dtype(param), compute_dtype=parse_dtype(compute))

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def cast_to_param(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.param_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def matmul_promotion_smell(self) -> tuple[str, str] | None:
        """The (from, to) HLO dtype pair that constitutes a hot-path
        precision violation under this policy, or None when the policy has
        nothing to violate.  With bf16 compute, a ``convert`` promoting a
        bf16 operand to f32 that then feeds a ``dot`` forfeits MXU bf16
        throughput — fp32 is reserved for reductions (loss, psums), never
        matmul operands.  fp32 *accumulation* of a bf16 dot
        (``f32[..] dot(bf16[..], bf16[..])``) is fine and not matched."""
        if self.compute_dtype == jnp.bfloat16:
            return (hlo_dtype_name(self.compute_dtype), "f32")
        return None
