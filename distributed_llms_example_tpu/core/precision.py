"""Mixed-precision policy.

The reference runs fp32 everywhere (its only precision awareness is an fp16
gate on collator padding, reference train-accelerator.py:158).  On TPU the
native fast path is bfloat16 on the MXU: parameters and optimizer state stay
float32, matmul/activation compute runs bf16, and loss/grad reductions are
fp32.  This module is the single place that policy lives.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def parse_dtype(name: str) -> jnp.dtype:
    try:
        return _DTYPES[name]
    except KeyError:
        raise ValueError(f"unknown dtype {name!r}; choose from {sorted(_DTYPES)}") from None


@dataclasses.dataclass(frozen=True)
class Policy:
    """What dtype each class of tensor uses.

    - ``param_dtype``: dtype parameters are stored in (fp32 master weights)
    - ``compute_dtype``: dtype activations/matmuls run in (bf16 on TPU)
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16

    @classmethod
    def from_names(cls, param: str = "float32", compute: str = "bfloat16") -> "Policy":
        return cls(param_dtype=parse_dtype(param), compute_dtype=parse_dtype(compute))

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )

    def cast_to_param(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.param_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )
