from distributed_llms_example_tpu.core.config import MeshConfig, TrainConfig
from distributed_llms_example_tpu.core.mesh import MeshSpec, build_mesh
from distributed_llms_example_tpu.core.precision import Policy

__all__ = ["MeshConfig", "TrainConfig", "MeshSpec", "build_mesh", "Policy"]
