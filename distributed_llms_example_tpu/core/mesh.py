"""Device mesh construction and multi-host bootstrap.

Replaces the reference's process-group plumbing with the TPU-native pair:

- ``jax.distributed.initialize(coordinator, num_processes, process_id)``
  consumes exactly the three rendezvous facts the reference pulls from the
  Valohai platform — master IP, world size, and rank
  (reference train-task.py:420-425, ``tcp://{primary_local_ip}:1234``) —
  but instead of a NCCL process group (train-task.py:405) it bootstraps the
  XLA runtime, after which all communication is compiler-inserted
  collectives over ICI/DCN.

- ``jax.sharding.Mesh`` over named axes ("stage", "data", "fsdp",
  "expert", "sequence", "tensor") — pipeline, data, ZeRO-3, MoE expert,
  ring-attention context, and tensor parallelism respectively — is the
  single object that expresses every parallelism strategy; the reference
  needed three different mechanisms (torchrun env vars, Accelerate,
  hand-rolled all_reduce) for data parallelism alone.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# AXES lives in core/config.py (the canonical home — importable without
# jax, which is what the CLI parser and the sharding lint need); it is
# re-exported here because the device-mesh constructor is its main user.
from distributed_llms_example_tpu.core.config import AXES, MeshConfig

logger = logging.getLogger(__name__)

DEFAULT_COORDINATOR_PORT = 1234  # parity with reference train-task.py:420


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Resolved (all positive) mesh axis sizes."""

    data: int
    fsdp: int
    sequence: int
    tensor: int
    stage: int = 1
    expert: int = 1

    @property
    def size(self) -> int:
        return self.stage * self.data * self.fsdp * self.expert * self.sequence * self.tensor

    @property
    def batch_shards(self) -> int:
        """Number of ways the global batch is split (data × fsdp × expert)."""
        return self.data * self.fsdp * self.expert

    def as_tuple(self) -> tuple[int, int, int, int, int, int]:
        """Axis sizes in mesh-axis order (AXES)."""
        return (self.stage, self.data, self.fsdp, self.expert, self.sequence, self.tensor)


def resolve_mesh_shape(cfg: MeshConfig, n_devices: int) -> MeshSpec:
    """Resolve -1 axes and validate the product against the device count."""
    sizes = cfg.axis_sizes()
    bad = {k: v for k, v in sizes.items() if v == 0 or v < -1}
    if bad:
        raise ValueError(f"mesh axis sizes must be positive or -1, got {bad}")
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(f"{n_devices} devices not divisible by fixed axes product {fixed}")
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(f"mesh {sizes} has size {total}, but {n_devices} devices are available")
    return MeshSpec(**sizes)


def build_mesh(cfg: MeshConfig | MeshSpec | None = None, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the global device mesh.

    ``jax.experimental.mesh_utils.create_device_mesh`` is used when possible
    so axis order maps onto physical ICI topology (tensor innermost).
    """
    devices = list(devices if devices is not None else jax.devices())
    if cfg is None:
        cfg = MeshConfig()
    spec = cfg if isinstance(cfg, MeshSpec) else resolve_mesh_shape(cfg, len(devices))
    shape = spec.as_tuple()
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:  # non-TPU platforms (CPU test meshes) lack topology info
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def initialize_distributed(
    coordinator_address: str = "",
    num_processes: int = 0,
    process_id: int = -1,
) -> None:
    """Multi-host bootstrap from the Valohai rendezvous triple.

    Mirrors reference train-task.py:404-430: the master's primary local IP,
    the required execution count (world size), and this member's rank are
    taken — in priority order — from explicit arguments, from the
    ``valohai.distributed`` platform config if importable, or from
    environment variables (``VH_MASTER_IP`` / ``VH_WORLD_SIZE`` /
    ``VH_RANK``, falling back to torchrun-style ``MASTER_ADDR`` /
    ``WORLD_SIZE`` / ``RANK`` for drop-in compatibility).  Single-process
    runs (no facts found, or world size 1) skip initialization entirely —
    the local-run fallback the reference only has for run identification
    (helpers.py:37-39) applied to distribution itself.
    """
    if not coordinator_address or num_processes <= 0 or process_id < 0:
        ip, world, rank = _valohai_facts()
        coordinator_address = coordinator_address or ip
        num_processes = num_processes if num_processes > 0 else world
        process_id = process_id if process_id >= 0 else (rank if rank is not None else -1)
    if num_processes <= 1:
        logger.info("single-process run; skipping jax.distributed.initialize")
        return
    # A multi-process run with unresolvable rendezvous facts must fail loudly:
    # silently skipping would degrade to N independent single-host trainings
    # with no gradient sync (wrong model, no error).
    if not coordinator_address:
        raise ValueError(
            f"num_processes={num_processes} but no coordinator address found "
            "(pass --coordinator-address, or set VH_MASTER_IP/MASTER_ADDR)"
        )
    if process_id < 0:
        raise ValueError(
            f"num_processes={num_processes} but no process id found "
            "(pass --process-id, or set VH_RANK/RANK)"
        )
    if ":" not in coordinator_address:
        coordinator_address = f"{coordinator_address}:{DEFAULT_COORDINATOR_PORT}"
    # The CPU client defaults to NO cross-process collectives backend
    # (jax_cpu_collectives_implementation="none") and then every
    # multi-process computation — put_batch's global arrays, the
    # preemption/heartbeat allgathers — dies with "Multiprocess
    # computations aren't implemented on the CPU backend".  Gloo over TCP
    # is jax's supported CPU answer; the flag only affects CPU client
    # construction (TPU/GPU ignore it), so set it before initialize
    # whenever this jax version has it.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # older/newer jax without the flag: keep its default
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: coordinator=%s process=%d/%d local_devices=%d",
        coordinator_address,
        process_id,
        num_processes,
        jax.local_device_count(),
    )


def elastic_mesh_spec(cfg: MeshConfig, n_devices: int) -> MeshSpec:
    """Resolve a mesh shape for a CHANGED device count (topology-change
    recovery, ISSUE 14): the configured factorization re-resolved against
    the surviving slice.

    A ``-1`` axis absorbs the change exactly as at startup.  A fully
    pinned factorization whose product no longer matches re-scales the
    DATA axis (the replica dimension is the one elasticity semantically
    varies — model sharding axes keep their meaning); when the remaining
    axes' product does not divide the device count there is no
    well-typed shrink and this raises with both factorizations named."""
    sizes = cfg.axis_sizes()
    try:
        return resolve_mesh_shape(cfg, n_devices)
    except ValueError:
        pass
    rest = int(np.prod([v for k, v in sizes.items() if k != "data"]))
    if -1 in sizes.values() or rest <= 0 or n_devices % rest:
        raise ValueError(
            f"cannot re-factorize mesh {sizes} onto {n_devices} surviving "
            f"device(s): the non-data axes' product ({rest}) must divide "
            "the device count — resume on a slice shape the configured "
            "model sharding fits, or change the mesh config"
        )
    sizes["data"] = n_devices // rest
    return MeshSpec(**sizes)


def reinitialize_distributed(
    coordinator_address: str = "",
    num_processes: int = 0,
    process_id: int = -1,
) -> None:
    """Tear down and re-run the multi-host bootstrap on a CHANGED slice
    (topology-change recovery): ``jax.distributed.shutdown`` if a client
    is live, then :func:`initialize_distributed` with the new rendezvous
    facts (argument > platform > env, exactly like startup).  This is
    the ONE owner of the re-init path — ``scripts/repo_lint.py`` forbids
    ``jax.distributed`` calls and raw ``Mesh`` construction outside this
    module, so a second, subtly different re-init cannot grow elsewhere.
    Single-process (no facts, or world size 1): shutdown only — the
    surviving slice needs no rendezvous."""
    try:
        jax.distributed.shutdown()
    except Exception:
        # no client initialized (single-process run, or a client torn
        # down by the failure itself): nothing to shut down
        pass
    initialize_distributed(coordinator_address, num_processes, process_id)


def _valohai_facts() -> tuple[str, int, int | None]:
    """(master_ip, world_size, rank) from the platform, else env, else local.

    ``rank`` is None when no source supplied it — callers must not default
    it for multi-process runs (every host claiming rank 0 is not a rendezvous).
    """
    try:
        import valohai  # type: ignore

        dist = valohai.distributed
        if dist.is_distributed_task():
            return (
                dist.master().primary_local_ip,
                int(dist.required_count),
                int(dist.me().rank),
            )
    except Exception:
        pass
    env = os.environ
    ip = env.get("VH_MASTER_IP", env.get("MASTER_ADDR", ""))
    world = int(env.get("VH_WORLD_SIZE", env.get("WORLD_SIZE", "1")))
    rank_s = env.get("VH_RANK", env.get("RANK"))
    return ip, world, (int(rank_s) if rank_s is not None else None)


def device_report() -> dict:
    """TPU analog of the reference's ``print_gpu_report``
    (train-torchrun.py:37-58): versions + device inventory, as a dict for the
    JSON-lines metadata channel instead of ``nvidia-smi`` stdout scraping."""
    devs = jax.devices()
    return {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_device_count": jax.device_count(),
        "local_device_count": jax.local_device_count(),
        "devices": [
            {
                "id": d.id,
                "platform": d.platform,
                "kind": getattr(d, "device_kind", "?"),
                "process": d.process_index,
            }
            for d in devs[:32]
        ],
    }
