"""Configuration for the framework.

The reference exposes exactly six CLI parameters, identical across its three
entry points (reference train-torchrun.py:182-188, train-accelerator.py:319-325,
train-task.py:410-416): ``model-ckpt``, ``output-dir``, ``batch-size``,
``num-epochs``, ``warmup-steps``, ``evaluation-steps``.  Two of them are dead
in the reference (``batch-size`` is hardcoded away in train-accelerator.py:169
and train-task.py:180; ``warmup-steps`` is overridden to 1 in
train-accelerator.py:204) — here every flag is honored for real.

On top of those six we add the knobs a TPU SPMD framework actually needs:
mesh shape, precision policy, gradient accumulation, checkpointing cadence,
and sequence lengths (the reference hardcodes 1024/128,
train-accelerator.py:115-127).
"""

from __future__ import annotations

import argparse
import dataclasses
import difflib
import json
from typing import Any

# The canonical mesh axis names, in physical-locality order (tensor is the
# innermost / fastest-varying axis; stage is outermost so pipeline hops can
# cross DCN).  Lives here — not in core/mesh.py — so axis-name validation
# (parse_mesh_arg, the sharding lint) never needs jax importable; mesh.py
# re-exports it for the device-mesh construction itself.
AXES: tuple[str, ...] = ("stage", "data", "fsdp", "expert", "sequence", "tensor")


def unknown_axis_error(name: str) -> ValueError:
    """A typo'd mesh axis must name itself and its likely intent — the
    alternative today is an opaque KeyError deep inside jax once the bad
    name reaches a PartitionSpec."""
    hint = difflib.get_close_matches(name, AXES, n=1)
    did_you_mean = f" (did you mean {hint[0]!r}?)" if hint else ""
    return ValueError(
        f"unknown mesh axis {name!r}{did_you_mean}; valid axes: {', '.join(AXES)}"
    )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh shape.

    Axis semantics (order is physical-locality order; ``tensor`` is the
    innermost / fastest-varying axis so tensor-parallel collectives ride the
    shortest ICI links, and ``stage`` is outermost so pipeline hops — the
    least latency-sensitive traffic — can cross DCN between slices):

    - ``stage``:    pipeline (GPipe-style) model parallelism — decoder
                    layers split into stages, microbatches streamed through
                    (parallel/pipeline.py)
    - ``data``:     pure data parallelism (batch sharding, params replicated)
    - ``fsdp``:     data parallelism with parameters/optimizer sharded
                    (ZeRO-3 equivalent; batch is also sharded over this axis)
    - ``expert``:   MoE expert parallelism (stacked expert weights shard
                    their leading E dim here; batch is also sharded over
                    this axis, and GSPMD lowers dispatch/combine to the
                    expert all-to-all) — independent of ``tensor`` so
                    expert count and megatron splits scale separately
    - ``sequence``: sequence/context parallelism (activations sharded over
                    the length dimension; ring attention)
    - ``tensor``:   tensor (megatron-style) model parallelism

    A value of -1 means "absorb all remaining devices" (at most one axis).
    """

    data: int = -1
    fsdp: int = 1
    sequence: int = 1
    tensor: int = 1
    stage: int = 1
    expert: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {
            "stage": self.stage,
            "data": self.data,
            "fsdp": self.fsdp,
            "expert": self.expert,
            "sequence": self.sequence,
            "tensor": self.tensor,
        }


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Checkpoint/resume policy.

    The reference saves exactly once, at the end of training
    (train-accelerator.py:277-280) and has no resume path (SURVEY.md §5);
    periodic save + resume is an intentional capability add.
    """

    save_every_steps: int = 0  # 0 = only at end of training
    keep: int = 3
    resume: bool = True  # resume from latest checkpoint in output_dir if present
    async_save: bool = True


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    # --- the reference's six parameters (names + defaults from valohai.yaml:8-20) ---
    model_ckpt: str = "t5-small"
    output_dir: str = "/tmp/dllm-tpu-out"
    batch_size: int = 8  # GLOBAL batch size (split across data×fsdp×sequence hosts)
    num_epochs: int = 1
    warmup_steps: int = 500
    evaluation_steps: int = 500

    # --- optimizer (reference: AdamW lr 5e-5, linear schedule, weight_decay
    #     nominally 0.01 in variant A, train-torchrun.py:120) ---
    learning_rate: float = 5e-5
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0
    grad_accum_steps: int = 1  # reference variant A uses 16 (train-torchrun.py:126)
    label_smoothing: float = 0.0

    # --- data (reference hardcodes src 1024 / tgt 128, train-accelerator.py:115-127) ---
    max_source_length: int = 1024
    max_target_length: int = 128
    source_column: str = "dialogue"  # with "article" fallback, per reference dual schema
    target_column: str = "summary"  # with "highlights" fallback
    shuffle_seed: int = 1234  # reference DataPartitioner seed (train-task.py:46)
    pad_to_multiple: int = 128  # TPU-idiomatic version of pad_to_multiple_of=8
    prefetch_batches: int = 2  # host batches assembled ahead of the device; 0 = off

    # --- precision / memory ---
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # "" = model default; else "auto" | "flash" | "ring" | "xla" (ops/mha.py)
    attention_impl: str = ""
    # fuse LM-head + CE into a vocab-chunked scan (causal families; no
    # (tokens, vocab) fp32 logits in HBM — ops/blockwise_ce.py).  Meant
    # for data/fsdp meshes; under tensor parallelism keep it off.
    fused_ce: bool = False
    # PRNG implementation for the in-step dropout stream: "auto" (default
    # — resolves to "rbg" on TPU backends and "threefry" elsewhere at
    # trainer startup; trainer.set_prng_impl owns the resolution and the
    # resolved value is logged + stamped into BENCH json so runs stay
    # comparable), "threefry" (counter-based, bit-reproducible across
    # backends) or "rbg" (TPU hardware RNG; much cheaper mask generation
    # when dropout sits on the critical path, different — still
    # deterministic — bit stream)
    prng_impl: str = "auto"
    # dropout implementation (ops/fused_dropout.py): "auto" (default —
    # fused Pallas kernel with in-kernel RNG + seed-recompute backward on
    # TPU, XLA bernoulli elsewhere), "fused" or "xla" to force.  "fused"
    # trades bit-reproducibility with the XLA mask stream for the removal
    # of threefry mask generation AND the mask's HBM round-trips
    dropout_impl: str = "auto"
    # optimizer-apply implementation (ops/fused_optim.py): "auto" (default
    # — fused Pallas clip+AdamW kernel on TPU: one in-place pass per
    # leaf-shard with the health partial sums riding the same pass; the
    # optax chain elsewhere), "fused" or "xla" to force.  The impls run
    # the identical op sequence — equal up to XLA float contraction (a
    # few ulp on rare elements; test-pinned) — and the opt-state pytree
    # layout never changes, so checkpoints roam freely between impls.
    # Pipelined (stage>1) runs always use xla; --optim-impl fused there
    # is a composition-matrix error.
    optim_impl: str = "auto"
    # gradient-collective compression (ops/quant_collectives.py): "off"
    # (default — the compiled step is bit-identical to the uncompressed
    # path) or "int8" — the cross-replica (data-axis) gradient reduction
    # runs as block-int8 with stochastic rounding, int-safe integer
    # partial sums on an s8 wire (~4x fewer gradient wire bytes, per
    # EQuARX arXiv:2506.17615), and a per-worker fp32 error-feedback
    # tree carried in TrainState (checkpointed; resume from an
    # uncompressed checkpoint zero-fills it).  Composes with grad
    # accumulation; stage>1 pipelines and sequence parallelism are
    # composition-matrix errors.
    grad_compression: str = "off"
    remat: bool = False  # jax.checkpoint the transformer blocks
    remat_policy: str = "full"  # "full" | "dots" (utils/remat.py)
    # microbatches per pipeline tick when mesh stage>1 (0 → stage count);
    # bubble fraction is (stages-1)/(microbatches+stages-1)
    pipeline_microbatches: int = 0
    # "gpipe": forward scan + autodiff backward, O(microbatches) activation
    # memory per stage.  "1f1b": fused schedule interleaving backward with
    # forward microbatches, O(stages) activation memory — the schedule that
    # makes large microbatch counts affordable (decoder-only families).
    # "interleaved": 1f1b with pipeline_virtual_stages non-contiguous layer
    # chunks per device (parallel/interleave.py) — shorter schedule at
    # stage >= 4, ~v× more buffered chunk inputs (decoder-only families).
    # NOTE: checkpoints store the stacked blocks in the schedule's storage
    # order; resume with the same schedule/virtual-stages flags.
    pipeline_schedule: str = "gpipe"
    pipeline_virtual_stages: int = 2  # chunks per device (interleaved only)
    # MoE expert capacity override for fine-tuning (None = keep the model's
    # own setting; HF-converted Mixtral defaults to no-drop, which is exact
    # but memory-hungry — 1.25 restores the capacity trade for training)
    moe_capacity_factor: float | None = None
    # Under stage>1, generation-based ROUGE eval unstacks the blocks onto
    # the FSDP/TP shardings (params/(fsdp·tensor) per device).  On a
    # pure-stage mesh (fsdp×tensor == 1) that would mean a fully replicated
    # whole-model copy per device, so the Trainer auto-skips ROUGE there
    # regardless of this flag; the stage-sharded teacher-forced val_loss
    # (computed through the pipeline itself, no unstacking) is always
    # reported.  False skips pipelined ROUGE on every mesh.
    pipeline_eval_rouge: bool = True

    # --- eval/generation (reference live path: beams=2, max_length=128,
    #     train-accelerator.py:239-242) ---
    num_beams: int = 2
    eval_max_new_tokens: int = 128
    eval_batch_size: int = 0  # 0 = use batch_size

    # --- logging (reference cadences: 10/300/100 steps; we default to 100) ---
    log_every_steps: int = 100

    # --- observability (obs/): the layered telemetry stack ---
    # "stdout": spans/heartbeat events ride the Valohai stdout channel;
    # "jsonl": additionally tee schema-versioned records into
    # <output_dir>/obs/metrics-p{process}.jsonl and turn the gauge compile
    # on (obs_gauges=auto); "off": no obs instrumentation (the stdout
    # metric channel itself never turns off — it is the platform contract)
    obs: str = "stdout"
    # static-gauge AOT compile (MFU FLOPs + collective-traffic account):
    # "auto" = only under --obs jsonl; "on"/"off" force it
    obs_gauges: str = "auto"
    # heartbeat cadence in steps (0 = off).  Multi-host: every process
    # probes at the same global step, process 0 reports skew/laggards
    obs_heartbeat_steps: int = 0
    # persistent-laggard classification (obs/health.py LaggardStreaks):
    # a rank named laggard this many CONSECUTIVE heartbeats becomes a
    # pod-agreed host_loss_suspect event — organic host-loss DETECTION
    # only (report row; the --on-host-loss policy is unchanged).  0 =
    # classification off, same convention as the heartbeat cadence
    obs_heartbeat_suspect_beats: int = 3
    # step-time budget accounting (obs/budget.py): each logging window's
    # wall time decomposed into data_wait / dispatch / device_busy /
    # sync_block / host_overhead (additive — the unattributed remainder
    # is test-pinned under 5%) with a dispatch_efficiency gauge and the
    # off-cadence host-transfer tripwire, emitted as step_budget events.
    # "auto" = on whenever --obs is not off; under --obs jsonl the span
    # instances are also captured for the Perfetto trace export
    # (obs.report --trace).  Host-clock arithmetic only; the single
    # device interaction is one timed block at the log cadence.
    obs_budget: str = "auto"
    # MFU denominator: peak per-chip FLOP/s in TFLOP/s (v5e bf16 ≈ 197)
    obs_peak_tflops: float = 197.0
    # per-chip HBM ceiling in GiB for the bucketed memory account
    # (obs/memprof.py): the static account's fit verdict, the report's
    # --max-peak-hbm-frac / --min-hbm-headroom-gib denominators, and the
    # serving capacity gauges all divide by this one number (v5e = 16)
    hbm_budget_gib: float = 16.0

    # --- training health (obs/health.py + in-graph numerics in train/step.py) ---
    # "on": the compiled step also returns param norm, per-bucket update
    # ratios and non-finite grad counts (computed in-graph, zero extra
    # device syncs) and the anomaly watchdog consumes them at the log
    # cadence; "auto" = on under --obs jsonl; "off" = neither
    health: str = "auto"
    # what the run does when an anomaly is agreed across hosts:
    # "warn" logs obs_anomaly and continues; "halt" stops the run (no
    # extra save); "checkpoint" force-saves a resumable checkpoint, dumps
    # the flight recorder, and stops; "rewind" recovers IN-PROCESS —
    # restore the last verified checkpoint, quarantine the anomaly
    # step's batch by fingerprint so the retry skips it, escalation
    # rewind → skip-batch → halt (train/recovery.py).  Requires periodic
    # checkpointing (--save-every-steps) and the flight recorder.
    on_anomaly: str = "warn"
    # bounded in-process rewind budget for --on-anomaly rewind; once
    # exhausted the escalation continues skip-batch → halt
    max_rewinds: int = 2
    # topology-change policy (ISSUE 14): on an agreed host-loss signal
    # ("--chaos host_loss@K", or a pod-size change at resume), "reshard"
    # tears down collectives, re-initializes jax.distributed on the
    # surviving slice, rebuilds mesh/shardings/train-step, and restores
    # the newest verified checkpoint through the resharding path;
    # "halt" checkpoints the evidence and stops (restart-based recovery)
    on_host_loss: str = "reshard"
    # flight-recorder ring capacity in steps (0 = off): the last N steps'
    # metrics + batch fingerprints, dumped on anomaly/SIGTERM/crash
    recorder_steps: int = 256
    # loss-spike threshold: loss above the EWMA by this many mean
    # absolute deviations trips "loss_spike"
    health_loss_spike_factor: float = 4.0
    # grad-norm explosion threshold: grad_norm above this multiple of its
    # EWMA trips "grad_explosion"
    health_grad_norm_factor: float = 10.0
    # finite steps the EWMAs absorb before spike/explosion detection arms
    # (the NaN/Inf tripwire is always armed)
    health_warmup_steps: int = 20

    # --- chaos (obs/chaos.py): deterministic fault injection, e.g.
    #     "nan_grad@120,ckpt_corrupt@2,data_error@300,sigterm@240" —
    #     every firing is logged as a chaos_injection event so obs.report
    #     separates injected faults from organic ones ("" = off) ---
    chaos: str = ""

    # --- profiling (SURVEY.md §7 step 8: jax.profiler hooks; the reference's
    #     only "profiling" is an nvidia-smi report at startup) ---
    profile_dir: str = ""  # "" = profiling off; else write a trace here
    # legacy count ("3": trace 3 steps after the first compiled one; needs
    # profile_dir) or an absolute inclusive step window ("100:105", trace
    # dir defaults under output_dir) — obs/profile.py parses both
    profile_steps: int | str = 3
    # trigger file polled at step cadence for on-demand capture;
    # "" = <output_dir>/obs/profile.trigger when obs is enabled
    profile_trigger: str = ""
    # arm the trigger automatically when the health watchdog agrees an
    # anomaly: the next steps are profiled, so the post-mortem carries a
    # device timeline (device_account) next to the flight recorder
    profile_on_anomaly: bool = False

    # --- nested ---
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    checkpoint: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)

    # --- tokenizer: path to HF tokenizer files, or "byte" for the built-in
    #     network-free byte-level tokenizer ---
    tokenizer: str = ""  # "" = try model_ckpt as a local path, else byte

    def replace(self, **kw: Any) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)


# Single source of defaults for the CLI layer: the dataclass itself.
# remat policy names; utils/remat.py asserts its POLICIES registry matches
# (kept here so config stays importable without jax/flax)
REMAT_POLICIES = ("full", "dots")

# Speculative-decode draft cap: the verify step scores spec_tokens + 1
# positions in ONE flash_decode call, and the kernel's q block holds at
# most 8 rows (ops/flash_attention.py MAX_DECODE_Q_ROWS) — so at most 7
# drafts ride each round.  Kept here (jax-free) so the CLI layer can
# validate --spec-tokens without importing the ops stack.
SPEC_MAX_DRAFT_TOKENS = 7

_D = TrainConfig()


def add_reference_args(p: argparse.ArgumentParser) -> None:
    """The six flags of the reference CLIs (train-torchrun.py:182-188), with
    the same names surfaced by valohai.yaml:8-20."""
    p.add_argument("--model-ckpt", type=str, default=_D.model_ckpt)
    p.add_argument("--output-dir", type=str, default=_D.output_dir)
    p.add_argument("--batch-size", type=int, default=_D.batch_size)
    p.add_argument("--num-epochs", type=int, default=_D.num_epochs)
    p.add_argument("--warmup-steps", type=int, default=_D.warmup_steps)
    p.add_argument("--evaluation-steps", type=int, default=_D.evaluation_steps)


def add_tpu_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--learning-rate", type=float, default=_D.learning_rate)
    p.add_argument("--weight-decay", type=float, default=_D.weight_decay)
    p.add_argument("--max-grad-norm", type=float, default=_D.max_grad_norm)
    p.add_argument("--label-smoothing", type=float, default=_D.label_smoothing)
    p.add_argument(
        "--grad-accum-steps",
        # the reference's parameter name (train-torchrun.py:126), as
        # valohai.yaml passes it — both spellings land on grad_accum_steps
        "--gradient-accumulation-steps",
        "--gradient_accumulation_steps",
        dest="grad_accum_steps",
        type=int, default=_D.grad_accum_steps,
        help="microbatches accumulated INSIDE each compiled step (a "
             "lax.scan with fp32 accumulators sharded like the params): "
             "--batch-size stays the effective optimizer batch and must "
             "divide evenly; one optimizer apply per step regardless of N. "
             "The reference's gradient_accumulation_steps "
             "(train-torchrun.py:126). Composes with data/fsdp/tensor "
             "meshes; stage>1 pipelines microbatch via "
             "--pipeline-microbatches instead",
    )
    p.add_argument("--shuffle-seed", type=int, default=_D.shuffle_seed)
    p.add_argument("--pad-to-multiple", type=int, default=_D.pad_to_multiple)
    p.add_argument("--max-source-length", type=int, default=_D.max_source_length)
    p.add_argument("--max-target-length", type=int, default=_D.max_target_length)
    p.add_argument("--param-dtype", type=str, default=_D.param_dtype)
    p.add_argument("--compute-dtype", type=str, default=_D.compute_dtype)
    p.add_argument("--remat", action="store_true")
    p.add_argument(
        "--attention-impl", type=str, default=_D.attention_impl,
        choices=("", "auto", "flash", "ring", "xla"),
        help="attention path override; empty = model default (auto)",
    )
    p.add_argument(
        "--fused-ce", action="store_true",
        help="vocab-chunked fused LM-head + cross-entropy (causal families, "
             "data/fsdp meshes; logits never materialize)",
    )
    p.add_argument(
        "--prng-impl", type=str, default=_D.prng_impl,
        choices=("auto", "threefry", "rbg"),
        help="dropout PRNG: auto (rbg on TPU, threefry elsewhere — the "
             "resolved impl is logged), threefry (bit-reproducible) or rbg "
             "(TPU hardware RNG, faster)",
    )
    p.add_argument(
        "--dropout-impl", type=str, default=_D.dropout_impl,
        choices=("auto", "fused", "xla"),
        help="dropout path: auto (fused Pallas kernel on TPU — in-kernel "
             "RNG, no mask in HBM, seed-recompute backward; XLA elsewhere), "
             "fused or xla to force",
    )
    p.add_argument(
        "--optim-impl", type=str, default=_D.optim_impl,
        choices=("auto", "fused", "xla"),
        help="optimizer apply: auto (fused Pallas clip+AdamW kernel on TPU "
             "— one in-place pass per leaf-shard, health stats from the "
             "same pass; optax chain elsewhere), fused or xla to force. "
             "Same op sequence either way (equal up to XLA float "
             "contraction); checkpoints roam between impls",
    )
    p.add_argument(
        "--grad-compression", type=str, default=_D.grad_compression,
        choices=("off", "int8"),
        help="gradient-collective compression: off (bit-identical to the "
             "uncompressed step) or int8 — the cross-replica gradient "
             "reduction rides an s8 wire (block quantization, stochastic "
             "rounding, integer partial sums) with a checkpointed "
             "error-feedback tree; ~4x fewer gradient wire bytes "
             "(ops/quant_collectives.py)",
    )
    p.add_argument("--remat-policy", type=str, default=_D.remat_policy, choices=REMAT_POLICIES)
    p.add_argument("--pipeline-microbatches", type=int, default=_D.pipeline_microbatches)
    p.add_argument(
        "--pipeline-schedule", type=str, default=_D.pipeline_schedule,
        choices=("gpipe", "1f1b", "interleaved"),
        help="stage>1 schedule: gpipe (O(M) activation memory), 1f1b (O(S)), "
             "or interleaved (1f1b with virtual layer chunks per device)",
    )
    p.add_argument(
        "--pipeline-virtual-stages", type=int, default=_D.pipeline_virtual_stages,
        help="layer chunks per device for --pipeline-schedule interleaved",
    )
    p.add_argument("--moe-capacity-factor", type=float, default=_D.moe_capacity_factor)
    p.add_argument(
        "--no-pipeline-eval-rouge", action="store_true",
        help="under stage>1, skip the unstacked generation eval (use for models too big to replicate)",
    )
    p.add_argument("--num-beams", type=int, default=_D.num_beams)
    p.add_argument("--eval-max-new-tokens", type=int, default=_D.eval_max_new_tokens)
    p.add_argument("--eval-batch-size", type=int, default=_D.eval_batch_size)
    p.add_argument("--log-every-steps", type=int, default=_D.log_every_steps)
    p.add_argument("--tokenizer", type=str, default=_D.tokenizer)
    p.add_argument("--prefetch-batches", type=int, default=_D.prefetch_batches)
    p.add_argument("--profile-dir", type=str, default=_D.profile_dir)
    p.add_argument(
        "--profile-steps", type=str, default=str(_D.profile_steps),
        help="jax.profiler capture: step count ('3', needs --profile-dir) "
             "or absolute inclusive window ('100:105')",
    )
    p.add_argument(
        "--profile-trigger", type=str, default=_D.profile_trigger,
        help="trigger-file path polled every step for on-demand capture "
             "(default: <output-dir>/obs/profile.trigger when --obs is on)",
    )
    p.add_argument(
        "--profile-on-anomaly", action="store_true",
        default=_D.profile_on_anomaly,
        help="arm the profile trigger automatically when the health "
             "watchdog agrees an anomaly: the following steps are "
             "captured and parsed into a device_account, so the "
             "post-mortem carries a device timeline",
    )
    p.add_argument(
        "--obs", type=str, default=_D.obs, choices=("off", "stdout", "jsonl"),
        help="telemetry (obs/): stdout-only events, + JSONL file under the "
             "output dir, or off (metric stdout always stays on)",
    )
    p.add_argument(
        "--obs-gauges", type=str, default=_D.obs_gauges,
        choices=("auto", "on", "off"),
        help="AOT-compile the train step at startup for MFU FLOPs + the "
             "collective-traffic account (auto = only under --obs jsonl)",
    )
    p.add_argument("--obs-heartbeat-steps", type=int, default=_D.obs_heartbeat_steps)
    p.add_argument(
        "--obs-heartbeat-suspect-beats", type=int,
        default=_D.obs_heartbeat_suspect_beats,
        help="consecutive heartbeats a rank must be named laggard before "
             "the pod-agreed host_loss_suspect event fires (detection + "
             "report row only; --on-host-loss policy unchanged; 0 = off)",
    )
    p.add_argument(
        "--obs-budget", type=str, default=_D.obs_budget,
        choices=("auto", "on", "off"),
        help="step-time budget accounting: per-window wall time decomposed "
             "into data_wait/dispatch/device_busy/sync_block/host_overhead "
             "with a dispatch_efficiency gauge and the off-cadence "
             "host-transfer tripwire (step_budget events; under --obs jsonl "
             "also span capture for obs.report --trace).  auto = on "
             "whenever --obs is not off",
    )
    p.add_argument("--obs-peak-tflops", type=float, default=_D.obs_peak_tflops)
    p.add_argument(
        "--hbm-budget-gib", type=float, default=_D.hbm_budget_gib,
        help="per-chip HBM ceiling in GiB for the bucketed memory account "
             "(obs/memprof.py fit verdict + report memory gates; v5e = 16)",
    )
    p.add_argument(
        "--health", type=str, default=_D.health, choices=("auto", "on", "off"),
        help="in-graph numerics (param norm, per-bucket update ratios, "
             "non-finite counts) + the anomaly watchdog at the log cadence "
             "(auto = on under --obs jsonl)",
    )
    p.add_argument(
        "--on-anomaly", type=str, default=_D.on_anomaly,
        choices=("warn", "halt", "checkpoint", "rewind"),
        help="agreed-anomaly policy: warn and continue, halt the run, "
             "force-save a resumable checkpoint + flight-recorder bundle "
             "and stop, or rewind — restore the last verified checkpoint "
             "in-process, quarantine the poison batch, and retry "
             "(escalation rewind -> skip-batch -> halt; needs "
             "--save-every-steps and the flight recorder)",
    )
    p.add_argument(
        "--max-rewinds", type=int, default=_D.max_rewinds,
        help="in-process rewind budget for --on-anomaly rewind; exhausted "
             "budget escalates skip-batch -> halt",
    )
    p.add_argument(
        "--on-host-loss", type=str, default=_D.on_host_loss,
        choices=("reshard", "halt"),
        help="agreed topology-change policy: reshard — tear down "
             "collectives, re-init jax.distributed on the surviving "
             "slice, rebuild mesh/shardings/train-step and restore the "
             "newest verified checkpoint through the resharding path "
             "(needs --save-every-steps); halt — checkpoint the evidence "
             "and stop, leaving recovery to a resumed run on the new "
             "slice (the resume path reshards either way)",
    )
    p.add_argument(
        "--chaos", type=str, default=_D.chaos,
        help="deterministic fault injection: comma list of kind@tick with "
             "kind in nan_grad/ckpt_corrupt/data_error/sigterm/host_loss/"
             "oom (tick = global step; for ckpt_corrupt the Nth checkpoint "
             "save), e.g. 'nan_grad@120,ckpt_corrupt@2'; every firing is "
             "logged as a chaos_injection event",
    )
    p.add_argument(
        "--recorder-steps", type=int, default=_D.recorder_steps,
        help="flight-recorder ring capacity in steps (0 = off); dumped to "
             "<output-dir>/obs/flight-recorder-p*.json on anomaly/SIGTERM/crash",
    )
    p.add_argument(
        "--health-loss-spike-factor", type=float,
        default=_D.health_loss_spike_factor,
    )
    p.add_argument(
        "--health-grad-norm-factor", type=float,
        default=_D.health_grad_norm_factor,
    )
    p.add_argument(
        "--health-warmup-steps", type=int, default=_D.health_warmup_steps,
    )
    p.add_argument("--save-every-steps", type=int, default=_D.checkpoint.save_every_steps)
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--mesh", type=str, default="data=-1", help="comma list axis=size, e.g. data=2,fsdp=4,tensor=1")
    # multi-host rendezvous (the triple consumed at reference train-task.py:421-425)
    p.add_argument("--coordinator-address", type=str, default="")
    p.add_argument("--num-processes", type=int, default=0)
    p.add_argument("--process-id", type=int, default=-1)


def parse_mesh_arg(spec: str) -> MeshConfig:
    """Parse ``"data=2,fsdp=4"`` into a MeshConfig."""
    kw: dict[str, int] = {}
    if spec.strip():
        for part in spec.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k not in AXES:
                raise unknown_axis_error(k)
            kw[k] = int(v)
    # MeshConfig defaults data to -1 (wildcard); if the user put the wildcard
    # on a different axis, pin data to 1 so there is exactly one wildcard.
    if "data" not in kw:
        kw["data"] = 1 if -1 in kw.values() else -1
    return MeshConfig(**kw)


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    """Build a TrainConfig from an argparse namespace.

    Only attributes actually present on the namespace are applied, so the
    dataclass remains the single source of defaults (argparse defaults are
    themselves read from the dataclass above).
    """
    present = vars(args)
    fields = {f.name for f in dataclasses.fields(TrainConfig)}
    kw = {k: v for k, v in present.items() if k in fields and k not in ("mesh", "checkpoint")}
    if "mesh" in present:
        kw["mesh"] = parse_mesh_arg(present["mesh"])
    if present.get("no_pipeline_eval_rouge"):
        kw["pipeline_eval_rouge"] = False
    ckpt_kw = {}
    if "save_every_steps" in present:
        ckpt_kw["save_every_steps"] = present["save_every_steps"]
    if "no_resume" in present:
        ckpt_kw["resume"] = not present["no_resume"]
    if ckpt_kw:
        kw["checkpoint"] = CheckpointConfig(**ckpt_kw)
    cfg = TrainConfig(**kw)
    # fail at parse time, not at first compile: the batch/accumulation
    # divisibility is knowable here (the mesh-aware microbatch-vs-shards
    # check runs at Trainer startup, where the device mesh exists)
    if cfg.grad_accum_steps < 1:
        raise ValueError(
            f"--grad-accum-steps must be >= 1, got {cfg.grad_accum_steps}"
        )
    if cfg.batch_size % cfg.grad_accum_steps:
        raise ValueError(
            f"--batch-size {cfg.batch_size} is not divisible by "
            f"--grad-accum-steps {cfg.grad_accum_steps}: batch-size is the "
            "EFFECTIVE optimizer batch; the step cuts it into "
            "grad-accum-steps equal microbatches"
        )
    # rewind recovery has hard prerequisites — surface them at parse time
    # with a fix-it, not as a mid-run halt the first time an anomaly fires
    if cfg.max_rewinds < 0:
        raise ValueError(f"--max-rewinds must be >= 0, got {cfg.max_rewinds}")
    if cfg.on_anomaly == "rewind":
        if cfg.checkpoint.save_every_steps <= 0:
            raise ValueError(
                "--on-anomaly rewind needs periodic checkpointing to rewind "
                "TO: set --save-every-steps N (N bounds the optimizer steps "
                "one recovery can lose)"
            )
        if cfg.recorder_steps <= 0:
            raise ValueError(
                "--on-anomaly rewind quarantines the poison batch via the "
                "flight recorder's fingerprints: set --recorder-steps N "
                "(default 256) instead of 0"
            )
    if cfg.chaos:
        # grammar errors fail here, not at injection time mid-run
        from distributed_llms_example_tpu.obs.chaos import parse_chaos

        schedule = parse_chaos(cfg.chaos)
        if (
            schedule.armed_at("host_loss")
            and cfg.on_host_loss == "reshard"
            and cfg.checkpoint.save_every_steps <= 0
        ):
            raise ValueError(
                "--chaos host_loss@K with --on-host-loss reshard needs a "
                "checkpoint to reshard FROM: set --save-every-steps N "
                "(a lost host's state is gone — topology recovery is a "
                "restore, not a migration)"
            )
    return cfg
