"""Tokenizers.

The reference downloads ``AutoTokenizer.from_pretrained(model_ckpt)`` from
the HF hub (reference train-torchrun.py:34).  This framework runs in
zero-egress environments, so tokenization is pluggable:

- ``HFTokenizer`` wraps a tokenizer loaded from *local* files (a checkpoint
  directory shipped as a platform input, the same mechanism the reference
  uses for datasets);
- ``ByteTokenizer`` is a dependency-free byte-level fallback (UTF-8 bytes
  shifted past the special ids) that makes every pipeline runnable and
  testable with no assets at all.
"""

from __future__ import annotations

import contextlib as _contextlib
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    """Role-based encoding: the SPECIAL-TOKEN LAYOUT is the tokenizer's
    job, not the dataset's.  Each model family lays out sequences its own
    way (BART ``<s>…</s>``, T5 ``…</s>``, LLaMA ``<s>…``), and a
    home-grown "append one EOS" convention silently mismatches the
    pretraining format when fine-tuning real checkpoints — so datasets ask
    for ids by ROLE and the tokenizer applies the family's layout."""

    vocab_size: int
    pad_id: int
    eos_id: int

    def encode(self, text: str) -> list[int]:
        """Plain content ids — no special tokens, no truncation."""
        ...

    def encode_source(self, text: str, max_length: int) -> list[int]:
        """Seq2seq encoder input, family layout applied, ≤ max_length."""
        ...

    def encode_target(self, text: str, max_length: int) -> list[int]:
        """Seq2seq decoder labels, family layout applied, ≤ max_length."""
        ...

    def encode_prompt(self, text: str, max_length: int) -> list[int]:
        """Causal-LM prompt prefix (loss-masked): leading specials only."""
        ...

    def encode_continuation(self, text: str, max_length: int) -> list[int]:
        """Causal-LM continuation: content + end-of-sequence, no BOS."""
        ...

    def decode(self, ids: Sequence[int]) -> str: ...

    def encode_source_batch(self, texts: Sequence[str], max_length: int) -> list[list[int]]:
        """Batch form of ``encode_source`` — id-identical, but tokenizers
        with a parallel batch path (HF fast tokenizers: Rust + rayon
        across all cores) encode the whole list at once.  One prefetch
        thread tokenizing example-by-example caps out near 200k tok/s —
        well short of the ~480k tok/s a v5e-8 host must assemble — so the
        datasets fill their caches through this entry point per batch."""
        ...

    def encode_target_batch(self, texts: Sequence[str], max_length: int) -> list[list[int]]: ...


class ByteTokenizer:
    """UTF-8 bytes + {pad=0, eos=1}; ids are byte+2.  Its "family layout"
    is the framework's own: sources/targets end in one EOS, prompts carry
    no specials at all."""

    OFFSET = 2

    def __init__(self) -> None:
        self.pad_id = 0
        self.eos_id = 1
        self.vocab_size = 256 + self.OFFSET

    def encode(self, text: str) -> list[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def encode_source(self, text: str, max_length: int) -> list[int]:
        return self.encode(text)[: max_length - 1] + [self.eos_id]

    encode_target = encode_source
    encode_continuation = encode_source

    def encode_prompt(self, text: str, max_length: int) -> list[int]:
        return self.encode(text)[:max_length]

    def encode_source_batch(self, texts: Sequence[str], max_length: int) -> list[list[int]]:
        # byte encoding is memory-bandwidth work; a plain loop already
        # clears the pod-host feed rate with >10x margin (bench.py host-input)
        return [self.encode_source(t, max_length) for t in texts]

    encode_target_batch = encode_source_batch

    def decode(self, ids: Sequence[int]) -> str:
        # ids outside [OFFSET, OFFSET+256) are skipped, not an error: models
        # may have a larger vocab than the tokenizer (padded/rounded vocab
        # sizes), and randomly-initialized models emit arbitrary ids
        data = bytes(i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256)
        return data.decode("utf-8", errors="replace")


@_contextlib.contextmanager
def _rust_parallelism():
    """Enable the Rust tokenizer's rayon parallelism for the duration of
    ONE batch call.  Setting TOKENIZERS_PARALLELISM=true process-wide
    would also disable the library's fork-detected auto-shutoff — a
    forked child (e.g. an embedder's fork-based multiprocessing) could
    then deadlock on the poisoned rayon pool.  Scoping the variable to
    the call keeps the batch path parallel AND the safety net intact; an
    explicit user setting (either value) always wins."""
    import os

    if os.environ.get("TOKENIZERS_PARALLELISM") is not None:
        yield
        return
    os.environ["TOKENIZERS_PARALLELISM"] = "true"
    try:
        yield
    finally:
        os.environ.pop("TOKENIZERS_PARALLELISM", None)


class HFTokenizer:
    """A Hugging Face tokenizer loaded from a local directory.

    Layout-bearing roles delegate to the HF tokenizer itself — its
    post-processor IS the family's special-token layout (BART's
    ``<s>…</s>``, T5's ``…</s>``, LLaMA's BOS-only), and HF truncation
    keeps the trailing specials — so ids match
    ``AutoTokenizer.__call__(text, max_length=…, truncation=True)``
    exactly (the reference recipe, reference train-accelerator.py:114-133;
    parity test: tests/test_tokenizer_parity.py)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id if self._tok.pad_token_id is not None else 0
        # _has_eos gates EOS-aware layout edits below: when the loaded
        # tokenizer defines no eos_token, the fallback id 1 is just an
        # ordinary vocab token and must be neither stripped nor appended
        self._has_eos = self._tok.eos_token_id is not None
        self.eos_id = self._tok.eos_token_id if self._has_eos else 1

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def encode_source(self, text: str, max_length: int) -> list[int]:
        return self._tok(text, max_length=max_length, truncation=True)["input_ids"]

    def encode_target(self, text: str, max_length: int) -> list[int]:
        # text_target routes through the target-side post-processor (for
        # BART/T5 identical to the source side; kept distinct for families
        # where it differs) — the reference's `text_target=` call path
        return self._tok(text_target=text, max_length=max_length, truncation=True)["input_ids"]

    def encode_prompt(self, text: str, max_length: int) -> list[int]:
        # a causal prompt keeps its leading specials (LLaMA's BOS) but must
        # NOT end the document — strip any trailing EOS the layout added
        ids = self._tok(text, max_length=max_length, truncation=True)["input_ids"]
        while self._has_eos and ids and ids[-1] == self.eos_id:
            ids = ids[:-1]
        return ids

    def encode_continuation(self, text: str, max_length: int) -> list[int]:
        # continuation of an already-started document: content ids only
        # (a BOS here would be a mid-sequence document restart) + EOS
        ids = self._tok.encode(text, add_special_tokens=False)
        if not self._has_eos:
            return ids[:max_length]
        return ids[: max_length - 1] + [self.eos_id]

    def encode_source_batch(self, texts: Sequence[str], max_length: int) -> list[list[int]]:
        # one call into the Rust tokenizer: rayon fans the batch across
        # cores and the ids are exactly the per-text encode_source ids
        with _rust_parallelism():
            return self._tok(list(texts), max_length=max_length, truncation=True)["input_ids"]

    def encode_target_batch(self, texts: Sequence[str], max_length: int) -> list[list[int]]:
        with _rust_parallelism():
            return self._tok(
                text_target=list(texts), max_length=max_length, truncation=True
            )["input_ids"]

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode([i for i in ids], skip_special_tokens=True)


def get_tokenizer(spec: str, model_ckpt: str = "") -> Tokenizer:
    """Resolve a tokenizer spec: explicit path > model checkpoint dir > byte."""
    import os

    if spec and spec != "byte":
        return HFTokenizer(spec)
    if spec == "byte":
        return ByteTokenizer()
    if model_ckpt and os.path.isdir(model_ckpt):
        try:
            return HFTokenizer(model_ckpt)
        except Exception:
            pass
    return ByteTokenizer()
