"""Tokenizers.

The reference downloads ``AutoTokenizer.from_pretrained(model_ckpt)`` from
the HF hub (reference train-torchrun.py:34).  This framework runs in
zero-egress environments, so tokenization is pluggable:

- ``HFTokenizer`` wraps a tokenizer loaded from *local* files (a checkpoint
  directory shipped as a platform input, the same mechanism the reference
  uses for datasets);
- ``ByteTokenizer`` is a dependency-free byte-level fallback (UTF-8 bytes
  shifted past the special ids) that makes every pipeline runnable and
  testable with no assets at all.
"""

from __future__ import annotations

from typing import Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    pad_id: int
    eos_id: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes + {pad=0, eos=1}; ids are byte+2."""

    OFFSET = 2

    def __init__(self) -> None:
        self.pad_id = 0
        self.eos_id = 1
        self.vocab_size = 256 + self.OFFSET

    def encode(self, text: str) -> list[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # ids outside [OFFSET, OFFSET+256) are skipped, not an error: models
        # may have a larger vocab than the tokenizer (padded/rounded vocab
        # sizes), and randomly-initialized models emit arbitrary ids
        data = bytes(i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """A Hugging Face tokenizer loaded from a local directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.pad_id = self._tok.pad_token_id if self._tok.pad_token_id is not None else 0
        self.eos_id = self._tok.eos_token_id if self._tok.eos_token_id is not None else 1

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode([i for i in ids], skip_special_tokens=True)


def get_tokenizer(spec: str, model_ckpt: str = "") -> Tokenizer:
    """Resolve a tokenizer spec: explicit path > model checkpoint dir > byte."""
    import os

    if spec and spec != "byte":
        return HFTokenizer(spec)
    if spec == "byte":
        return ByteTokenizer()
    if model_ckpt and os.path.isdir(model_ckpt):
        try:
            return HFTokenizer(model_ckpt)
        except Exception:
            pass
    return ByteTokenizer()
