"""JSON summarization datasets + deterministic partitioning.

Parity targets in the reference:

- ``load_dataset('json', data_files={train,val})`` over ``train.json`` /
  ``val.json`` placed next to the first Valohai input file
  (reference train-torchrun.py:153-159) — here a plain loader that accepts
  a JSON array, a JSONL file, or a {"data": [...]} wrapper;
- the dual column schema: the live path reads ``dialogue``/``summary``
  (train-task.py:158,164) while the dead eval path reads
  ``article``/``highlights`` (train-task.py:125-126) — here both are
  accepted, in that order;
- ``DataPartitioner`` (train-task.py:45-62): seed-1234 shuffled index
  split by fractional sizes with ``.use(rank)`` — re-implemented as a pure
  function, plus the epoch-aware per-host sampler the reference lacks
  (its variant C re-uses one fixed shard forever and every rank loads the
  whole file, train-task.py:373-380).

JSONL files are parsed by the C++ loader in ``native/`` (compiled on
demand; parse + string-unescape happen outside the interpreter and records
materialize lazily); the pure-Python ``json.loads`` path below is the
always-available fallback with identical semantics and also handles the
non-JSONL layouts (JSON array, {"data": [...]} wrapper).
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Iterator, Sequence

import numpy as np

from distributed_llms_example_tpu.data.tokenizer import Tokenizer

SOURCE_COLUMNS = ("dialogue", "article", "document", "text")
TARGET_COLUMNS = ("summary", "highlights", "target")


DATA_READ_RETRIES = 3  # transient-I/O retry budget for load_json_records


def load_json_records(
    path: str, *, retries: int = DATA_READ_RETRIES, backoff_s: float = 0.1
) -> Sequence[dict]:
    """Load a JSON array / JSONL / {"data": [...]} file into records.

    JSONL goes through the native C++ loader when it is available (returns
    a lazy zero-copy sequence); anything the native parser rejects — and
    the non-line-delimited layouts — takes the Python path.

    Robustness (ISSUE 6): transient read errors (a flaky NFS/GCS mount
    mid-preemption-storm) retry with capped exponential backoff instead
    of killing the run at startup; malformed JSONL lines are skipped with
    a counter surfaced as a ``data_skipped_records`` event instead of
    killing the epoch — one corrupt line in a million-record corpus is a
    data bug to report, not a reason to lose the pod reservation."""
    from distributed_llms_example_tpu.utils.backoff import sleep_backoff

    delay = float(backoff_s)
    for attempt in range(max(0, retries) + 1):
        try:
            return _read_json_records(path)
        except (FileNotFoundError, PermissionError, IsADirectoryError,
                NotADirectoryError):
            raise  # permanent: a typo'd path must fail fast, not "retry"
        except OSError as e:
            if attempt == retries:
                raise
            from distributed_llms_example_tpu.utils.jsonlog import log_json

            log_json({
                "event": "data_retry",
                "path": path,
                "attempt": attempt + 1,
                "backoff_s": round(delay, 3),
                "error": str(e)[:200],
            })
            delay = sleep_backoff(delay, cap_s=2.0)
    raise AssertionError("unreachable")


def _read_json_records(path: str) -> Sequence[dict]:
    import os

    from distributed_llms_example_tpu import native

    with open(path, "r", encoding="utf-8") as f:
        head = f.read(1)
        f.seek(0)
        use_native = (
            head == "{"
            # env check first: opting out must not trigger the g++ build
            and os.environ.get("DLLM_NATIVE_JSONL", "1") != "0"
            and native.available()
        )
        if use_native:
            try:
                recs = native.load_jsonl(path)
            except ValueError:
                pass  # multi-line object / data-wrapper / bad line → Python path
            else:
                if len(recs) == 1:
                    only = recs[0]  # materialize once: json.loads runs on access
                    if isinstance(only.get("data"), list):
                        return only["data"]  # single-line {"data": [...]} wrapper
                return recs
        if head == "[":
            return json.load(f)
        if head == "{":
            records: list[dict] = []
            skipped = 0
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    skipped += 1
                    continue
                if not isinstance(rec, dict):
                    skipped += 1  # a bare scalar/array line is not a record
                    continue
                records.append(rec)
            if skipped:
                # unparseable lines: either this is really a pretty-printed
                # single JSON document (not JSONL at all — parse it whole)
                # or a JSONL file with corrupt lines (skip them, loudly)
                f.seek(0)
                try:
                    whole = json.load(f)
                except json.JSONDecodeError:
                    pass  # genuinely line-delimited with bad lines
                else:
                    if isinstance(whole, dict) and isinstance(whole.get("data"), list):
                        return whole["data"]
                    return [whole]
                if not records:
                    raise ValueError(f"{path}: no parseable JSON records")
                from distributed_llms_example_tpu.utils.jsonlog import log_json

                log_json({
                    "event": "data_skipped_records",
                    "path": path,
                    "skipped": skipped,
                    "kept": len(records),
                })
            if len(records) == 1 and isinstance(records[0].get("data"), list):
                return records[0]["data"]
            return records
        raise ValueError(f"{path}: not a JSON array, JSONL, or data-wrapper file")


def resolve_columns(record: dict, source_column: str = "", target_column: str = "") -> tuple[str, str]:
    """Pick (source, target) column names, honoring explicit config first."""
    src = source_column if source_column in record else next((c for c in SOURCE_COLUMNS if c in record), None)
    tgt = target_column if target_column in record else next((c for c in TARGET_COLUMNS if c in record), None)
    if src is None or tgt is None:
        raise ValueError(
            f"cannot find source/target columns in record keys {sorted(record)}; "
            f"expected one of {SOURCE_COLUMNS} and {TARGET_COLUMNS}"
        )
    return src, tgt


def partition_indices(n: int, sizes: Sequence[float], seed: int = 1234) -> list[list[int]]:
    """Reference ``DataPartitioner`` semantics (train-task.py:45-62): seeded
    shuffle, fractional split; partition k is ``use(k)``."""
    idx = list(range(n))
    random.Random(seed).shuffle(idx)
    out: list[list[int]] = []
    start = 0
    for frac in sizes:
        take = int(frac * n)
        out.append(idx[start : start + take])
        start += take
    return out


@dataclasses.dataclass
class Example:
    input_ids: list[int]
    labels: list[int]


class SummarizationDataset:
    """Summarization examples, tokenized LAZILY with truncation (no padding
    here — padding is the batcher's job so shapes can be bucketed).

    The reference tokenizes the entire corpus up front on every rank
    (``dataset.map`` before the loop, train-accelerator.py:144-153); round-1
    of this framework copied that in ``__init__``, serializing minutes of
    host work before step 1.  Tokenization now happens on first access per
    example (memoized), so startup cost is one batch and the rest overlaps
    training via the prefetcher."""

    def __init__(
        self,
        records: Sequence[dict],
        tokenizer: Tokenizer,
        *,
        max_source_length: int = 1024,
        max_target_length: int = 128,
        source_column: str = "",
        target_column: str = "",
    ):
        self.tokenizer = tokenizer
        self._records = records
        self._max_source_length = max_source_length
        self._max_target_length = max_target_length
        self._cache: list[Example | None] = [None] * len(records)
        if records:
            self._src_col, self._tgt_col = resolve_columns(
                dict(records[0]), source_column, target_column
            )

    def __len__(self) -> int:
        return len(self._records)

    def ensure_encoded(self, indices: Sequence[int]) -> None:
        """Fill the cache for ``indices`` with ONE batch tokenizer call.

        Per-example encoding caps a pod host's feed rate (bench.py
        host-input: ~200k tok/s single-stream HF vs the ~480k a v5e-8
        needs); the batch entry points let the Rust tokenizer fan the
        work across cores.  ``__getitem__`` stays the correctness path —
        ids are identical either way (tests/test_data.py)."""
        todo = [j for j in (int(i) for i in indices) if self._cache[j] is None]
        if not todo:
            return
        srcs = [str(self._records[j][self._src_col]) for j in todo]
        tgts = [str(self._records[j][self._tgt_col]) for j in todo]
        src_ids = self.tokenizer.encode_source_batch(srcs, self._max_source_length)
        tgt_ids = self.tokenizer.encode_target_batch(tgts, self._max_target_length)
        for j, s, t in zip(todo, src_ids, tgt_ids):
            self._cache[j] = Example(s, t)

    def clear_cache(self) -> None:
        """Drop memoized encodings (benchmarks re-timing cold tokenization)."""
        self._cache = [None] * len(self._records)

    def __getitem__(self, i: int) -> Example:
        ex = self._cache[i]
        if ex is None:
            r = self._records[i]
            # special-token layout (BART <s>…</s>, T5 …</s>) is the
            # tokenizer's job — see Tokenizer protocol
            src = self.tokenizer.encode_source(str(r[self._src_col]), self._max_source_length)
            tgt = self.tokenizer.encode_target(str(r[self._tgt_col]), self._max_target_length)
            ex = self._cache[i] = Example(src, tgt)
        return ex


@dataclasses.dataclass
class CausalExample:
    input_ids: list[int]  # prompt + target (+ eos)
    labels: list[int]  # -100 over the prompt, target ids over the target
    prompt_ids: list[int]
    target_ids: list[int]


class CausalLMDataset:
    """Instruction-tuning examples for decoder-only models (BASELINE.json
    config 5: llama-2-7b causal-LM fine-tune): source and target are
    concatenated, the loss is masked over the prompt."""

    def __init__(
        self,
        records: Sequence[dict],
        tokenizer: Tokenizer,
        *,
        max_length: int = 1024,
        max_target_length: int = 256,
        source_column: str = "",
        target_column: str = "",
    ):
        self.tokenizer = tokenizer
        self._records = records
        self._max_length = max_length
        self._max_target_length = max_target_length
        self._cache: list[CausalExample | None] = [None] * len(records)
        if records:
            self._src_col, self._tgt_col = resolve_columns(
                dict(records[0]), source_column, target_column
            )

    def __len__(self) -> int:
        return len(self._records)

    def ensure_encoded(self, indices: Sequence[int]) -> None:
        """Uniform batch-fill hook (see SummarizationDataset).  The causal
        layout couples each prompt's budget to its continuation's length
        (max_prompt below), so this stays a loop — instruction-tuning
        prompts are one-tenth the summarization corpus volume and the
        per-example path already clears the feed rate."""
        for i in indices:
            self[int(i)]

    def clear_cache(self) -> None:
        """Drop memoized encodings (benchmarks re-timing cold tokenization)."""
        self._cache = [None] * len(self._records)

    def __getitem__(self, i: int) -> CausalExample:
        ex = self._cache[i]
        if ex is None:
            r = self._records[i]
            # layout via the tokenizer: the prompt keeps its leading
            # specials (LLaMA's BOS) and the continuation ends in EOS
            tgt = self.tokenizer.encode_continuation(
                str(r[self._tgt_col]), self._max_target_length
            )
            max_prompt = max(1, self._max_length - len(tgt))
            src = self.tokenizer.encode_prompt(str(r[self._src_col]), max_prompt)
            ex = self._cache[i] = CausalExample(src + tgt, [-100] * len(src) + tgt, src, tgt)
        return ex


def epoch_order(n: int, *, seed: int, epoch: int, shuffle: bool = True) -> np.ndarray:
    """Deterministic global example order for an epoch — identical on every
    host (the multi-host determinism the reference ducks, SURVEY.md §7
    hard-part 3)."""
    if not shuffle:
        return np.arange(n)
    rng = np.random.RandomState(seed + epoch)
    return rng.permutation(n)


def host_batch_slices(global_batch: int, process_count: int, process_index: int) -> slice:
    """The contiguous slice of each global batch this host materializes."""
    if global_batch % process_count != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {process_count} processes")
    per = global_batch // process_count
    return slice(process_index * per, (process_index + 1) * per)


def iter_global_batches(
    n: int,
    global_batch: int,
    *,
    seed: int,
    epoch: int,
    shuffle: bool = True,
    drop_last: bool = True,
) -> Iterator[np.ndarray]:
    """Yield index arrays of exactly ``global_batch`` per step, same on all
    hosts.  With ``drop_last=False`` the final short batch wraps around to
    the epoch start so shapes stay fixed (no recompilation)."""
    order = epoch_order(n, seed=seed, epoch=epoch, shuffle=shuffle)
    steps, rem = divmod(n, global_batch)
    for s in range(steps):
        yield order[s * global_batch : (s + 1) * global_batch]
    if rem and not drop_last:
        tail = order[steps * global_batch :]
        # np.resize cycles the order, so the batch is exactly global_batch
        # even when the corpus itself is smaller than one batch
        yield np.concatenate([tail, np.resize(order, global_batch - rem)])
