"""Fixed-shape bucketed batching for TPU.

The reference pads everything to ``max_length`` at tokenize time
(train-accelerator.py:115-127, ``padding="max_length"``) — simple but
wasteful: a 60-token dialogue burns a 1024-wide matmul row.  The dynamic
padding of its ``DataCollatorForSeq2Seq`` (train-accelerator.py:155-159)
is the other extreme and would recompile XLA programs at every new shape.

The TPU-idiomatic middle ground: pad each batch to the smallest multiple
of ``bucket_multiple`` that fits the longest example in the *global* batch
(capped at the configured max).  The bucket is a deterministic function of
the global batch, so every host picks the same shape, and the number of
distinct compiled programs is bounded by max_len / bucket_multiple.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from distributed_llms_example_tpu.data.dataset import (
    SummarizationDataset,
    host_batch_slices,
    iter_global_batches,
)

LABEL_PAD = -100  # loss-mask value, parity with HF label padding


def microbatch_size(
    global_batch: int,
    grad_accum_steps: int,
    *,
    batch_shards: int = 1,
    process_count: int = 1,
) -> int:
    """Validate the (global batch, accumulation, sharding) triple and
    return the microbatch size.

    One iterator batch = one optimizer step, ALWAYS — ``grad_accum_steps``
    never changes the epoch/resume iterator contract (the step counter,
    checkpoints, and O(1) resume all count optimizer steps; the compiled
    step regroups the batch into microbatches internally).  What it does
    change is the divisibility the regrouping needs:

    - ``global_batch % grad_accum_steps``: the reshape that cuts the
      microbatches;
    - ``microbatch % batch_shards``: each microbatch's rows must split
      evenly over the (data, fsdp, expert) axes, or the shard-local
      regrouping degrades into a per-step GSPMD reshard;
    - ``global_batch % process_count``: each host materializes its slice
      of every optimizer batch (unchanged from accum=1, re-checked here
      so the error names the accumulation config).
    """
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if global_batch % grad_accum_steps:
        raise ValueError(
            f"global batch {global_batch} is not divisible by "
            f"grad_accum_steps={grad_accum_steps}"
        )
    micro = global_batch // grad_accum_steps
    if micro % max(1, batch_shards):
        raise ValueError(
            f"microbatch {micro} (batch {global_batch} / grad_accum_steps "
            f"{grad_accum_steps}) is not divisible by the mesh's "
            f"{batch_shards} batch shards (data x fsdp x expert) — the "
            "shard-local microbatch regrouping needs every microbatch to "
            "split evenly over the batch axes"
        )
    if global_batch % max(1, process_count):
        raise ValueError(
            f"global batch {global_batch} is not divisible by "
            f"{process_count} processes"
        )
    return micro


def validate_batch_mesh(
    global_batch: int,
    mesh_axes: dict,
    *,
    process_count: int = 1,
    grad_accum_steps: int = 1,
) -> None:
    """Re-validate the batch-plan divisibilities against a (possibly
    NEW) mesh — the topology-change path's precondition check (ISSUE
    14): the global batch is PRESERVED across a reshard (that is what
    keeps the loss trajectory comparable), so the new factorization must
    still divide it.  Raises with the failing triple named; a passing
    call means the re-derived batch plan slices cleanly on every
    surviving host.  (The grad-compression worker regrouping needs no
    extra check here: the worker axes are a subset of the batch-shard
    axes, so ``microbatch % shards == 0`` already implies the per-worker
    split divides.)"""
    shards = 1
    for ax in ("data", "fsdp", "expert"):
        shards *= max(1, int(mesh_axes.get(ax, 1) or 1))
    # microbatch_size covers batch % accum, microbatch % shards and
    # batch % processes with the accumulation named in each error
    microbatch_size(
        global_batch,
        max(1, grad_accum_steps),
        batch_shards=shards,
        process_count=max(1, process_count),
    )


def bucket_len(max_len_in_batch: int, multiple: int, cap: int) -> int:
    b = ((max(1, max_len_in_batch) + multiple - 1) // multiple) * multiple
    return min(b, cap)


def pad_2d(seqs: Sequence[Sequence[int]], width: int, pad_value: int) -> np.ndarray:
    out = np.full((len(seqs), width), pad_value, dtype=np.int32)
    for i, s in enumerate(seqs):
        s = list(s)[:width]
        out[i, : len(s)] = s
    return out


def make_batch(
    ds: SummarizationDataset,
    idx: np.ndarray,
    *,
    pad_id: int,
    bucket_multiple: int = 128,
    max_source_length: int = 1024,
    max_target_length: int = 128,
) -> dict[str, np.ndarray]:
    """Assemble one (host-local or global) batch at bucketed fixed shapes."""
    ex = [ds[int(i)] for i in idx]
    src_w = bucket_len(max(len(e.input_ids) for e in ex), bucket_multiple, max_source_length)
    tgt_w = bucket_len(max(len(e.labels) for e in ex), min(bucket_multiple, max_target_length), max_target_length)
    input_ids = pad_2d([e.input_ids for e in ex], src_w, pad_id)
    attention_mask = (input_ids != pad_id).astype(np.int32)
    # pad_id may legitimately appear inside a sequence (byte tokenizer never
    # emits it, HF pad ids don't occur mid-sequence) — mask from lengths instead
    for i, e in enumerate(ex):
        attention_mask[i, : min(len(e.input_ids), src_w)] = 1
    labels = pad_2d([e.labels for e in ex], tgt_w, LABEL_PAD)
    return {"input_ids": input_ids, "attention_mask": attention_mask, "labels": labels}


class BatchIterator:
    """Per-epoch iterator over host-local batches with global determinism.

    Every host iterates the same global index stream; each materializes only
    its slice (global_batch / process_count examples), but computes the
    bucket from the full global batch so shapes agree across hosts.
    """

    def __init__(
        self,
        ds: SummarizationDataset,
        *,
        global_batch: int,
        process_count: int = 1,
        process_index: int = 0,
        seed: int = 1234,
        shuffle: bool = True,
        drop_last: bool = True,
        bucket_multiple: int = 128,
        max_source_length: int = 1024,
        max_target_length: int = 128,
    ):
        self.ds = ds
        self.global_batch = global_batch
        self.process_count = process_count
        self.process_index = process_index
        self.seed = seed
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.bucket_multiple = bucket_multiple
        self.max_source_length = max_source_length
        self.max_target_length = max_target_length
        self._slice = host_batch_slices(global_batch, process_count, process_index)

    def steps_per_epoch(self) -> int:
        steps, rem = divmod(len(self.ds), self.global_batch)
        return steps + (1 if rem and not self.drop_last else 0)

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[dict[str, np.ndarray]]:
        """Iterator over the host's batches for one epoch, optionally
        starting at ``start_step`` (in-epoch resume).

        The batch plan is a deterministic function of (seed, epoch), so
        skipping happens on the INDEX lists before any tokenization —
        resuming at step N costs O(1) per skipped batch, not N batch
        assemblies (round-4 fast-forwarded by assembling and discarding).
        Multi-host: every host passes the same ``start_step`` (the step
        counter agrees by construction), so the per-epoch width-agreement
        allgather still sees identical shapes everywhere.

        Multi-host: an eager pass (on the caller's thread, NOT under the
        prefetcher) tokenizes the host's 1/P slice to get per-batch length
        maxima, then ONE ``process_allgather`` per epoch agrees on bucket
        widths.  Round 2 computed widths from the *global* index list,
        which tokenized the entire corpus on every host (the per-rank
        duplication SURVEY.md §7 hard-part 3 warns about); now each host
        touches only its own slice.  The agreement collective runs on the
        main thread at the epoch boundary, never on the prefetch thread
        (background-thread collectives could interleave differently across
        hosts and deadlock the runtime) and never on the step critical
        path.  Single-process: widths come lazily per batch (no agreement
        needed), so first-epoch tokenization overlaps device steps under
        the prefetcher."""
        batches = list(
            iter_global_batches(
                len(self.ds),
                self.global_batch,
                seed=self.seed,
                epoch=epoch,
                shuffle=self.shuffle,
                drop_last=self.drop_last,
            )
        )
        if start_step:
            batches = batches[start_step:]
        import jax

        if self.process_count > 1 and jax.process_count() > 1:  # pod-agreed: pod-uniform guard; the branch body is the once-per-epoch agreement allgather every rank joins
            # Real multi-host: eager local maxima (tokenizes only this
            # host's 1/P slice; memoized in the dataset so the cost is
            # once per run), then ONE agreement allgather per epoch on the
            # caller's thread.
            maxima = np.zeros((len(batches), 2), np.int32)
            for s, global_idx in enumerate(batches):
                self.ds.ensure_encoded(global_idx[self._slice])
                ex = [self.ds[int(i)] for i in global_idx[self._slice]]
                maxima[s, 0] = max(len(e.input_ids) for e in ex)
                maxima[s, 1] = max(len(e.labels) for e in ex)
            from jax.experimental import multihost_utils

            gathered = np.asarray(multihost_utils.process_allgather(maxima))
            maxima = np.max(gathered.reshape(-1, *maxima.shape), axis=0)
            return self._iter_batches(batches, iter(maxima))
        # Single process needs no cross-host agreement: stay LAZY so
        # first-epoch tokenization overlaps device steps under the
        # prefetcher instead of serializing at epoch start.  Simulated
        # multi-host (tests build P iterators in ONE process and drain
        # them sequentially) has no peers to gather from: scan the global
        # index list per batch — same widths, test-only cost.
        rows = slice(None) if self.process_count > 1 else self._slice

        def maxima_lazy():
            for global_idx in batches:
                # batch-fill the cache BEFORE the per-example length scan:
                # one Rust-parallel tokenizer call per batch instead of a
                # Python loop of singles (the pod-host feed-rate fix,
                # bench.py host-input)
                self.ds.ensure_encoded(global_idx[rows])
                yield (
                    max(len(self.ds[int(i)].input_ids) for i in global_idx[rows]),
                    max(len(self.ds[int(i)].labels) for i in global_idx[rows]),
                )

        return self._iter_batches(batches, maxima_lazy())

    def _iter_batches(
        self, batches: list[np.ndarray], maxima: Iterator[tuple[int, int]]
    ) -> Iterator[dict[str, np.ndarray]]:
        pad_id = self.ds.tokenizer.pad_id
        for global_idx, (src_max, tgt_max) in zip(batches, maxima):
            src_w = bucket_len(int(src_max), self.bucket_multiple, self.max_source_length)
            tgt_w = bucket_len(
                int(tgt_max), min(self.bucket_multiple, self.max_target_length), self.max_target_length
            )
            ex = [self.ds[int(i)] for i in global_idx[self._slice]]
            input_ids = pad_2d([e.input_ids for e in ex], src_w, pad_id)
            attention_mask = np.zeros_like(input_ids)
            for i, e in enumerate(ex):
                attention_mask[i, : min(len(e.input_ids), src_w)] = 1
            labels = pad_2d([e.labels for e in ex], tgt_w, LABEL_PAD)
            yield {"input_ids": input_ids, "attention_mask": attention_mask, "labels": labels}
