"""Background-thread batch prefetching.

SURVEY.md §7 hard-part 7: hitting the throughput target needs input work
(tokenize, pad, bucket) overlapped with device steps — the reference builds
every batch on the critical path between optimizer steps (its DataLoaders
run with default num_workers=0).  A thread is the right tool here: batch
assembly is numpy/tokenizer work that releases the GIL for its hot parts,
and the consumer blocks in XLA dispatch anyway.

``Prefetcher`` wraps any iterator: a daemon thread fills a bounded queue
``depth`` items ahead; producer exceptions re-raise in the consumer at the
point of failure; early consumer exit (``close()``, GC, or ``with``) stops
the producer promptly instead of leaking the thread on an unbounded queue.

``stats()`` reports how much the consumer actually BLOCKED on the queue
(plus items moved): the per-run answer to "is the input pipeline on the
critical path?".  BENCH_r05 measured prefetch depth 2 ≈ depth 0 on the
trainer loop — the spans showed the loop is device-bound at those shapes
(batch assembly is ~2% of a 400 ms step, so there is nothing for the
thread to hide); these counters are what proves that cheaply, per run,
without a profiler (tests/test_prefetch.py pins both directions).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Iterable, Iterator

_DONE = object()


class Prefetcher:
    def __init__(self, it: Iterable, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: BaseException | None = None
        self._finished = False  # latched: never block on the queue again
        self._items = 0  # items handed to the consumer
        self._wait_s = 0.0  # wall time the consumer spent blocked on get()
        self._thread = threading.Thread(target=self._fill, args=(iter(it),), daemon=True)
        self._thread.start()

    def _fill(self, it: Iterator) -> None:
        try:
            for item in it:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._err = e
        # _err is visible before the consumer sees _DONE (queue is a barrier)
        while not self._stop.is_set():
            try:
                self._q.put(_DONE, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self) -> Any:
        # latched terminal state: the producer thread is gone, so another
        # q.get() would block forever (after exhaustion, a producer error
        # the consumer caught and retried past, or close())
        if self._finished:
            if self._err is not None:
                raise self._err
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        self._wait_s += time.perf_counter() - t0
        if item is _DONE:
            self._finished = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        self._items += 1
        return item

    def stats(self) -> dict:
        """``{"items", "consumer_wait_s"}`` — items delivered and the wall
        time the consumer spent blocked waiting for one.  A healthy
        overlapped pipeline keeps ``consumer_wait_s`` near the FIRST
        item's assembly time (the warm-up the thread cannot hide); wait
        growing with item count means the producer cannot keep up and the
        input pipeline is on the critical path."""
        return {"items": self._items, "consumer_wait_s": self._wait_s}

    def close(self) -> None:
        self._finished = True
        self._stop.set()
        # drain so a blocked producer can observe the stop event
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __del__(self) -> None:
        self.close()
