"""Model registry: named configs + checkpoint loading.

Stands in for ``AutoModelForSeq2SeqLM.from_pretrained(model_ckpt)``
(reference train-torchrun.py:35): a name resolves to (a) a built-in config
— sized to match the public checkpoints — plus random init, or (b) a local
directory containing HF ``config.json`` + ``pytorch_model.bin`` /
``model.safetensors``, which is converted into framework params.  There is
no network path at all (the image has zero egress; weight download is the
platform's job, mirroring how the reference receives datasets as Valohai
inputs).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.models import t5 as t5_mod
from distributed_llms_example_tpu.models.bart import BartConfig, BartForConditionalGeneration
from distributed_llms_example_tpu.models.convert import convert_state_dict
from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from distributed_llms_example_tpu.models.t5 import T5Config, T5ForConditionalGeneration

# Built-in configs sized like the public checkpoints (dims from the public
# HF config.json files; no weights are bundled).
T5_CONFIGS: dict[str, T5Config] = {
    "t5-test": T5Config(vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2, num_heads=4),
    "t5-small": T5Config(d_model=512, d_kv=64, d_ff=2048, num_layers=6, num_heads=8),
    "t5-base": T5Config(d_model=768, d_kv=64, d_ff=3072, num_layers=12, num_heads=12),
    "t5-large": T5Config(d_model=1024, d_kv=64, d_ff=4096, num_layers=24, num_heads=16),
    "flan-t5-xl": T5Config(
        d_model=2048,
        d_kv=64,
        d_ff=5120,
        num_layers=24,
        num_heads=32,
        feed_forward_proj="gated-gelu",
        tie_word_embeddings=False,
    ),
}

BART_CONFIGS: dict[str, BartConfig] = {
    "bart-test": BartConfig(
        vocab_size=256, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, max_position_embeddings=128,
        forced_bos_token_id=0,
    ),
    "bart-base": BartConfig(
        d_model=768, encoder_layers=6, decoder_layers=6,
        encoder_attention_heads=12, decoder_attention_heads=12,
        encoder_ffn_dim=3072, decoder_ffn_dim=3072,
    ),
    # the reference's default model (reference valohai.yaml:10)
    "bart-large-cnn": BartConfig(forced_bos_token_id=0),
    "bart-large": BartConfig(),
}

LLAMA_CONFIGS: dict[str, LlamaConfig] = {
    "llama-test": LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    ),
    # 4/8 layers: enough depth for stage x virtual_stages interleaved-
    # pipeline tests (llama-test's 2 layers only split into 2 plain stages)
    "llama-test-4l": LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    ),
    "llama-test-8l": LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=8, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    ),
    "llama-2-7b": LlamaConfig(),
    "llama-2-13b": LlamaConfig(
        hidden_size=5120, intermediate_size=13824, num_hidden_layers=40, num_attention_heads=40
    ),
    # Mixtral-class sparse MoE (ops/moe.py): LLaMA blocks with top-2 routed
    # expert MLPs, experts sharded over ``tensor`` (expert parallelism)
    "mixtral-test": LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
        num_experts=4, num_experts_per_tok=2, moe_aux_weight=0.01,
    ),
    # 4 layers: MoE × interleaved pipeline tests need stage=2 × v=2 chunks
    "mixtral-test-4l": LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
        num_experts=4, num_experts_per_tok=2, moe_aux_weight=0.01,
    ),
    "mixtral-8x7b": LlamaConfig(
        hidden_size=4096, intermediate_size=14336, num_hidden_layers=32,
        num_attention_heads=32, num_key_value_heads=8, vocab_size=32000,
        max_position_embeddings=32768, rope_theta=1e6,
        num_experts=8, num_experts_per_tok=2, moe_aux_weight=0.02,
    ),
}


@dataclasses.dataclass
class LoadedModel:
    family: str
    config: Any
    module: Any  # the flax module (not bound)
    params: Any | None  # None until init_params/load
    is_seq2seq: bool = True

    def init_params(self, rng: jax.Array | int = 0) -> Any:
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        if self.is_seq2seq:
            dummy = jnp.ones((1, 8), jnp.int32)
            variables = self.module.init(rng, dummy, jnp.ones_like(dummy), dummy)
        else:
            dummy = jnp.ones((1, 8), jnp.int32)
            variables = self.module.init(rng, dummy)
        return variables["params"]


def _t5_from_hf_config(cfg: dict) -> T5Config:
    return T5Config(
        vocab_size=cfg["vocab_size"],
        d_model=cfg["d_model"],
        d_kv=cfg["d_kv"],
        d_ff=cfg["d_ff"],
        num_layers=cfg["num_layers"],
        num_decoder_layers=cfg.get("num_decoder_layers"),
        num_heads=cfg["num_heads"],
        relative_attention_num_buckets=cfg.get("relative_attention_num_buckets", 32),
        relative_attention_max_distance=cfg.get("relative_attention_max_distance", 128),
        dropout_rate=cfg.get("dropout_rate", 0.1),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-6),
        feed_forward_proj=cfg.get("feed_forward_proj", "relu").replace("gated-gelu_new", "gated-gelu"),
        tie_word_embeddings=cfg.get("tie_word_embeddings", True),
        pad_token_id=cfg.get("pad_token_id", 0),
        eos_token_id=cfg.get("eos_token_id", 1),
        decoder_start_token_id=cfg.get("decoder_start_token_id", 0),
    )


def _load_local_state_dict(path: str) -> dict:
    # sharded layouts first: large checkpoints (7B+, mixtral-8x7b) are always
    # shipped as model-0000N-of-000NN files plus an index json
    for index_name, loader in (
        ("model.safetensors.index.json", "safetensors"),
        ("pytorch_model.bin.index.json", "torch"),
    ):
        index_path = os.path.join(path, index_name)
        if not os.path.exists(index_path):
            continue
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
        out: dict = {}
        for shard in sorted(set(weight_map.values())):
            shard_path = os.path.join(path, shard)
            if loader == "safetensors":
                from safetensors.numpy import load_file  # ships with transformers

                out.update(load_file(shard_path))
            else:
                import torch

                out.update(torch.load(shard_path, map_location="cpu", weights_only=True))
        return out
    st_path = os.path.join(path, "model.safetensors")
    if os.path.exists(st_path):
        from safetensors.numpy import load_file  # ships with transformers

        return dict(load_file(st_path))
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(bin_path):
        import torch

        return torch.load(bin_path, map_location="cpu", weights_only=True)
    raise FileNotFoundError(
        f"no model.safetensors(.index.json) or pytorch_model.bin(.index.json) under {path}"
    )


def _bart_from_hf_config(cfg: dict) -> BartConfig:
    return BartConfig(
        vocab_size=cfg["vocab_size"],
        d_model=cfg["d_model"],
        encoder_layers=cfg["encoder_layers"],
        decoder_layers=cfg["decoder_layers"],
        encoder_attention_heads=cfg["encoder_attention_heads"],
        decoder_attention_heads=cfg["decoder_attention_heads"],
        encoder_ffn_dim=cfg["encoder_ffn_dim"],
        decoder_ffn_dim=cfg["decoder_ffn_dim"],
        max_position_embeddings=cfg.get("max_position_embeddings", 1024),
        dropout_rate=cfg.get("dropout", 0.1),
        # HF probs dropout (bart-large ships 0.0); rides the flash
        # kernels' in-kernel mask stream when a checkpoint sets it
        attn_dropout_rate=cfg.get("attention_dropout", 0.0),
        scale_embedding=cfg.get("scale_embedding", False),
        pad_token_id=cfg.get("pad_token_id", 1),
        bos_token_id=cfg.get("bos_token_id", 0),
        eos_token_id=cfg.get("eos_token_id", 2),
        decoder_start_token_id=cfg.get("decoder_start_token_id", 2),
        forced_bos_token_id=cfg.get("forced_bos_token_id"),
        forced_eos_token_id=cfg.get("forced_eos_token_id"),
    )


def _llama_from_hf_config(cfg: dict) -> LlamaConfig:
    return LlamaConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_hidden_layers=cfg["num_hidden_layers"],
        num_attention_heads=cfg["num_attention_heads"],
        num_key_value_heads=cfg.get("num_key_value_heads"),
        max_position_embeddings=cfg.get("max_position_embeddings", 4096),
        attn_dropout_rate=cfg.get("attention_dropout", 0.0),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        rope_theta=cfg.get("rope_theta", 10000.0),
        pad_token_id=cfg.get("pad_token_id") or 0,
        bos_token_id=cfg.get("bos_token_id", 1),
        eos_token_id=cfg.get("eos_token_id", 2),
    )


def _build(family: str, cfg: Any, dtype: jnp.dtype, remat: bool, params: Any = None,
           remat_policy: str = "full") -> LoadedModel:
    if family == "t5":
        module = T5ForConditionalGeneration(cfg, dtype=dtype, remat=remat, remat_policy=remat_policy)
        return LoadedModel("t5", cfg, module, params, is_seq2seq=True)
    if family == "bart":
        module = BartForConditionalGeneration(cfg, dtype=dtype, remat=remat, remat_policy=remat_policy)
        return LoadedModel("bart", cfg, module, params, is_seq2seq=True)
    if family in ("llama", "mixtral"):  # mixtral = llama blocks + MoE MLP
        module = LlamaForCausalLM(cfg, dtype=dtype, remat=remat, remat_policy=remat_policy)
        return LoadedModel("llama", cfg, module, params, is_seq2seq=False)
    raise ValueError(f"unsupported model family {family!r}")


def _mixtral_from_hf_config(cfg: dict) -> LlamaConfig:
    base = _llama_from_hf_config(cfg)
    return dataclasses.replace(
        base,
        num_experts=cfg.get("num_local_experts", 8),
        num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
        # HF MixtralConfig default; a larger fallback would silently apply
        # stronger load-balance pressure than the same checkpoint under HF
        moe_aux_weight=cfg.get("router_aux_loss_coef", 0.001),
        # HF routes densely (no capacity limit): <=0 = no-drop everywhere,
        # so converted checkpoints reproduce HF logits on every path
        moe_capacity_factor=-1.0,
    )


_HF_CONFIG_PARSERS = {
    "t5": _t5_from_hf_config,
    "bart": _bart_from_hf_config,
    "llama": _llama_from_hf_config,
    "mixtral": _mixtral_from_hf_config,
}


def load_model(
    name_or_path: str,
    *,
    dtype: jnp.dtype = jnp.float32,
    remat: bool = False,
    remat_policy: str = "full",
    load_weights: bool = True,
    attention_impl: str | None = None,
    moe_capacity_factor: float | None = None,
    fused_ce: bool | None = None,
) -> LoadedModel:
    """Resolve a model name or local HF checkpoint dir into a LoadedModel.

    ``attention_impl`` overrides the config's attention path ("auto" /
    "flash" / "ring" / "xla", see ops/mha.py) for every family.  T5's
    learned relative-position bias rides the flash kernel's differentiable
    ``learned_bias`` input on any mesh (multi-device via the sharded path
    whose hand-written vjp psums dbias across batch shards); T5
    cross-attention takes the same flash/ring paths as BART/LLaMA.

    ``moe_capacity_factor`` overrides the MoE expert capacity factor for
    models that have experts.  HF-converted Mixtral checkpoints default to
    no-drop routing (<= 0) for exact logit parity with HF, but no-drop
    sizes the dispatch tensors at capacity = group_size — a memory cliff
    at fine-tune batch/length.  Passing e.g. 1.25 here restores the
    standard capacity-factor trade for training while leaving parity
    evals (which load without the override) exact.
    """
    if attention_impl not in (None, "auto", "flash", "ring", "xla"):
        raise ValueError(
            f"attention_impl={attention_impl!r}: must be 'auto', 'flash', 'ring', or 'xla'"
        )

    def _apply_impl(cfg):
        if attention_impl is not None and hasattr(cfg, "attention_impl"):
            cfg = dataclasses.replace(cfg, attention_impl=attention_impl)
        if (
            moe_capacity_factor is not None
            and getattr(cfg, "num_experts", 0) > 0
        ):
            cfg = dataclasses.replace(cfg, moe_capacity_factor=moe_capacity_factor)
        if fused_ce is not None and hasattr(cfg, "fused_ce"):
            # vocab-chunked LM-head + CE (ops/blockwise_ce.py); causal
            # families only — seq2seq configs have no such field
            cfg = dataclasses.replace(cfg, fused_ce=fused_ce)
        return cfg

    if os.path.isdir(name_or_path):
        with open(os.path.join(name_or_path, "config.json")) as f:
            hf_cfg = json.load(f)
        model_type = hf_cfg.get("model_type", "t5")
        if model_type not in _HF_CONFIG_PARSERS:
            raise ValueError(f"unsupported model_type {model_type!r} at {name_or_path}")
        cfg = _apply_impl(_HF_CONFIG_PARSERS[model_type](hf_cfg))
        params = None
        if load_weights:
            params = convert_state_dict(model_type, _load_local_state_dict(name_or_path))
            params = jax.tree.map(jnp.asarray, params)
        return _build(model_type, cfg, dtype, remat, params, remat_policy=remat_policy)
    # short names: strip org prefixes like "google/" or "facebook/"
    short = name_or_path.rsplit("/", 1)[-1]
    if short in T5_CONFIGS:
        return _build("t5", _apply_impl(T5_CONFIGS[short]), dtype, remat, remat_policy=remat_policy)
    if short in BART_CONFIGS:
        return _build("bart", _apply_impl(BART_CONFIGS[short]), dtype, remat, remat_policy=remat_policy)
    if short in LLAMA_CONFIGS:
        return _build("llama", _apply_impl(LLAMA_CONFIGS[short]), dtype, remat, remat_policy=remat_policy)
    known = sorted(T5_CONFIGS) + sorted(BART_CONFIGS) + sorted(LLAMA_CONFIGS)
    raise ValueError(
        f"unknown model {name_or_path!r}: not a local checkpoint dir and not one of {known}"
    )


__all__ = [
    "LoadedModel",
    "load_model",
    "T5_CONFIGS",
    "BART_CONFIGS",
    "LLAMA_CONFIGS",
    "t5_mod",
]
