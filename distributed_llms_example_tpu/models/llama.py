"""LLaMA-family causal LM in flax.linen (llama-2-7b-class, BASELINE.json
config 5: multi-host bf16 instruction fine-tuning).

Architecture facts matched against HF ``LlamaForCausalLM`` (parity-tested):
pre-RMSNorm residual blocks, rotary position embeddings in the HF
half-rotation layout, SwiGLU MLP, bias-free projections, optional
grouped-query attention, untied LM head.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.ops.attention import mask_to_bias
from distributed_llms_example_tpu.ops.fused_dropout import Dropout
from distributed_llms_example_tpu.ops.mha import MultiHeadAttention
from distributed_llms_example_tpu.ops.moe import MoEMLP
from distributed_llms_example_tpu.ops.norms import RMSNorm
from distributed_llms_example_tpu.parallel.activation import constrain_hidden, constrain_logits
from distributed_llms_example_tpu.utils.remat import remat_block


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None  # None → MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    pad_token_id: int = 0
    bos_token_id: int = 1
    eos_token_id: int = 2
    attention_impl: str = "auto"  # "auto" | "flash" | "ring" | "xla" (see ops/mha.py)
    # fuse the LM head + CE into a vocab-chunked scan so (tokens, vocab)
    # fp32 logits never materialize (ops/blockwise_ce.py; data/fsdp
    # meshes — under tensor parallelism the chunked slicing fights the
    # partitioner's vocab sharding, keep the unfused path)
    fused_ce: bool = False
    # Mixture-of-experts (Mixtral-class): 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.0  # load-balance loss weight (0 disables)
    # LLaMA pretrains dropout-free (HF ships no dropout knobs); these
    # default to 0 for checkpoint fidelity, but the plumbing routes
    # through the shared fused helper so a fine-tuning recipe CAN enable
    # residual/probs dropout without touching model code
    dropout_rate: float = 0.0
    attn_dropout_rate: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    # aliases so generation/loss code can treat all configs uniformly
    @property
    def decoder_start_token_id(self) -> int:
        return self.bos_token_id


class LlamaMLP(nn.Module):
    config: LlamaConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        gate = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=self.dtype, name="gate_proj")(x)
        up = nn.Dense(cfg.intermediate_size, use_bias=False, dtype=self.dtype, name="up_proj")(x)
        return nn.Dense(cfg.hidden_size, use_bias=False, dtype=self.dtype, name="down_proj")(
            nn.silu(gate) * up
        )


class LlamaBlock(nn.Module):
    config: LlamaConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg = self.config
        self.attn_norm = RMSNorm(cfg.rms_norm_eps, self.dtype, name="attn_norm")
        self.self_attn = MultiHeadAttention(
            num_heads=cfg.num_attention_heads,
            head_dim=cfg.head_dim,
            model_dim=cfg.hidden_size,
            num_kv_heads=cfg.num_key_value_heads,
            use_bias=False,
            causal=True,
            use_rope=True,
            rope_theta=cfg.rope_theta,
            dtype=self.dtype,
            attention_impl=cfg.attention_impl,
            probs_dropout_rate=cfg.attn_dropout_rate,
            name="self_attn",
        )
        self.mlp_norm = RMSNorm(cfg.rms_norm_eps, self.dtype, name="mlp_norm")
        if cfg.num_experts > 0:
            self.mlp = MoEMLP(
                num_experts=cfg.num_experts,
                intermediate_size=cfg.intermediate_size,
                top_k=cfg.num_experts_per_tok,
                capacity_factor=cfg.moe_capacity_factor,
                dtype=self.dtype,
                name="mlp",
            )
        else:
            self.mlp = LlamaMLP(cfg, dtype=self.dtype, name="mlp")
        self.dropout = Dropout(self.config.dropout_rate)

    def __call__(
        self, hidden, bias=None, deterministic: bool = True, use_cache: bool = False,
        positions=None, cache_positions=None,
    ):
        h = self.self_attn(
            self.attn_norm(hidden), bias=bias, use_cache=use_cache,
            positions=positions, deterministic=deterministic,
            cache_positions=cache_positions,
        )
        # rate defaults to 0 (checkpoint-faithful): the helper is then a
        # plain residual add; a recipe that turns dropout on gets the
        # fused kernel with zero model changes
        hidden = self.dropout(h, deterministic, residual=hidden)
        if self.config.num_experts > 0:
            # cached decode/prefill = inference: size expert capacity so no
            # token drops (exact HF-checkpoint behavior); training keeps the
            # capacity-factor trade
            return self.dropout(
                self.mlp(self.mlp_norm(hidden), no_drop=use_cache),
                deterministic, residual=hidden,
            )
        return self.dropout(
            self.mlp(self.mlp_norm(hidden)), deterministic, residual=hidden
        )


def _seq_shift_labels(labels: jnp.ndarray, axis_name: str, n: int) -> jnp.ndarray:
    """Next-token targets for a LOCAL sequence shard inside a manual region.

    Global convention: position t's logits predict ``labels[t+1]``.  Shard
    i holds positions [i·T, (i+1)·T); the target of its LAST position is
    the FIRST label of shard i+1 — fetched with a one-column ``ppermute``.
    The global final position has no target: the last shard's final column
    is set to LABEL_PAD (exactly the position ``logits[:, :-1]`` drops in
    the unsharded objective)."""
    from distributed_llms_example_tpu.data.batching import LABEL_PAD

    idx = jax.lax.axis_index(axis_name)
    nxt = jax.lax.ppermute(
        labels[:, :1], axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    shifted = jnp.concatenate([labels[:, 1:], nxt], axis=1)
    t_loc = labels.shape[1]
    drop = (idx == n - 1) & (jnp.arange(t_loc)[None, :] == t_loc - 1)
    return jnp.where(drop, LABEL_PAD, shifted)


class PipelinedLlama:
    """Train-time ``apply()`` adapter running the LLaMA block stack as a
    GPipe pipeline over the ``stage`` mesh axis (parallel/pipeline.py).

    Drop-in for ``LlamaForCausalLM.apply`` in the train step's loss fn
    (same call signature/logits), but the param tree holds the blocks
    *stacked*: ``{embed_tokens, stacked_blocks, final_norm, lm_head}``
    (``stack_blocks`` of the standard tree; checkpoints/eval use
    ``unstack_blocks`` to return to the per-layer layout).  Embedding,
    final norm, and LM head run outside the pipeline body under plain
    GSPMD.  The pipeline shard_map is manual over ``stage`` ONLY, so
    ``stage`` composes with data/fsdp (batch), ``tensor`` (megatron
    splits on the stacked kernels, partitioned automatically by GSPMD
    inside each stage — the stage×tensor topology 7B+ models use) AND
    ``expert`` (MoE configs on the gpipe schedule: the load-balance loss
    rides out of the pipeline as an explicit output, see ``_layer_fn``).
    ``sequence`` composes on BOTH schedules via ONE combined manual region
    over {stage, sequence}: the pipeline installs a ``manual_sequence``
    context and the blocks' attention switches to the in-region ring body
    with RoPE offset to global positions — long-context LLaMA training
    with the layer stack ALSO split across stages.  On 1f1b the per-chunk
    vjps differentiate the ring in place and the next-token loss handles
    the cross-shard target shift (``_seq_shift_labels``).  Training +
    teacher-forced scoring only: no KV-cache generation path (unstack for
    decoding).
    """

    def __init__(self, config: LlamaConfig, mesh, dtype=jnp.float32,
                 num_microbatches: int = 0, remat: bool = True,
                 schedule: str = "gpipe", virtual_stages: int = 2):
        # imported here so a missing pipeline module fails at construction
        from distributed_llms_example_tpu.parallel.pipeline import pipeline_apply  # noqa: F401

        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"pipeline schedule {schedule!r}: must be gpipe, 1f1b, or interleaved"
            )

        # known-bad combos (MoE × sequence, ...) live as rows in the
        # composition matrix (analysis/composition.py)
        from distributed_llms_example_tpu.analysis.composition import (
            validate_composition,
        )

        flags = ["pipelined"]
        if getattr(config, "num_experts", 0) > 0:
            flags.append("moe")
        validate_composition(
            family="llama", schedule=schedule, mesh_axes=dict(mesh.shape),
            flags=flags,
        )
        stages = mesh.shape.get("stage", 1)
        if config.num_hidden_layers % max(stages, 1):
            raise ValueError(
                f"{config.num_hidden_layers} layers not divisible into {stages} stages"
            )
        self.virtual_stages = int(virtual_stages) if schedule == "interleaved" else 1
        if schedule == "interleaved":
            # the schedule generator needs stage >= 2; v chunks per device.
            # NOTE: stacked_blocks must be in INTERLEAVED storage order
            # (interleave.interleave_tree) — the Trainer permutes at setup
            # and un-permutes for eval/export.
            if stages < 2:
                raise ValueError("pipeline schedule interleaved needs stage >= 2")
            if self.virtual_stages < 1:
                raise ValueError(
                    f"--pipeline-virtual-stages must be >= 1, got {self.virtual_stages}"
                )
            if config.num_hidden_layers % (stages * self.virtual_stages):
                raise ValueError(
                    f"{config.num_hidden_layers} layers not divisible into "
                    f"{stages} stages x {self.virtual_stages} virtual chunks"
                )
        self.config = config
        self.mesh = mesh
        self.dtype = dtype
        self.num_microbatches = num_microbatches or max(stages, 1)
        self.remat = remat  # per-layer jax.checkpoint inside the pipeline
        self.pipeline_schedule = schedule
        self._embed = nn.Embed(config.vocab_size, config.hidden_size, dtype=dtype)
        self._block = LlamaBlock(config, dtype=dtype)
        self._norm = RMSNorm(config.rms_norm_eps, dtype)
        self._head = nn.Dense(config.vocab_size, use_bias=False, dtype=dtype)

    def _layer_fn(self, with_aux: bool = False):
        from distributed_llms_example_tpu.parallel.activation import activation_mesh

        def layer_fn(p, h, ex, key=None):
            # no ambient mesh inside the pipeline body: attention runs its
            # single-shard path per stage (no nested shard_map).  ``key``
            # satisfies the pipeline rng contract (layer_fn(p, h, ex[, key]));
            # LLaMA blocks are dropout-free (config.dropout_rate == 0) so a
            # provided key changes nothing, but the call must not crash.
            rngs = {} if key is None else {"dropout": key}
            with activation_mesh(None):
                if with_aux:
                    # sown collections cannot cross the pipeline shard_map;
                    # surface the MoE load-balance loss as an explicit
                    # layer output the schedule accumulates
                    h, mut = self._block.apply(
                        {"params": p}, h, ex.get("bias"), rngs=rngs, mutable=["losses"]
                    )
                    leaves = jax.tree.leaves(mut.get("losses", {}))
                    aux = sum(leaves, jnp.zeros((), jnp.float32))
                    return h, aux
                return self._block.apply({"params": p}, h, ex.get("bias"), rngs=rngs)

        return layer_fn

    def make_value_and_grad(self, label_smoothing: float = 0.0,
                            is_seq2seq: bool = False):
        """1F1B training path: ``(params, batch, rng) -> (loss_sum, tokens,
        grads)`` with the schedule owning the backward pass
        (``pipeline_value_and_grad``).  The embedding runs outside the
        pipeline under GSPMD with its own ``jax.vjp``; final norm + LM head
        + next-token CE run per-microbatch on the last stage so each
        microbatch's activation-gradient enters the backward ring on the
        tick its forward finishes.

        Under stage×sequence the loss runs on LOCAL sequence shards: the
        next-token target of a shard's last position lives in the NEXT
        shard, so the labels are pre-shifted with a one-column ``ppermute``
        (``_seq_shift_labels``) and the CE covers every local position —
        summing to exactly the global ``logits[:, :-1]`` vs
        ``labels[:, 1:]`` objective."""
        from distributed_llms_example_tpu.data.batching import LABEL_PAD
        from distributed_llms_example_tpu.parallel.activation import activation_mesh
        from distributed_llms_example_tpu.parallel.pipeline import (
            pipeline_value_and_grad,
            pipeline_value_and_grad_interleaved,
        )
        from distributed_llms_example_tpu.train.step import cross_entropy_sums

        assert not is_seq2seq
        n_seq = self.mesh.shape.get("sequence", 1)
        moe = getattr(self.config, "num_experts", 0) > 0
        moe_weight = float(getattr(self.config, "moe_aux_weight", 0.0) or 0.0)
        L = self.config.num_hidden_layers
        M = self.num_microbatches

        def post_loss(pp, h, mb):
            with activation_mesh(None):
                h = self._norm.apply({"params": pp["final_norm"]}, h)
                logits = self._head.apply({"params": pp["lm_head"]}, h)
            if n_seq > 1:
                labels = _seq_shift_labels(mb["labels"], "sequence", n_seq)
                return cross_entropy_sums(logits, labels, label_smoothing)
            return cross_entropy_sums(logits[:, :-1], mb["labels"][:, 1:], label_smoothing)

        layer_fn = self._layer_fn(with_aux=moe)

        def value_and_grad_sums(params, batch, rng=None):
            hidden, embed_vjp = jax.vjp(
                lambda ep: constrain_hidden(
                    self._embed.apply({"params": ep}, batch["input_ids"])
                ),
                params["embed_tokens"],
            )
            bias = mask_to_bias(batch["attention_mask"])
            post_params = {"final_norm": params["final_norm"], "lm_head": params["lm_head"]}
            common = dict(
                mesh=self.mesh,
                num_microbatches=self.num_microbatches,
                checkpoint=self.remat,
                rng=rng,
                seq_axis="sequence",
                extras_seq_dims={"bias": 3},
                loss_seq_dims={"labels": 1},
            )
            if self.pipeline_schedule == "interleaved":
                run = pipeline_value_and_grad_interleaved
                common["virtual_stages"] = self.virtual_stages
            else:
                run = pipeline_value_and_grad
            if moe:
                # the aux cotangent is a DATA-only constant — the token
                # count the CE will report, known before the schedule
                # runs — so every chunk vjp can fold the load-balance
                # gradient in as it goes (matches the gpipe objective
                # lsum + w·aux_mean·tokens exactly); both fused schedules
                # take the same contract
                tokens_const = jnp.sum(
                    (batch["labels"][:, 1:] != LABEL_PAD).astype(jnp.float32)
                )
                common["with_aux"] = True
                common["aux_cotangent"] = moe_weight * tokens_const / (L * M)
            out = run(
                layer_fn,
                post_loss,
                params["stacked_blocks"],
                post_params,
                hidden,
                {"bias": bias},
                {"labels": batch["labels"]},
                **common,
            )
            if moe:
                lsum, tokens, d_stacked, d_post, d_hidden, aux_sum = out
                lsum = lsum + moe_weight * (aux_sum / (L * M)) * tokens
            else:
                lsum, tokens, d_stacked, d_post, d_hidden = out
            (d_embed,) = embed_vjp(d_hidden.astype(hidden.dtype))
            grads = {
                "embed_tokens": d_embed,
                "stacked_blocks": d_stacked,
                "final_norm": d_post["final_norm"],
                "lm_head": d_post["lm_head"],
            }
            return lsum, tokens, grads

        return value_and_grad_sums

    def apply(self, variables, input_ids, attention_mask=None, *,
              deterministic: bool = True, rngs=None, mutable=None):
        """Flax-compatible: with ``mutable=["losses"]`` (the loss fn's MoE
        path) returns ``(logits, {"losses": {"moe_aux": aux}})`` where
        ``aux`` is the per-(layer, microbatch) mean carried OUT of the
        pipeline as an explicit scan output — matching the standard
        module's mean-over-layers sow semantics at grad-accumulation
        (per-microbatch) granularity."""
        from distributed_llms_example_tpu.parallel.pipeline import pipeline_apply

        params = variables["params"]
        stacked = params["stacked_blocks"]
        if self.pipeline_schedule == "interleaved" and self.virtual_stages > 1:
            # apply() runs the gpipe forward, which assumes TRUE layer
            # order — un-permute the interleaved storage first (v == 1 is
            # already true order).  NOTE this take() executes on EVERY
            # call (one full stacked-params gather per compiled
            # invocation); the Trainer's val-loss path hoists it to once
            # per evaluate() via a gpipe-view adapter, and the training
            # step never comes through here.
            from distributed_llms_example_tpu.parallel.interleave import (
                uninterleave_tree,
            )

            stacked = uninterleave_tree(
                stacked, self.mesh.shape["stage"], self.virtual_stages
            )
        hidden = constrain_hidden(self._embed.apply({"params": params["embed_tokens"]}, input_ids))
        bias = mask_to_bias(attention_mask) if attention_mask is not None else None
        extras = {"bias": bias} if bias is not None else {}
        with_aux = bool(mutable) and getattr(self.config, "num_experts", 0) > 0

        out = pipeline_apply(
            self._layer_fn(with_aux=with_aux),
            stacked,
            hidden,
            extras,
            mesh=self.mesh,
            num_microbatches=self.num_microbatches,
            checkpoint=self.remat,
            with_aux=with_aux,
            # stage×sequence: ONE manual region over both axes; the K-only
            # padding bias shards its K dim and rides the ring with K/V
            seq_axis="sequence",
            extras_seq_dims={"bias": 3} if "bias" in extras else {},
        )
        hidden, aux = out if with_aux else (out, None)
        hidden = self._norm.apply({"params": params["final_norm"]}, hidden)
        logits = constrain_logits(self._head.apply({"params": params["lm_head"]}, hidden))
        if mutable:
            return logits, ({"losses": {"moe_aux": aux}} if with_aux else {})
        return logits


class LlamaForCausalLM(nn.Module):
    config: LlamaConfig
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    remat_policy: str = "full"  # "full" | "dots" (utils/remat.py)

    def setup(self) -> None:
        cfg = self.config
        self.embed_tokens = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=self.dtype, name="embed_tokens")
        # static args: deterministic (3), use_cache (4) — counting self at 0
        block = remat_block(LlamaBlock, (3, 4), self.remat_policy) if self.remat else LlamaBlock
        self.blocks = [block(cfg, dtype=self.dtype, name=f"block_{i}") for i in range(cfg.num_hidden_layers)]
        self.final_norm = RMSNorm(cfg.rms_norm_eps, self.dtype, name="final_norm")
        self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head")

    def __call__(
        self,
        input_ids,
        attention_mask=None,
        *,
        deterministic: bool = True,
        use_cache: bool = False,
        cache_offset: int | jnp.ndarray = 0,
        max_kv_len: int | None = None,
        positions: jnp.ndarray | None = None,
        cache_positions: jnp.ndarray | None = None,
    ):
        hidden = constrain_hidden(self.embed_tokens(input_ids))
        # causal masking lives inside MultiHeadAttention (applied natively by
        # the flash kernel); only the padding mask is passed as a bias
        bias = mask_to_bias(attention_mask) if attention_mask is not None else None
        for blk in self.blocks:
            hidden = constrain_hidden(
                blk(hidden, bias, deterministic, use_cache, positions, cache_positions)
            )
        return constrain_logits(self.lm_head(self.final_norm(hidden)))

    def hidden_states(self, input_ids, attention_mask=None, *, deterministic: bool = True):
        """Final-norm output WITHOUT the LM-head projection — the fused-CE
        training path (ops/blockwise_ce.py) consumes this and applies the
        head inside its vocab-chunked scan."""
        hidden = constrain_hidden(self.embed_tokens(input_ids))
        bias = mask_to_bias(attention_mask) if attention_mask is not None else None
        for blk in self.blocks:
            hidden = constrain_hidden(blk(hidden, bias, deterministic, False))
        return self.final_norm(hidden)
