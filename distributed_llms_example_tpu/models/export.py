"""Framework parameter tree → HF checkpoint export (the reverse of convert.py).

The reference's final artifact is ``model.save_pretrained(output_dir)``
(reference helpers.py:13) — an HF-loadable directory any downstream tool
(transformers, vLLM, the reference itself) can consume.  This module gives
the framework the same exit door: ``save_hf_checkpoint`` writes HF
``config.json`` + ``model.safetensors`` (sharded with an index when large),
with tensor names and layouts exactly inverse to ``convert.py`` — flax
(in, out) kernels transpose back to torch (out, in), stacked Mixtral expert
tensors unstack into per-expert linears, and tied embeddings are emitted
once under their canonical name (transformers re-ties on load).

Round-trip contract (tested in tests/test_export.py): for every family,
``load_model(export_dir)`` reproduces the original logits bit-for-bit, and
``transformers.*.from_pretrained(export_dir)`` loads with no unexpected
keys.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Mapping

import numpy as np

# HF's default shard size; checkpoints above this split into
# model-0000N-of-0000M.safetensors + model.safetensors.index.json (the
# layout _load_local_state_dict already reads back)
MAX_SHARD_BYTES = 5 * 1024**3


def _t(x: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(x.T)


def _flat(tree: Mapping[str, Any], prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        p = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, Mapping):
            out.update(_flat(v, p))
        else:
            arr = np.asarray(v)
            if arr.dtype not in (np.float32, np.float64):
                arr = arr.astype(np.float32)  # bf16 params → fp32 artifact
            out[p] = arr
    return out


# --- T5 -------------------------------------------------------------------

_T5_MLP_LAYER = {"encoder": 1, "decoder": 2}


def export_t5_state_dict(params: Mapping[str, Any]) -> dict[str, np.ndarray]:
    """Our T5 tree → HF ``T5ForConditionalGeneration`` names (inverse of
    ``convert_t5_state_dict``; encoder layers are [self_attn, mlp], decoder
    layers are [self_attn, cross_attn, mlp])."""
    out: dict[str, np.ndarray] = {}
    for path, arr in _flat(params).items():
        if path == "shared/embedding":
            out["shared.weight"] = arr
            continue
        if path == "lm_head/kernel":  # only present when untied
            out["lm_head.weight"] = _t(arr)
            continue
        m = re.fullmatch(r"(encoder|decoder)/final_norm/scale", path)
        if m:
            out[f"{m.group(1)}.final_layer_norm.weight"] = arr
            continue
        m = re.fullmatch(r"(encoder|decoder)/relative_attention_bias/embedding", path)
        if m:
            out[f"{m.group(1)}.block.0.layer.0.SelfAttention.relative_attention_bias.weight"] = arr
            continue
        m = re.fullmatch(r"(encoder|decoder)/block_(\d+)/(.+)", path)
        if not m:
            raise ValueError(f"unrecognized T5 parameter path: {path}")
        stack, i, rest = m.groups()
        base = f"{stack}.block.{i}.layer"
        m = re.fullmatch(r"self_attn/([qkvo])_proj/kernel", rest)
        if m:
            out[f"{base}.0.SelfAttention.{m.group(1)}.weight"] = _t(arr)
            continue
        if rest == "self_attn_norm/scale":
            out[f"{base}.0.layer_norm.weight"] = arr
            continue
        m = re.fullmatch(r"cross_attn/([qkvo])_proj/kernel", rest)
        if m:
            out[f"{base}.1.EncDecAttention.{m.group(1)}.weight"] = _t(arr)
            continue
        if rest == "cross_attn_norm/scale":
            out[f"{base}.1.layer_norm.weight"] = arr
            continue
        mlp_layer = _T5_MLP_LAYER[stack]
        m = re.fullmatch(r"mlp/(wi|wo|wi_0|wi_1)/kernel", rest)
        if m:
            out[f"{base}.{mlp_layer}.DenseReluDense.{m.group(1)}.weight"] = _t(arr)
            continue
        if rest == "mlp_norm/scale":
            out[f"{base}.{mlp_layer}.layer_norm.weight"] = arr
            continue
        raise ValueError(f"unrecognized T5 parameter path: {path}")
    return out


# --- BART -----------------------------------------------------------------

_BART_ATTN_OUT = {"q_proj": "q_proj", "k_proj": "k_proj", "v_proj": "v_proj", "o_proj": "out_proj"}
_BART_SUB_OUT = {"self_attn": "self_attn", "cross_attn": "encoder_attn"}
_BART_NORM_OUT = {
    "self_attn_layer_norm": "self_attn_layer_norm",
    "cross_attn_layer_norm": "encoder_attn_layer_norm",
    "final_layer_norm": "final_layer_norm",
}


def export_bart_state_dict(params: Mapping[str, Any]) -> dict[str, np.ndarray]:
    """Our BART tree → HF ``BartForConditionalGeneration`` names (inverse
    of ``convert_bart_state_dict``)."""
    out: dict[str, np.ndarray] = {}
    for path, arr in _flat(params).items():
        if path == "shared/embedding":
            out["model.shared.weight"] = arr
            continue
        if path == "final_logits_bias":
            out["final_logits_bias"] = arr.reshape(1, -1)
            continue
        m = re.fullmatch(r"(encoder|decoder)_embed_positions/embedding", path)
        if m:
            out[f"model.{m.group(1)}.embed_positions.weight"] = arr
            continue
        m = re.fullmatch(r"(encoder|decoder)_layernorm_embedding/(scale|bias)", path)
        if m:
            leaf = "weight" if m.group(2) == "scale" else "bias"
            out[f"model.{m.group(1)}.layernorm_embedding.{leaf}"] = arr
            continue
        m = re.fullmatch(r"(encoder|decoder)_block_(\d+)/(.+)", path)
        if not m:
            raise ValueError(f"unrecognized BART parameter path: {path}")
        stack, i, rest = m.groups()
        base = f"model.{stack}.layers.{i}"
        m = re.fullmatch(r"(self_attn|cross_attn)/([qkvo]_proj)/(kernel|bias)", rest)
        if m:
            sub, proj, kind = m.groups()
            leaf = "weight" if kind == "kernel" else "bias"
            val = _t(arr) if kind == "kernel" else arr
            out[f"{base}.{_BART_SUB_OUT[sub]}.{_BART_ATTN_OUT[proj]}.{leaf}"] = val
            continue
        m = re.fullmatch(r"mlp/(fc1|fc2)/(kernel|bias)", rest)
        if m:
            proj, kind = m.groups()
            leaf = "weight" if kind == "kernel" else "bias"
            out[f"{base}.{proj}.{leaf}"] = _t(arr) if kind == "kernel" else arr
            continue
        m = re.fullmatch(
            r"(self_attn_layer_norm|cross_attn_layer_norm|final_layer_norm)/(scale|bias)", rest
        )
        if m:
            norm, kind = m.groups()
            leaf = "weight" if kind == "scale" else "bias"
            out[f"{base}.{_BART_NORM_OUT[norm]}.{leaf}"] = arr
            continue
        raise ValueError(f"unrecognized BART parameter path: {path}")
    return out


# --- LLaMA / Mixtral ------------------------------------------------------

_MIXTRAL_W = {"gate_proj": "w1", "up_proj": "w3", "down_proj": "w2"}


def export_llama_state_dict(params: Mapping[str, Any]) -> dict[str, np.ndarray]:
    """Our LLaMA/Mixtral tree → HF ``LlamaForCausalLM`` /
    ``MixtralForCausalLM`` names (inverse of ``convert_llama_state_dict``).
    Stacked (E, d_in, d_out) expert tensors unstack into per-expert
    ``block_sparse_moe.experts.{j}.w{1,2,3}`` linears."""
    out: dict[str, np.ndarray] = {}
    for path, arr in _flat(params).items():
        if path == "embed_tokens/embedding":
            out["model.embed_tokens.weight"] = arr
            continue
        if path == "final_norm/scale":
            out["model.norm.weight"] = arr
            continue
        if path == "lm_head/kernel":
            out["lm_head.weight"] = _t(arr)
            continue
        m = re.fullmatch(r"block_(\d+)/(.+)", path)
        if not m:
            raise ValueError(f"unrecognized LLaMA parameter path: {path}")
        i, rest = m.groups()
        base = f"model.layers.{i}"
        m = re.fullmatch(r"self_attn/([qkvo])_proj/kernel", rest)
        if m:
            out[f"{base}.self_attn.{m.group(1)}_proj.weight"] = _t(arr)
            continue
        m = re.fullmatch(r"mlp/(gate_proj|up_proj|down_proj)(/kernel)?", rest)
        if m:
            name, is_dense = m.group(1), m.group(2) is not None
            if is_dense:
                out[f"{base}.mlp.{name}.weight"] = _t(arr)
            else:  # stacked experts: (E, d_in, d_out)
                for j in range(arr.shape[0]):
                    out[f"{base}.block_sparse_moe.experts.{j}.{_MIXTRAL_W[name]}.weight"] = _t(arr[j])
            continue
        if rest == "mlp/router/kernel":
            out[f"{base}.block_sparse_moe.gate.weight"] = _t(arr)
            continue
        if rest == "attn_norm/scale":
            out[f"{base}.input_layernorm.weight"] = arr
            continue
        if rest == "mlp_norm/scale":
            out[f"{base}.post_attention_layernorm.weight"] = arr
            continue
        raise ValueError(f"unrecognized LLaMA parameter path: {path}")
    return out


EXPORTERS = {
    "t5": export_t5_state_dict,
    "bart": export_bart_state_dict,
    "llama": export_llama_state_dict,
    "mixtral": export_llama_state_dict,
}


# --- HF config.json -------------------------------------------------------


def hf_config_dict(family: str, cfg: Any) -> dict:
    """Framework config dataclass → the HF ``config.json`` fields that
    ``transformers`` needs to reconstruct the architecture (the same
    fields registry._*_from_hf_config reads, so the round trip is exact)."""
    if family == "t5":
        return {
            "model_type": "t5",
            "architectures": ["T5ForConditionalGeneration"],
            "is_encoder_decoder": True,
            "vocab_size": cfg.vocab_size,
            "d_model": cfg.d_model,
            "d_kv": cfg.d_kv,
            "d_ff": cfg.d_ff,
            "num_layers": cfg.num_layers,
            "num_decoder_layers": cfg.num_decoder_layers or cfg.num_layers,
            "num_heads": cfg.num_heads,
            "relative_attention_num_buckets": cfg.relative_attention_num_buckets,
            "relative_attention_max_distance": cfg.relative_attention_max_distance,
            "dropout_rate": cfg.dropout_rate,
            "layer_norm_epsilon": cfg.layer_norm_epsilon,
            "feed_forward_proj": cfg.feed_forward_proj,
            "tie_word_embeddings": cfg.tie_word_embeddings,
            "pad_token_id": cfg.pad_token_id,
            "eos_token_id": cfg.eos_token_id,
            "decoder_start_token_id": cfg.decoder_start_token_id,
        }
    if family == "bart":
        return {
            "model_type": "bart",
            "architectures": ["BartForConditionalGeneration"],
            "is_encoder_decoder": True,
            "vocab_size": cfg.vocab_size,
            "d_model": cfg.d_model,
            "encoder_layers": cfg.encoder_layers,
            "decoder_layers": cfg.decoder_layers,
            "encoder_attention_heads": cfg.encoder_attention_heads,
            "decoder_attention_heads": cfg.decoder_attention_heads,
            "encoder_ffn_dim": cfg.encoder_ffn_dim,
            "decoder_ffn_dim": cfg.decoder_ffn_dim,
            "max_position_embeddings": cfg.max_position_embeddings,
            "dropout": cfg.dropout_rate,
            "attention_dropout": cfg.attn_dropout_rate,
            "scale_embedding": cfg.scale_embedding,
            "pad_token_id": cfg.pad_token_id,
            "bos_token_id": cfg.bos_token_id,
            "eos_token_id": cfg.eos_token_id,
            "decoder_start_token_id": cfg.decoder_start_token_id,
            "forced_bos_token_id": cfg.forced_bos_token_id,
            "forced_eos_token_id": cfg.forced_eos_token_id,
        }
    if family in ("llama", "mixtral"):
        is_moe = getattr(cfg, "num_experts", 0) > 0
        out = {
            "model_type": "mixtral" if is_moe else "llama",
            "architectures": ["MixtralForCausalLM" if is_moe else "LlamaForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "intermediate_size": cfg.intermediate_size,
            "num_hidden_layers": cfg.num_hidden_layers,
            "num_attention_heads": cfg.num_attention_heads,
            "num_key_value_heads": cfg.num_key_value_heads or cfg.num_attention_heads,
            "max_position_embeddings": cfg.max_position_embeddings,
            "attention_dropout": cfg.attn_dropout_rate,
            "rms_norm_eps": cfg.rms_norm_eps,
            "rope_theta": cfg.rope_theta,
            "tie_word_embeddings": False,
            "pad_token_id": cfg.pad_token_id,
            "bos_token_id": cfg.bos_token_id,
            "eos_token_id": cfg.eos_token_id,
        }
        if is_moe:
            out["num_local_experts"] = cfg.num_experts
            out["num_experts_per_tok"] = cfg.num_experts_per_tok
            out["router_aux_loss_coef"] = cfg.moe_aux_weight
        return out
    raise ValueError(f"no HF config export for family {family!r}")


# --- checkpoint writer ----------------------------------------------------


def save_hf_checkpoint(out_dir: str, family: str, cfg: Any, params: Mapping[str, Any]) -> None:
    """Write ``config.json`` + ``model.safetensors`` (sharded + indexed
    above MAX_SHARD_BYTES, HF's file layout) to ``out_dir``."""
    from safetensors.numpy import save_file  # ships with transformers

    os.makedirs(out_dir, exist_ok=True)
    state = EXPORTERS[family](params)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_config_dict(family, cfg), f, indent=2, sort_keys=True)

    total = sum(a.nbytes for a in state.values())
    if total <= MAX_SHARD_BYTES:
        save_file(state, os.path.join(out_dir, "model.safetensors"), metadata={"format": "pt"})
        return
    # size-based sharding, preserving insertion order
    shards: list[dict[str, np.ndarray]] = [{}]
    size = 0
    for name, arr in state.items():
        if size + arr.nbytes > MAX_SHARD_BYTES and shards[-1]:
            shards.append({})
            size = 0
        shards[-1][name] = arr
        size += arr.nbytes
    n = len(shards)
    weight_map: dict[str, str] = {}
    for k, shard in enumerate(shards, start=1):
        fname = f"model-{k:05d}-of-{n:05d}.safetensors"
        save_file(shard, os.path.join(out_dir, fname), metadata={"format": "pt"})
        for name in shard:
            weight_map[name] = fname
    with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {"total_size": total}, "weight_map": weight_map}, f, indent=2)
