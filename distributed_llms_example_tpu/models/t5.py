"""T5 encoder-decoder, written TPU-first in flax.linen.

Replaces the reference's opaque ``AutoModelForSeq2SeqLM.from_pretrained``
(reference train-accelerator.py:40-41) with an in-repo model definition the
sharding rules and Pallas kernels can see into.  Numerical semantics match
HF T5 so converted checkpoints are drop-in (verified by parity tests):

- RMSNorm (no mean subtraction, no bias), fp32 statistics
- relative position bias added to attention scores, bias table shared
  across layers (held once per stack, not per block 0 as HF stores it)
- attention scores are NOT scaled by 1/sqrt(d_kv) — T5 folds that into init
- pre-norm residual blocks; final stack norm
- tied embeddings scale decoder output by d_model**-0.5 before the logits
  projection; T5 v1.1 ("gated-gelu") unties and adds a separate lm_head

Supports both T5 v1.0 (relu FFN, tied) and v1.1/flan (gated-gelu, untied).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.ops.attention import (
    NEG_INF,
    beam_grouped_attention,
    dot_product_attention,
    make_causal_bias,
    mask_to_bias,
)
from distributed_llms_example_tpu.ops.flash_attention import flash_attention
from distributed_llms_example_tpu.ops.fused_dropout import Dropout
from distributed_llms_example_tpu.ops.norms import RMSNorm
from distributed_llms_example_tpu.utils.remat import remat_block
from distributed_llms_example_tpu.parallel.activation import constrain_hidden, constrain_logits


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    # attention-PROBS dropout.  HF T5 trains with this equal to
    # dropout_rate; this port has historically run it at 0 (activations
    # only) and keeps that default so trajectories stay comparable — set
    # it explicitly to recover the HF recipe.  On the flash path the mask
    # is drawn in-kernel (never materialized); the XLA path uses the
    # bernoulli reference (ops/attention.py).
    attn_dropout_rate: float = 0.0
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"  # or "gated-gelu"
    tie_word_embeddings: bool = True
    pad_token_id: int = 0
    eos_token_id: int = 1
    decoder_start_token_id: int = 0
    # "auto": Pallas flash attention where eligible — the learned
    # relative-position bias rides the kernel's differentiable
    # ``learned_bias`` input (multi-device meshes use the sharded path
    # whose hand-written vjp psums dbias across batch shards,
    # ops/flash_attention.flash_attention_lbias_sharded), and mask-only
    # cross-attention takes the same paths as BART/LLaMA.
    attention_impl: str = "auto"

    @property
    def decoder_layers(self) -> int:
        return self.num_decoder_layers if self.num_decoder_layers is not None else self.num_layers

    @property
    def is_gated(self) -> bool:
        return self.feed_forward_proj.startswith("gated")


def relative_position_bucket(
    relative_position: jnp.ndarray,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jnp.ndarray:
    """T5's log-bucketed relative position (kv_pos - q_pos) → bucket id."""
    ret = jnp.zeros_like(relative_position)
    if bidirectional:
        num_buckets //= 2
        ret += (relative_position > 0).astype(jnp.int32) * num_buckets
        rel = jnp.abs(relative_position)
    else:
        rel = -jnp.minimum(relative_position, 0)
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    rel_f = jnp.maximum(rel.astype(jnp.float32), 1.0)
    if_large = max_exact + (
        jnp.log(rel_f / max_exact) / jnp.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    if_large = jnp.minimum(if_large, num_buckets - 1)
    return ret + jnp.where(is_small, rel, if_large)


class T5Attention(nn.Module):
    """Multi-head attention with optional causal masking and KV cache.

    Cache protocol (flax "cache" collection): initialize zero-filled
    full-length buffers with ``init_cache``, then each call with a
    single-query-step writes k/v at ``cache_index`` and attends over the
    prefix — the standard fixed-shape autoregressive decode under jit.
    """

    config: T5Config
    causal: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg = self.config
        inner = cfg.num_heads * cfg.d_kv
        dense = lambda name: nn.Dense(inner, use_bias=False, dtype=self.dtype, name=name)  # noqa: E731
        self.q_proj, self.k_proj, self.v_proj = dense("q_proj"), dense("k_proj"), dense("v_proj")
        self.o_proj = nn.Dense(cfg.d_model, use_bias=False, dtype=self.dtype, name="o_proj")

    def _split(self, x: jnp.ndarray) -> jnp.ndarray:
        b, s, _ = x.shape
        return x.reshape(b, s, self.config.num_heads, self.config.d_kv).transpose(0, 2, 1, 3)

    def project_kv(self, kv_hidden: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        """K/V projections alone — precomputed once per sequence for
        cross-attention decode (see MultiHeadAttention.project_kv)."""
        return self._split(self.k_proj(kv_hidden)), self._split(self.v_proj(kv_hidden))

    def _merge(self, x: jnp.ndarray) -> jnp.ndarray:
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    @nn.compact
    def _cache_kv(self, key: jnp.ndarray, value: jnp.ndarray,
                  cache_positions: jnp.ndarray | None = None) -> tuple:
        """Append this step's k/v into the cache; returns full-length k/v,
        the int8-KV scales (None on the f32 path) and the (pre-update)
        cache index.  ``cache_positions`` (B,) switches to per-row writes
        (continuous-batching slots at distinct offsets; q_len must be 1,
        out-of-range positions drop — idle slots park there).  Under
        ``kv_cache_context("int8")`` the buffers are s8 with per-head
        per-position scale leaves, exactly like
        ``MultiHeadAttention._cache_kv``."""
        from distributed_llms_example_tpu.ops.flash_attention import quantize_kv
        from distributed_llms_example_tpu.parallel.activation import (
            current_kv_cache_dtype,
        )

        # At creation time (init with full-length dummy inputs) the buffers
        # are allocated but NOT written: cache_index must stay 0 so the first
        # real decode step writes at position 0.
        int8_kv = current_kv_cache_dtype() == "int8"
        store_dtype = jnp.int8 if int8_kv else key.dtype
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros, key.shape, store_dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros, value.shape, store_dtype)
        if int8_kv:
            k_scale = self.variable(
                "cache", "key_scale", jnp.zeros, key.shape[:3], jnp.float32
            )
            v_scale = self.variable(
                "cache", "value_scale", jnp.zeros, value.shape[:3], jnp.float32
            )
        cache_index = self.variable("cache", "cache_index", lambda: jnp.array(0, dtype=jnp.int32))
        idx = cache_index.value
        if is_initialized:
            if int8_kv:
                key, ks_new = quantize_kv(key)
                value, vs_new = quantize_kv(value)
            if cache_positions is not None:
                if key.shape[2] != 1:
                    raise ValueError(
                        f"per-row cache_positions requires q_len == 1, got {key.shape[2]}"
                    )
                b = jnp.arange(key.shape[0])
                k = cached_k.value.at[b, :, cache_positions].set(
                    key[:, :, 0, :], mode="drop"
                )
                v = cached_v.value.at[b, :, cache_positions].set(
                    value[:, :, 0, :], mode="drop"
                )
                cached_k.value, cached_v.value = k, v
                if int8_kv:
                    k_scale.value = k_scale.value.at[b, :, cache_positions].set(
                        ks_new[:, :, 0], mode="drop"
                    )
                    v_scale.value = v_scale.value.at[b, :, cache_positions].set(
                        vs_new[:, :, 0], mode="drop"
                    )
            else:
                # buffers are stored (batch, heads, max_len, head_dim); write at idx on axis 2
                k = jax.lax.dynamic_update_slice(cached_k.value, key, (0, 0, idx, 0))
                v = jax.lax.dynamic_update_slice(cached_v.value, value, (0, 0, idx, 0))
                cached_k.value, cached_v.value = k, v
                if int8_kv:
                    k_scale.value = jax.lax.dynamic_update_slice(
                        k_scale.value, ks_new, (0, 0, idx)
                    )
                    v_scale.value = jax.lax.dynamic_update_slice(
                        v_scale.value, vs_new, (0, 0, idx)
                    )
                cache_index.value = idx + key.shape[2]
        else:
            k, v = cached_k.value, cached_v.value
        if int8_kv:
            return k, v, k_scale.value, v_scale.value, idx
        return k, v, None, None, idx

    def __call__(
        self,
        hidden: jnp.ndarray,
        kv_hidden: jnp.ndarray | None = None,
        bias: jnp.ndarray | None = None,
        *,
        use_cache: bool = False,
        learned_bias: jnp.ndarray | None = None,
        cross_kv: tuple[jnp.ndarray, jnp.ndarray] | None = None,
        deterministic: bool = True,
        cache_positions: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        """``bias``: constant (mask-like) additive bias.  ``learned_bias``:
        the (1, H, Q, K) relative-position bias, kept SEPARATE so the flash
        kernel can treat the mask as constant while computing the learned
        bias's gradient in its dbias kernel.  When the caller pre-combines
        everything into ``bias`` (cache decode, the pipeline adapter), the
        XLA path reproduces round-2 behavior exactly.  ``cross_kv``:
        precomputed ``project_kv`` output — skips the k/v projections.
        ``deterministic`` gates ``config.attn_dropout_rate`` (probs
        dropout; in-kernel mask on the flash path)."""
        q = self._split(self.q_proj(hidden))
        if cross_kv is not None:
            k, v = cross_kv
            if k.shape[0] != hidden.shape[0]:
                # beam decode: beams share the row's cross K/V — one
                # shared fold/unfold convention (ops/attention.py); T5
                # attention is unscaled
                out = beam_grouped_attention(
                    q, k, v, bias, scale=1.0, dtype=self.dtype,
                    learned_bias=learned_bias,
                )
                return self.o_proj(self._merge(out))
        else:
            kv_src = hidden if kv_hidden is None else kv_hidden
            k = self._split(self.k_proj(kv_src))
            v = self._split(self.v_proj(kv_src))
        causal_in_bias = False
        if use_cache and self.causal:
            from distributed_llms_example_tpu.ops.flash_attention import (
                flash_decode_run,
            )
            from distributed_llms_example_tpu.ops.mha import (
                _log_impl_once,
                decode_step_bias,
                select_decode_impl,
            )
            from distributed_llms_example_tpu.parallel.activation import current_mesh

            k, v, k_scale, v_scale, idx = self._cache_kv(k, v, cache_positions)
            kv_len = k.shape[2]
            q_len = q.shape[2]
            offsets = (
                cache_positions
                if cache_positions is not None
                else jnp.full((q.shape[0],), idx, jnp.int32)
            )
            mesh = current_mesh()
            impl, reason = select_decode_impl(
                self.config.attention_impl,
                batch=q.shape[0],
                heads=self.config.num_heads,
                head_dim=self.config.d_kv,
                q_len=q_len,
                kv_len=kv_len,
                mesh=mesh,
                backend=jax.default_backend(),
                device_count=jax.device_count(),
            )
            if (
                impl == "flash_decode"
                and not deterministic
                and float(self.config.attn_dropout_rate) > 0.0
            ):
                # no in-kernel mask stream in the decode kernel: keep the
                # XLA probs-dropout semantics via _attend below
                impl, reason = "xla", "probs dropout requested on cached decode"
            _log_impl_once(f"t5:{impl}", reason)
            if impl == "flash_decode":
                # the decode-step relative-position bias rides ``bias`` as a
                # constant (no gradients in decode); validity/causality ride
                # the kernel's per-row length mask.  T5 scores are unscaled;
                # int8 KV scales dequantize per kv tile inside the kernel.
                out = flash_decode_run(
                    q, k, v, bias, offsets=offsets, mesh=mesh, scale=1.0,
                    k_scale=k_scale, v_scale=v_scale,
                    dtype=self.dtype,
                )
                return self.o_proj(self._merge(out))
            if k_scale is not None:
                # the XLA fallback dequantizes through the IDENTICAL
                # expression the kernel evaluates per tile
                from distributed_llms_example_tpu.ops.flash_attention import (
                    dequantize_kv,
                )

                k = dequantize_kv(k, k_scale)
                v = dequantize_kv(v, v_scale)
            # XLA path: per-row validity+causality mask merged into the bias
            step_bias = decode_step_bias(offsets, q_len, kv_len)
            bias = step_bias if bias is None else bias + step_bias
            causal_in_bias = True
        out = self._attend(
            q, k, v, bias, learned_bias, use_cache, causal_in_bias,
            deterministic,
        )
        return self.o_proj(self._merge(out))

    def _attend(self, q, k, v, bias, learned_bias, use_cache, causal_in_bias,
                deterministic=True):
        """T5 attention is UNSCALED (scale=1.0).  Selection mirrors
        MultiHeadAttention: ring on sequence meshes (cross-attention /
        mask-only biases), Pallas flash on TPU where tileable, XLA
        otherwise.  With a learned bias, multi-device meshes use the
        dedicated sharded path whose hand-written vjp psums dbias across
        batch shards (flash_attention_lbias_sharded)."""
        from distributed_llms_example_tpu.ops.flash_attention import (
            flash_attention_lbias_sharded,
        )
        from distributed_llms_example_tpu.ops.mha import (
            _log_impl_once,
            flash_run,
            select_attention_impl,
        )
        from distributed_llms_example_tpu.ops.ring_attention import ring_attention_sharded
        from distributed_llms_example_tpu.parallel.activation import BATCH_AXES, current_mesh

        causal_here = self.causal and not use_cache and not causal_in_bias
        mesh = current_mesh()
        impl, reason = select_attention_impl(
            self.config.attention_impl,
            batch=q.shape[0],
            heads=self.config.num_heads,
            head_dim=self.config.d_kv,
            q_len=q.shape[2],
            kv_len=k.shape[2],
            use_cache=use_cache,
            mesh=mesh,
            backend=jax.default_backend(),
            device_count=jax.device_count(),
            causal=causal_here,
            bias_kv_only=(
                False
                if learned_bias is not None
                else None if bias is None else (bias.shape[1] == 1 and bias.shape[2] == 1)
            ),
            has_learned_bias=learned_bias is not None,
        )
        _log_impl_once(f"t5:{impl}", reason)
        probs_dropout = (
            float(self.config.attn_dropout_rate) if not deterministic else 0.0
        )
        if impl == "ring":
            if probs_dropout > 0.0:
                raise ValueError(
                    "attn_dropout_rate > 0 is not supported on the ring "
                    "attention path; use attention_impl 'flash'/'xla'"
                )
            return ring_attention_sharded(
                q, k, v, bias, mesh=mesh, causal=causal_here, scale=1.0, dtype=self.dtype
            )
        if impl == "flash":
            seed = None
            if probs_dropout > 0.0:
                from distributed_llms_example_tpu.ops.fused_dropout import (
                    seed_from_key,
                )

                seed = seed_from_key(self.make_rng("dropout"))
            if learned_bias is not None:
                if mesh is not None and math.prod(mesh.devices.shape) > 1:
                    return flash_attention_lbias_sharded(
                        q, k, v, bias, learned_bias, mesh=mesh,
                        batch_axes=tuple(a for a in BATCH_AXES if a in mesh.shape),
                        head_axis="tensor" if "tensor" in mesh.shape else None,
                        causal=causal_here, scale=1.0, dtype=self.dtype,
                        dropout_rate=probs_dropout, dropout_seed=seed,
                    )
                return flash_attention(
                    q, k, v, bias, learned_bias=learned_bias,
                    causal=causal_here, scale=1.0, dtype=self.dtype,
                    dropout_rate=probs_dropout, dropout_seed=seed,
                )
            return flash_run(
                q, k, v, bias, causal=causal_here, mesh=mesh, dtype=self.dtype,
                scale=1.0, dropout_rate=probs_dropout, dropout_seed=seed,
            )
        if causal_here:
            step = make_causal_bias(q.shape[2], k.shape[2])
            bias = step if bias is None else bias + step
        if learned_bias is not None:
            bias = learned_bias if bias is None else bias + learned_bias
        return dot_product_attention(
            q, k, v, bias, scale=1.0, dtype=self.dtype,
            dropout_rate=probs_dropout,
            dropout_rng=self.make_rng("dropout") if probs_dropout > 0.0 else None,
        )


class T5MLP(nn.Module):
    config: T5Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        cfg = self.config
        if cfg.is_gated:
            gate = nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype, name="wi_0")(x)
            lin = nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype, name="wi_1")(x)
            h = nn.gelu(gate, approximate=True) * lin
        else:
            h = nn.relu(nn.Dense(cfg.d_ff, use_bias=False, dtype=self.dtype, name="wi")(x))
        h = Dropout(cfg.dropout_rate)(h, deterministic)
        return nn.Dense(cfg.d_model, use_bias=False, dtype=self.dtype, name="wo")(h)


class T5Block(nn.Module):
    config: T5Config
    causal: bool = False
    has_cross: bool = False
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg = self.config
        eps = cfg.layer_norm_epsilon
        self.self_attn_norm = RMSNorm(epsilon=eps, dtype=self.dtype, name="self_attn_norm")
        self.self_attn = T5Attention(cfg, causal=self.causal, dtype=self.dtype, name="self_attn")
        if self.has_cross:
            self.cross_attn_norm = RMSNorm(epsilon=eps, dtype=self.dtype, name="cross_attn_norm")
            self.cross_attn = T5Attention(cfg, causal=False, dtype=self.dtype, name="cross_attn")
        self.mlp_norm = RMSNorm(epsilon=eps, dtype=self.dtype, name="mlp_norm")
        self.mlp = T5MLP(cfg, dtype=self.dtype, name="mlp")
        self.dropout = Dropout(cfg.dropout_rate)

    def __call__(
        self,
        hidden: jnp.ndarray,
        self_bias: jnp.ndarray | None,
        encoder_hidden: jnp.ndarray | None = None,
        cross_bias: jnp.ndarray | None = None,
        deterministic: bool = True,
        use_cache: bool = False,
        pos_bias: jnp.ndarray | None = None,
        cross_kv=None,
        cache_positions: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        # deterministic/use_cache are positional so nn.remat can mark them
        # static (argnums 5, 6 counting self at 0); pos_bias is the learned
        # relative-position bias kept separate from the (constant) mask in
        # self_bias so the flash kernel can compute its gradient
        h = self.self_attn(
            self.self_attn_norm(hidden), bias=self_bias, use_cache=use_cache,
            learned_bias=pos_bias, deterministic=deterministic,
            cache_positions=cache_positions,
        )
        # residual rides the dropout kernel (one fused pass on TPU)
        hidden = self.dropout(h, deterministic, residual=hidden)
        if self.has_cross:
            h = self.cross_attn(
                self.cross_attn_norm(hidden), kv_hidden=encoder_hidden,
                bias=cross_bias, cross_kv=cross_kv, deterministic=deterministic,
            )
            hidden = self.dropout(h, deterministic, residual=hidden)
        h = self.mlp(self.mlp_norm(hidden), deterministic=deterministic)
        return self.dropout(h, deterministic, residual=hidden)


class T5Stack(nn.Module):
    config: T5Config
    causal: bool = False  # True → decoder (causal self-attn + cross-attn)
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    remat_policy: str = "full"  # "full" | "dots" (utils/remat.py)

    def setup(self) -> None:
        cfg = self.config
        n = cfg.decoder_layers if self.causal else cfg.num_layers
        self.relative_attention_bias = nn.Embed(
            cfg.relative_attention_num_buckets,
            cfg.num_heads,
            dtype=jnp.float32,
            name="relative_attention_bias",
        )
        block = T5Block
        if self.remat:
            block = remat_block(T5Block, (5, 6), self.remat_policy)
        self.blocks = [
            block(cfg, causal=self.causal, has_cross=self.causal, dtype=self.dtype, name=f"block_{i}")
            for i in range(n)
        ]
        self.final_norm = RMSNorm(epsilon=cfg.layer_norm_epsilon, dtype=self.dtype, name="final_norm")
        self.dropout = Dropout(cfg.dropout_rate)

    def position_bias(self, q_len: int, kv_len: int, offset: int | jnp.ndarray = 0) -> jnp.ndarray:
        """(1, heads, q_len, kv_len) additive relative-position bias.

        ``offset`` may be a (B,) array — per-ROW decode offsets for
        continuous-batching slots, yielding a (B, heads, q_len, kv_len)
        bias (each slot's relative positions computed against its own
        cache offset)."""
        cfg = self.config
        off = jnp.asarray(offset)
        if off.ndim == 1:
            q_pos = off[:, None, None] + jnp.arange(q_len)[None, :, None]  # (B, q, 1)
            rel = jnp.arange(kv_len)[None, None, :] - q_pos  # (B, q, kv)
        else:
            q_pos = jnp.arange(q_len)[:, None] + off
            rel = jnp.arange(kv_len)[None, :] - q_pos  # (q, kv)
        buckets = relative_position_bucket(
            rel,
            bidirectional=not self.causal,
            num_buckets=cfg.relative_attention_num_buckets,
            max_distance=cfg.relative_attention_max_distance,
        )
        bias = self.relative_attention_bias(buckets)  # (..., q, kv, heads)
        if off.ndim == 1:
            return bias.transpose(0, 3, 1, 2).astype(self.dtype)
        return bias.transpose(2, 0, 1)[None].astype(self.dtype)

    def __call__(
        self,
        hidden: jnp.ndarray,
        attention_mask: jnp.ndarray | None = None,
        encoder_hidden: jnp.ndarray | None = None,
        encoder_mask: jnp.ndarray | None = None,
        *,
        deterministic: bool = True,
        use_cache: bool = False,
        cache_offset: int | jnp.ndarray = 0,
        max_kv_len: int | None = None,
        cross_kv=None,
    ) -> jnp.ndarray:
        q_len = hidden.shape[1]
        pos_bias = None
        cache_positions = None
        if use_cache and self.causal:
            # Incremental decoding: relative bias of the current step(s)
            # against the full cache buffer (max_kv_len); masking of not-yet-
            # written cache slots + causality is added inside T5Attention.
            # The learned bias rides the combined constant-treated bias on
            # both decode impls (XLA merged mask, flash_decode additive
            # input) — no gradients in decode.  A (B,) ``cache_offset``
            # is the continuous-batching form: per-SLOT offsets, per-row
            # position bias and per-row cache writes.
            if max_kv_len is None:
                raise ValueError("max_kv_len is required when decoding with a cache")
            if getattr(jnp.asarray(cache_offset), "ndim", 0) == 1:
                cache_positions = jnp.asarray(cache_offset, jnp.int32)
            self_bias = self.position_bias(q_len, max_kv_len, offset=cache_offset)
        else:
            # keep the LEARNED bias separate from the constant mask:
            # T5Attention routes it through the flash kernel's
            # differentiable learned_bias input (causality is the
            # attention impl's job — flash applies it natively)
            pos_bias = self.position_bias(q_len, q_len)
            self_bias = mask_to_bias(attention_mask) if attention_mask is not None else None
        cross_bias = mask_to_bias(encoder_mask) if encoder_mask is not None else None
        hidden = self.dropout(hidden, deterministic=deterministic)
        for i, blk in enumerate(self.blocks):
            # re-anchor the residual stream every layer so GSPMD never
            # propagates a param sharding (d_model over fsdp/tensor) into it
            hidden = constrain_hidden(
                blk(hidden, self_bias, encoder_hidden, cross_bias, deterministic, use_cache, pos_bias,
                    cross_kv=None if cross_kv is None else cross_kv[i],
                    cache_positions=cache_positions)
            )
        return self.dropout(self.final_norm(hidden), deterministic=deterministic)


class T5ForConditionalGeneration(nn.Module):
    """Full seq2seq model: encode + decode + LM head."""

    config: T5Config
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    remat_policy: str = "full"  # "full" | "dots" (utils/remat.py)

    def setup(self) -> None:
        cfg = self.config
        self.shared = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            embedding_init=nn.initializers.normal(1.0),
            dtype=self.dtype,
            name="shared",
        )
        self.encoder = T5Stack(cfg, causal=False, dtype=self.dtype, remat=self.remat,
                               remat_policy=self.remat_policy, name="encoder")
        self.decoder = T5Stack(cfg, causal=True, dtype=self.dtype, remat=self.remat,
                               remat_policy=self.remat_policy, name="decoder")
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=self.dtype, name="lm_head")

    def encode(
        self, input_ids: jnp.ndarray, attention_mask: jnp.ndarray | None = None, *, deterministic: bool = True
    ) -> jnp.ndarray:
        return self.encoder(
            constrain_hidden(self.shared(input_ids)),
            attention_mask=attention_mask,
            deterministic=deterministic,
        )

    def _logits(self, hidden: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        if cfg.tie_word_embeddings:
            hidden = hidden * (cfg.d_model**-0.5)
            return constrain_logits(hidden @ self.shared.embedding.astype(self.dtype).T)
        return constrain_logits(self.lm_head(hidden))

    def cross_kv(self, encoder_hidden: jnp.ndarray):
        """Per-decoder-layer cross-attention K/V, projected ONCE from the
        encoder output (see BartForConditionalGeneration.cross_kv)."""
        return tuple(
            blk.cross_attn.project_kv(encoder_hidden) for blk in self.decoder.blocks
        )

    def decode(
        self,
        decoder_input_ids: jnp.ndarray,
        encoder_hidden: jnp.ndarray,
        encoder_mask: jnp.ndarray | None = None,
        decoder_attention_mask: jnp.ndarray | None = None,
        *,
        deterministic: bool = True,
        use_cache: bool = False,
        cache_offset: int | jnp.ndarray = 0,
        max_kv_len: int | None = None,
        cross_kv=None,
    ) -> jnp.ndarray:
        hidden = constrain_hidden(self.shared(decoder_input_ids))
        if use_cache:
            hidden = self.decoder(
                hidden,
                encoder_hidden=encoder_hidden,
                encoder_mask=encoder_mask,
                deterministic=deterministic,
                use_cache=True,
                cache_offset=cache_offset,
                max_kv_len=max_kv_len,
                cross_kv=cross_kv,
            )
        else:
            hidden = self.decoder(
                hidden,
                attention_mask=decoder_attention_mask,
                encoder_hidden=encoder_hidden,
                encoder_mask=encoder_mask,
                deterministic=deterministic,
            )
        return self._logits(hidden)

    def __call__(
        self,
        input_ids: jnp.ndarray,
        attention_mask: jnp.ndarray | None = None,
        decoder_input_ids: jnp.ndarray | None = None,
        decoder_attention_mask: jnp.ndarray | None = None,
        *,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        enc = self.encode(input_ids, attention_mask, deterministic=deterministic)
        return self.decode(
            decoder_input_ids,
            enc,
            encoder_mask=attention_mask,
            decoder_attention_mask=decoder_attention_mask,
            deterministic=deterministic,
        )


def shift_right(labels: jnp.ndarray, decoder_start_token_id: int, pad_token_id: int) -> jnp.ndarray:
    """Teacher-forcing decoder inputs from labels (HF shift_tokens_right
    semantics: -100 label positions become pad)."""
    shifted = jnp.roll(labels, 1, axis=-1).at[:, 0].set(decoder_start_token_id)
    return jnp.where(shifted == -100, pad_token_id, shifted)


class PipelinedT5:
    """Train-time ``apply()`` adapter running both T5 stacks as GPipe
    pipelines over ``stage`` (parallel/pipeline.py; see ``PipelinedBart``
    for the twin-pipeline shape).  The learned relative-position bias is
    computed OUTSIDE the pipelines directly from each stack's bucket table
    — one (1, heads, q, kv) tensor per stack, entering the stage loop as a
    replicated per-call extra, so the bias table itself still receives
    gradient through the bucket lookup.  Param tree:
    ``stack_for_family("t5", ...)`` (each stack's blocks stacked under
    ``{encoder,decoder}/stacked_blocks``).  Dropout supported (key folded
    per microbatch/stage/layer, see PipelinedBart); training +
    teacher-forced scoring only.
    """

    def __init__(self, config: T5Config, mesh, dtype=jnp.float32,
                 num_microbatches: int = 0, remat: bool = True,
                 schedule: str = "gpipe"):
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}: must be gpipe, "
                "1f1b, or interleaved"
            )
        # known-bad combos are rows in the composition matrix
        # (analysis/composition.py): 1f1b×fsdp partitioner crash,
        # interleaved (decoder-only), sequence parallelism
        from distributed_llms_example_tpu.analysis.composition import (
            validate_composition,
        )

        validate_composition(
            family="t5", schedule=schedule, mesh_axes=dict(mesh.shape),
            flags=("pipelined",),
        )
        stages = mesh.shape.get("stage", 1)
        for n, what in ((config.num_layers, "encoder"), (config.decoder_layers, "decoder")):
            if n % max(stages, 1):
                raise ValueError(f"{n} {what} layers not divisible into {stages} stages")
        self.config = config
        self.mesh = mesh
        self.dtype = dtype
        self.num_microbatches = num_microbatches or max(stages, 1)
        self.remat = remat
        self.pipeline_schedule = schedule
        cfg = config
        self._shared = nn.Embed(
            cfg.vocab_size, cfg.d_model, embedding_init=nn.initializers.normal(1.0), dtype=dtype
        )
        self._enc_block = T5Block(cfg, causal=False, has_cross=False, dtype=dtype)
        self._dec_block = T5Block(cfg, causal=True, has_cross=True, dtype=dtype)
        self._norm = RMSNorm(epsilon=cfg.layer_norm_epsilon, dtype=dtype)
        if not cfg.tie_word_embeddings:
            self._head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=dtype)

    def _position_bias(self, table: jnp.ndarray, q_len: int, causal: bool) -> jnp.ndarray:
        """(1, heads, q, q) additive bias from a stack's bucket table —
        the functional twin of T5Stack.position_bias."""
        cfg = self.config
        rel = jnp.arange(q_len)[None, :] - jnp.arange(q_len)[:, None]
        buckets = relative_position_bucket(
            rel,
            bidirectional=not causal,
            num_buckets=cfg.relative_attention_num_buckets,
            max_distance=cfg.relative_attention_max_distance,
        )
        bias = jnp.take(table, buckets, axis=0)  # (q, kv, heads)
        return bias.transpose(2, 0, 1)[None].astype(self.dtype)

    def _dropout(self, x, key):
        from distributed_llms_example_tpu.parallel.pipeline import dropout

        return dropout(x, key, self.config.dropout_rate)

    def make_value_and_grad(self, label_smoothing: float = 0.0,
                            is_seq2seq: bool = True):
        """Twin-pipeline 1F1B training path (see ``PipelinedBart`` for the
        shape).  T5's extra structure maps onto the fused executor's hooks:
        the encoder's final-norm + dropout become the SEAM transform
        (applied once per microbatch where the encoder output enters the
        decoder pipeline, differentiated for the norm scale's gradient);
        the learned relative-position biases ride ``diff_extras`` — the
        executor accumulates their cotangents across every (chunk,
        microbatch) vjp, and the bucket tables get their gradients through
        an outer ``jax.vjp`` of the bias construction."""
        from distributed_llms_example_tpu.parallel.activation import activation_mesh
        from distributed_llms_example_tpu.parallel.pipeline_seq2seq import (
            pipeline_value_and_grad_seq2seq,
        )
        from distributed_llms_example_tpu.train.step import cross_entropy_sums

        assert is_seq2seq
        cfg = self.config

        def post_loss(pp, y, mb, key):
            # decoder tail: final_norm + dropout (T5Stack's trailing
            # dropout) + (tied-scaled) logits projection
            h = self._norm.apply({"params": pp["final_norm"]}, y["dec"])
            if key is not None:
                # post_loss runs INSIDE the pipeline shard_map: clear the
                # ambient mesh (like the block fns) so the shared dropout
                # helper takes its no-mesh XLA path instead of nesting a
                # shard_map in the manual region
                with activation_mesh(None):
                    h = self._dropout(h, jax.random.fold_in(key, 555))
            if cfg.tie_word_embeddings:
                h = h * (cfg.d_model**-0.5)
                logits = h @ pp["shared"]["embedding"].astype(self.dtype).T
            else:
                logits = self._head.apply({"params": pp["lm_head"]}, h)
            return cross_entropy_sums(logits, mb["labels"], label_smoothing)

        def seam(sp, h, key):
            # encoder tail between the pipelines: final_norm + dropout
            # (runs inside the pipeline shard_map — same ambient-mesh
            # reset as post_loss/the block fns)
            h = self._norm.apply({"params": sp["final_norm"]}, h)
            if key is not None:
                with activation_mesh(None):
                    h = self._dropout(h, key)
            return h

        def enc_fn(lp, h, ex, key=None):
            with activation_mesh(None):
                if key is None:
                    return self._enc_block.apply(
                        {"params": lp}, h, ex.get("src_bias"), None, None,
                        True, False, ex.get("enc_pos"),
                    )
                return self._enc_block.apply(
                    {"params": lp}, h, ex.get("src_bias"), None, None,
                    False, False, ex.get("enc_pos"), rngs={"dropout": key},
                )

        def dec_fn(lp, h, ex, key=None):
            with activation_mesh(None):
                if key is None:
                    return self._dec_block.apply(
                        {"params": lp}, h, None, ex["enc"], ex.get("src_bias"),
                        True, False, ex.get("dec_pos"),
                    )
                return self._dec_block.apply(
                    {"params": lp}, h, None, ex["enc"], ex.get("src_bias"),
                    False, False, ex.get("dec_pos"), rngs={"dropout": key},
                )

        def value_and_grad_sums(params, batch, rng=None):
            labels = batch["labels"]
            dec_ids = shift_right(labels, cfg.decoder_start_token_id, cfg.pad_token_id)

            def embed_all(shared_p):
                eh = constrain_hidden(
                    self._shared.apply({"params": shared_p}, batch["input_ids"])
                )
                dh = constrain_hidden(self._shared.apply({"params": shared_p}, dec_ids))
                # T5Stack applies dropout to the embedded input of each stack
                if rng is not None:
                    eh = self._dropout(eh, jax.random.fold_in(rng, 201))
                    dh = self._dropout(dh, jax.random.fold_in(rng, 202))
                return eh, dh

            (enc_h, dec_h), embed_vjp = jax.vjp(embed_all, params["shared"])

            def pos_biases(tables):
                et, dt = tables
                return (
                    self._position_bias(et, batch["input_ids"].shape[1], causal=False),
                    self._position_bias(dt, dec_ids.shape[1], causal=True),
                )

            (enc_pos, dec_pos), pos_vjp = jax.vjp(
                pos_biases,
                (
                    params["encoder"]["relative_attention_bias"]["embedding"],
                    params["decoder"]["relative_attention_bias"]["embedding"],
                ),
            )
            src_bias = (
                mask_to_bias(batch["attention_mask"])
                if batch.get("attention_mask") is not None else None
            )
            extras = {} if src_bias is None else {"src_bias": src_bias}
            post_params = {"final_norm": params["decoder"]["final_norm"]}
            if cfg.tie_word_embeddings:
                post_params["shared"] = params["shared"]
            else:
                post_params["lm_head"] = params["lm_head"]
            seam_params = {"final_norm": params["encoder"]["final_norm"]}
            (lsum, tokens, d_se, d_sd, d_pp, d_seam, d_dex, d_eh, d_dh) = (
                pipeline_value_and_grad_seq2seq(
                    enc_fn, dec_fn, post_loss,
                    params["encoder"]["stacked_blocks"],
                    params["decoder"]["stacked_blocks"],
                    post_params, enc_h, dec_h, extras, {"labels": labels},
                    mesh=self.mesh, num_microbatches=self.num_microbatches,
                    seam_fn=seam, seam_params=seam_params,
                    diff_extras={"enc_pos": enc_pos, "dec_pos": dec_pos},
                    checkpoint=self.remat,
                    rng=None if rng is None else jax.random.fold_in(rng, 7),
                )
            )
            (d_embed,) = embed_vjp((d_eh.astype(enc_h.dtype), d_dh.astype(dec_h.dtype)))
            ((d_enc_table, d_dec_table),) = pos_vjp(
                (d_dex["enc_pos"].astype(enc_pos.dtype), d_dex["dec_pos"].astype(dec_pos.dtype))
            )
            d_shared = d_embed
            if cfg.tie_word_embeddings:
                d_shared = jax.tree.map(jnp.add, d_shared, d_pp["shared"])
            grads = {
                "shared": d_shared,
                "encoder": {
                    "stacked_blocks": d_se,
                    "final_norm": d_seam["final_norm"],
                    "relative_attention_bias": {"embedding": d_enc_table},
                },
                "decoder": {
                    "stacked_blocks": d_sd,
                    "final_norm": d_pp["final_norm"],
                    "relative_attention_bias": {"embedding": d_dec_table},
                },
            }
            if not cfg.tie_word_embeddings:
                grads["lm_head"] = d_pp["lm_head"]
            return lsum, tokens, grads

        return value_and_grad_sums

    def _run_stack(self, stack_params, block, hidden, self_bias, pos_bias, extras,
                   rng=None):
        from distributed_llms_example_tpu.parallel.activation import activation_mesh
        from distributed_llms_example_tpu.parallel.pipeline import pipeline_apply

        ex = {k: v for k, v in extras.items() if v is not None}
        if self_bias is not None:
            ex["self_bias"] = self_bias
        if pos_bias is not None:
            # the LEARNED bias rides its own slot all the way into
            # T5Attention.learned_bias — pre-combining it into the constant
            # mask would zero its gradient on any flash-selected path
            ex["pos_bias"] = pos_bias

        # T5Stack applies dropout on the embedded input and after the
        # final norm; mirror that around the pipeline
        if rng is not None:
            hidden = self._dropout(hidden, jax.random.fold_in(rng, 101))

        def layer_fn(lp, h, e, key=None):
            with activation_mesh(None):
                if key is None:
                    return block.apply(
                        {"params": lp}, h, e.get("self_bias"), e.get("enc"),
                        e.get("cross_bias"), True, False, e.get("pos_bias"),
                    )
                return block.apply(
                    {"params": lp}, h, e.get("self_bias"), e.get("enc"),
                    e.get("cross_bias"), False, False, e.get("pos_bias"),
                    rngs={"dropout": key},
                )

        hidden = pipeline_apply(
            layer_fn, stack_params["stacked_blocks"], hidden, ex,
            mesh=self.mesh, num_microbatches=self.num_microbatches, checkpoint=self.remat,
            rng=rng,
        )
        hidden = self._norm.apply({"params": stack_params["final_norm"]}, hidden)
        if rng is not None:
            hidden = self._dropout(hidden, jax.random.fold_in(rng, 102))
        return hidden

    def apply(self, variables, input_ids, attention_mask=None, decoder_input_ids=None,
              decoder_attention_mask=None, *, deterministic: bool = True, rngs=None):
        p = variables["params"]
        cfg = self.config
        rng = None
        if not deterministic and rngs and "dropout" in rngs and cfg.dropout_rate > 0:
            rng = rngs["dropout"]
        shared = lambda ids: constrain_hidden(  # noqa: E731
            self._shared.apply({"params": p["shared"]}, ids)
        )

        q_len = input_ids.shape[1]
        enc_table = p["encoder"]["relative_attention_bias"]["embedding"]
        enc_pos = self._position_bias(enc_table, q_len, causal=False)
        enc_mask = mask_to_bias(attention_mask) if attention_mask is not None else None
        enc = self._run_stack(
            p["encoder"], self._enc_block, shared(input_ids), enc_mask, enc_pos, {},
            rng=None if rng is None else jax.random.fold_in(rng, 0),
        )

        d_len = decoder_input_ids.shape[1]
        dec_table = p["decoder"]["relative_attention_bias"]["embedding"]
        dec_pos = self._position_bias(dec_table, d_len, causal=True)
        # causality is the attention impl's job (T5Block's decoder
        # self-attention has causal=True); only the padding mask goes in
        dec_mask = (
            mask_to_bias(decoder_attention_mask) if decoder_attention_mask is not None else None
        )
        cross_bias = mask_to_bias(attention_mask) if attention_mask is not None else None
        hidden = self._run_stack(
            p["decoder"], self._dec_block, shared(decoder_input_ids), dec_mask, dec_pos,
            {"enc": enc, "cross_bias": cross_bias},
            rng=None if rng is None else jax.random.fold_in(rng, 1),
        )
        if cfg.tie_word_embeddings:
            hidden = hidden * (cfg.d_model**-0.5)
            return constrain_logits(hidden @ p["shared"]["embedding"].astype(self.dtype).T)
        return constrain_logits(self._head.apply({"params": p["lm_head"]}, hidden))
