"""HF PyTorch checkpoint → framework parameter tree conversion.

The reference gets weights through ``AutoModelForSeq2SeqLM.from_pretrained``
(reference train-torchrun.py:35); this framework has its own model
definitions, so checkpoints are converted once at load time: torch tensors
→ numpy, ``nn.Linear`` weights transposed (torch stores (out, in), flax
kernels are (in, out)), names remapped per model family.

Works on a raw ``state_dict`` (no torch model construction needed), so it
also serves local directories containing ``pytorch_model.bin`` or
``model.safetensors``.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Mapping

import numpy as np


def _t(x: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(x.T)


def _to_numpy(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def _set(tree: dict, path: str, value: np.ndarray) -> None:
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


# --- T5 -------------------------------------------------------------------

_T5_LAYER = {
    ("0", "SelfAttention"): "self_attn",
    ("0", "layer_norm"): "self_attn_norm",
    ("1", "EncDecAttention"): "cross_attn",
    ("1", "layer_norm"): None,  # resolved by context: mlp_norm in encoder, cross_attn_norm in decoder
    ("2", "DenseReluDense"): "mlp",
    ("2", "layer_norm"): "mlp_norm",
    ("1", "DenseReluDense"): "mlp",
}

_T5_PROJ = {"q": "q_proj", "k": "k_proj", "v": "v_proj", "o": "o_proj"}


def convert_t5_state_dict(state_dict: Mapping[str, Any]) -> dict:
    """HF ``T5ForConditionalGeneration`` state_dict → our param tree."""
    params: dict = {}
    for name, tensor in state_dict.items():
        arr = _to_numpy(tensor)
        if name == "shared.weight":
            _set(params, "shared/embedding", arr)
            continue
        if name == "lm_head.weight":
            _set(params, "lm_head/kernel", _t(arr))
            continue
        m = re.match(r"(encoder|decoder)\.final_layer_norm\.weight", name)
        if m:
            _set(params, f"{m.group(1)}/final_norm/scale", arr)
            continue
        m = re.match(r"(encoder|decoder)\.embed_tokens\.weight", name)
        if m:
            continue  # duplicate of shared.weight
        m = re.match(
            r"(encoder|decoder)\.block\.(\d+)\.layer\.(\d+)\.(SelfAttention|EncDecAttention|"
            r"DenseReluDense|layer_norm)\.(.+)",
            name,
        )
        if not m:
            raise ValueError(f"unrecognized T5 parameter: {name}")
        stack, block, layer_idx, kind, rest = m.groups()
        is_decoder = stack == "decoder"
        if kind == "SelfAttention" and rest == "relative_attention_bias.weight":
            _set(params, f"{stack}/relative_attention_bias/embedding", arr)
            continue
        if kind in ("SelfAttention", "EncDecAttention"):
            sub = "self_attn" if kind == "SelfAttention" else "cross_attn"
            proj, _, leaf = rest.partition(".")
            _set(params, f"{stack}/block_{block}/{sub}/{_T5_PROJ[proj]}/kernel", _t(arr))
            continue
        if kind == "DenseReluDense":
            proj, _, leaf = rest.partition(".")
            _set(params, f"{stack}/block_{block}/mlp/{proj}/kernel", _t(arr))
            continue
        # layer_norm: position depends on stack layout
        if layer_idx == "0":
            sub = "self_attn_norm"
        elif layer_idx == "1":
            sub = "cross_attn_norm" if is_decoder else "mlp_norm"
        else:
            sub = "mlp_norm"
        _set(params, f"{stack}/block_{block}/{sub}/scale", arr)
    return params


# --- generic entry point --------------------------------------------------

CONVERTERS: dict[str, Callable[[Mapping[str, Any]], dict]] = {
    "t5": convert_t5_state_dict,
}


def convert_state_dict(family: str, state_dict: Mapping[str, Any]) -> dict:
    try:
        conv = CONVERTERS[family]
    except KeyError:
        raise ValueError(f"no converter for model family {family!r}; have {sorted(CONVERTERS)}") from None
    return conv(state_dict)
