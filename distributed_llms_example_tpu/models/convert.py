"""HF PyTorch checkpoint → framework parameter tree conversion.

The reference gets weights through ``AutoModelForSeq2SeqLM.from_pretrained``
(reference train-torchrun.py:35); this framework has its own model
definitions, so checkpoints are converted once at load time: torch tensors
→ numpy, ``nn.Linear`` weights transposed (torch stores (out, in), flax
kernels are (in, out)), names remapped per model family.

Works on a raw ``state_dict`` (no torch model construction needed), so it
also serves local directories containing ``pytorch_model.bin`` or
``model.safetensors``.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Mapping

import numpy as np


def _t(x: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(x.T)


def _to_numpy(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t)


def _set(tree: dict, path: str, value: np.ndarray) -> None:
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


# --- T5 -------------------------------------------------------------------

_T5_LAYER = {
    ("0", "SelfAttention"): "self_attn",
    ("0", "layer_norm"): "self_attn_norm",
    ("1", "EncDecAttention"): "cross_attn",
    ("1", "layer_norm"): None,  # resolved by context: mlp_norm in encoder, cross_attn_norm in decoder
    ("2", "DenseReluDense"): "mlp",
    ("2", "layer_norm"): "mlp_norm",
    ("1", "DenseReluDense"): "mlp",
}

_T5_PROJ = {"q": "q_proj", "k": "k_proj", "v": "v_proj", "o": "o_proj"}


def convert_t5_state_dict(state_dict: Mapping[str, Any]) -> dict:
    """HF ``T5ForConditionalGeneration`` state_dict → our param tree."""
    params: dict = {}
    for name, tensor in state_dict.items():
        arr = _to_numpy(tensor)
        if name == "shared.weight":
            _set(params, "shared/embedding", arr)
            continue
        if name == "lm_head.weight":
            _set(params, "lm_head/kernel", _t(arr))
            continue
        m = re.match(r"(encoder|decoder)\.final_layer_norm\.weight", name)
        if m:
            _set(params, f"{m.group(1)}/final_norm/scale", arr)
            continue
        m = re.match(r"(encoder|decoder)\.embed_tokens\.weight", name)
        if m:
            continue  # duplicate of shared.weight
        m = re.match(
            r"(encoder|decoder)\.block\.(\d+)\.layer\.(\d+)\.(SelfAttention|EncDecAttention|"
            r"DenseReluDense|layer_norm)\.(.+)",
            name,
        )
        if not m:
            raise ValueError(f"unrecognized T5 parameter: {name}")
        stack, block, layer_idx, kind, rest = m.groups()
        is_decoder = stack == "decoder"
        if kind == "SelfAttention" and rest == "relative_attention_bias.weight":
            _set(params, f"{stack}/relative_attention_bias/embedding", arr)
            continue
        if kind in ("SelfAttention", "EncDecAttention"):
            sub = "self_attn" if kind == "SelfAttention" else "cross_attn"
            proj, _, leaf = rest.partition(".")
            _set(params, f"{stack}/block_{block}/{sub}/{_T5_PROJ[proj]}/kernel", _t(arr))
            continue
        if kind == "DenseReluDense":
            proj, _, leaf = rest.partition(".")
            _set(params, f"{stack}/block_{block}/mlp/{proj}/kernel", _t(arr))
            continue
        # layer_norm: position depends on stack layout
        if layer_idx == "0":
            sub = "self_attn_norm"
        elif layer_idx == "1":
            sub = "cross_attn_norm" if is_decoder else "mlp_norm"
        else:
            sub = "mlp_norm"
        _set(params, f"{stack}/block_{block}/{sub}/scale", arr)
    return params


# --- BART -----------------------------------------------------------------

_BART_ATTN = {"q_proj": "q_proj", "k_proj": "k_proj", "v_proj": "v_proj", "out_proj": "o_proj"}
_BART_SUB = {"self_attn": "self_attn", "encoder_attn": "cross_attn"}
_BART_NORM = {
    "self_attn_layer_norm": "self_attn_layer_norm",
    "encoder_attn_layer_norm": "cross_attn_layer_norm",
    "final_layer_norm": "final_layer_norm",
}


def convert_bart_state_dict(state_dict: Mapping[str, Any]) -> dict:
    """HF ``BartForConditionalGeneration`` state_dict → our param tree."""
    params: dict = {}
    for name, tensor in state_dict.items():
        arr = _to_numpy(tensor)
        name = name.removeprefix("model.")
        if name == "shared.weight":
            _set(params, "shared/embedding", arr)
            continue
        if name in ("encoder.embed_tokens.weight", "decoder.embed_tokens.weight", "lm_head.weight"):
            continue  # tied duplicates of shared.weight
        if name == "final_logits_bias":
            _set(params, "final_logits_bias", arr.reshape(-1))
            continue
        m = re.match(r"(encoder|decoder)\.embed_positions\.weight", name)
        if m:
            _set(params, f"{m.group(1)}_embed_positions/embedding", arr)
            continue
        m = re.match(r"(encoder|decoder)\.layernorm_embedding\.(weight|bias)", name)
        if m:
            leaf = "scale" if m.group(2) == "weight" else "bias"
            _set(params, f"{m.group(1)}_layernorm_embedding/{leaf}", arr)
            continue
        m = re.match(r"(encoder|decoder)\.layers\.(\d+)\.(.+)", name)
        if not m:
            raise ValueError(f"unrecognized BART parameter: {name}")
        stack, i, rest = m.groups()
        prefix = f"{stack}_block_{i}"
        m = re.match(r"(self_attn|encoder_attn)\.(q_proj|k_proj|v_proj|out_proj)\.(weight|bias)", rest)
        if m:
            sub, proj, kind = m.groups()
            leaf = "kernel" if kind == "weight" else "bias"
            val = _t(arr) if kind == "weight" else arr
            _set(params, f"{prefix}/{_BART_SUB[sub]}/{_BART_ATTN[proj]}/{leaf}", val)
            continue
        m = re.match(r"(fc1|fc2)\.(weight|bias)", rest)
        if m:
            proj, kind = m.groups()
            leaf = "kernel" if kind == "weight" else "bias"
            _set(params, f"{prefix}/mlp/{proj}/{leaf}", _t(arr) if kind == "weight" else arr)
            continue
        m = re.match(r"(self_attn_layer_norm|encoder_attn_layer_norm|final_layer_norm)\.(weight|bias)", rest)
        if m:
            norm, kind = m.groups()
            leaf = "scale" if kind == "weight" else "bias"
            _set(params, f"{prefix}/{_BART_NORM[norm]}/{leaf}", arr)
            continue
        raise ValueError(f"unrecognized BART layer parameter: {name}")
    return params


# --- LLaMA ----------------------------------------------------------------


def convert_llama_state_dict(state_dict: Mapping[str, Any]) -> dict:
    """HF ``LlamaForCausalLM`` / ``MixtralForCausalLM`` state_dict → our
    param tree.  Mixtral's per-expert ``block_sparse_moe.experts.{j}.w1/w2/w3``
    linears are stacked into our (E, d_in, d_out) expert tensors
    (w1→gate_proj, w3→up_proj, w2→down_proj) and the router gate transposes
    into ``mlp/router/kernel``."""
    params: dict = {}
    # (block prefix, w-index) → {expert index: transposed weight}
    experts: dict[tuple, dict[int, Any]] = {}
    for name, tensor in state_dict.items():
        if name.endswith("rotary_emb.inv_freq"):
            continue  # derived buffer
        arr = _to_numpy(tensor)
        if name == "model.embed_tokens.weight":
            _set(params, "embed_tokens/embedding", arr)
            continue
        if name == "model.norm.weight":
            _set(params, "final_norm/scale", arr)
            continue
        if name == "lm_head.weight":
            _set(params, "lm_head/kernel", _t(arr))
            continue
        m = re.match(r"model\.layers\.(\d+)\.(.+)", name)
        if not m:
            raise ValueError(f"unrecognized LLaMA parameter: {name}")
        i, rest = m.groups()
        prefix = f"block_{i}"
        m = re.match(r"self_attn\.(q|k|v|o)_proj\.weight", rest)
        if m:
            _set(params, f"{prefix}/self_attn/{m.group(1)}_proj/kernel", _t(arr))
            continue
        m = re.match(r"mlp\.(gate|up|down)_proj\.weight", rest)
        if m:
            _set(params, f"{prefix}/mlp/{m.group(1)}_proj/kernel", _t(arr))
            continue
        if rest == "block_sparse_moe.gate.weight":
            _set(params, f"{prefix}/mlp/router/kernel", _t(arr))
            continue
        m = re.match(r"block_sparse_moe\.experts\.(\d+)\.w([123])\.weight", rest)
        if m:
            experts.setdefault((prefix, m.group(2)), {})[int(m.group(1))] = _t(arr)
            continue
        if rest == "input_layernorm.weight":
            _set(params, f"{prefix}/attn_norm/scale", arr)
            continue
        if rest == "post_attention_layernorm.weight":
            _set(params, f"{prefix}/mlp_norm/scale", arr)
            continue
        raise ValueError(f"unrecognized LLaMA layer parameter: {name}")
    w_names = {"1": "gate_proj", "3": "up_proj", "2": "down_proj"}
    for (prefix, w), per_expert in experts.items():
        stacked = np.stack([per_expert[j] for j in range(len(per_expert))])
        _set(params, f"{prefix}/mlp/{w_names[w]}", stacked)
    return params


# --- generic entry point --------------------------------------------------

CONVERTERS: dict[str, Callable[[Mapping[str, Any]], dict]] = {
    "t5": convert_t5_state_dict,
    "bart": convert_bart_state_dict,
    "llama": convert_llama_state_dict,
    "mixtral": convert_llama_state_dict,  # llama blocks + stacked experts
}


def convert_state_dict(family: str, state_dict: Mapping[str, Any]) -> dict:
    try:
        conv = CONVERTERS[family]
    except KeyError:
        raise ValueError(f"no converter for model family {family!r}; have {sorted(CONVERTERS)}") from None
    return conv(state_dict)
