"""BART seq2seq in flax.linen — the reference's flagship model family
(``facebook/bart-large-cnn``, reference valohai.yaml:10).

Architecture facts matched against HF ``BartForConditionalGeneration``
(verified by parity tests): post-layernorm residual blocks, learned
positional embeddings with the +2 offset quirk, optional sqrt(d) embedding
scale, gelu FFN, biased attention/FFN projections, tied LM head plus
``final_logits_bias``, decoder starts at EOS with a forced BOS first token
for the -cnn checkpoints.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.ops.attention import mask_to_bias
from distributed_llms_example_tpu.ops.fused_dropout import Dropout
from distributed_llms_example_tpu.utils.remat import remat_block
from distributed_llms_example_tpu.ops.mha import MultiHeadAttention
from distributed_llms_example_tpu.ops.norms import LayerNorm
from distributed_llms_example_tpu.parallel.activation import constrain_hidden, constrain_logits


@dataclasses.dataclass(frozen=True)
class BartConfig:
    vocab_size: int = 50265
    d_model: int = 1024
    encoder_layers: int = 12
    decoder_layers: int = 12
    encoder_attention_heads: int = 16
    decoder_attention_heads: int = 16
    encoder_ffn_dim: int = 4096
    decoder_ffn_dim: int = 4096
    max_position_embeddings: int = 1024
    dropout_rate: float = 0.1
    # HF ``attention_dropout`` (probs dropout; bart-large ships 0.0).
    # Rides the flash kernels' in-kernel mask stream when > 0.
    attn_dropout_rate: float = 0.0
    scale_embedding: bool = False
    pad_token_id: int = 1
    bos_token_id: int = 0
    eos_token_id: int = 2
    decoder_start_token_id: int = 2
    forced_bos_token_id: Optional[int] = None
    forced_eos_token_id: Optional[int] = 2  # HF BART default: force EOS at max length
    layer_norm_epsilon: float = 1e-5
    attention_impl: str = "auto"  # "auto" | "flash" | "xla" (see ops/mha.py)

    POSITION_OFFSET = 2  # HF BartLearnedPositionalEmbedding quirk

    @property
    def head_dim(self) -> int:
        return self.d_model // self.encoder_attention_heads

    @property
    def embed_scale(self) -> float:
        return self.d_model**0.5 if self.scale_embedding else 1.0


class BartEncoderLayer(nn.Module):
    config: BartConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg = self.config
        self.self_attn = MultiHeadAttention(
            num_heads=cfg.encoder_attention_heads,
            head_dim=cfg.d_model // cfg.encoder_attention_heads,
            model_dim=cfg.d_model,
            use_bias=True,
            dtype=self.dtype,
            attention_impl=cfg.attention_impl,
            probs_dropout_rate=cfg.attn_dropout_rate,
            name="self_attn",
        )
        self.self_attn_layer_norm = LayerNorm(cfg.layer_norm_epsilon, self.dtype, name="self_attn_layer_norm")
        self.mlp = BartMLP(cfg.encoder_ffn_dim, cfg.d_model, cfg.dropout_rate, self.dtype, name="mlp")
        self.final_layer_norm = LayerNorm(cfg.layer_norm_epsilon, self.dtype, name="final_layer_norm")
        self.dropout = Dropout(cfg.dropout_rate)

    def __call__(self, hidden, bias, deterministic: bool = True):
        residual = hidden
        h = self.self_attn(hidden, bias=bias, deterministic=deterministic)
        # the residual add rides the dropout kernel (one fused pass on TPU)
        hidden = self.self_attn_layer_norm(self.dropout(h, deterministic, residual=residual))
        residual = hidden
        h = self.mlp(hidden, deterministic=deterministic)
        hidden = self.final_layer_norm(self.dropout(h, deterministic, residual=residual))
        return hidden


class BartMLP(nn.Module):
    ffn_dim: int
    model_dim: int
    dropout_rate: float
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        h = nn.gelu(nn.Dense(self.ffn_dim, dtype=self.dtype, name="fc1")(x), approximate=False)
        h = Dropout(self.dropout_rate)(h, deterministic)
        return nn.Dense(self.model_dim, dtype=self.dtype, name="fc2")(h)


class BartDecoderLayer(nn.Module):
    config: BartConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self) -> None:
        cfg = self.config
        mk_attn = lambda causal, name: MultiHeadAttention(  # noqa: E731
            num_heads=cfg.decoder_attention_heads,
            head_dim=cfg.d_model // cfg.decoder_attention_heads,
            model_dim=cfg.d_model,
            use_bias=True,
            causal=causal,
            dtype=self.dtype,
            attention_impl=cfg.attention_impl,
            probs_dropout_rate=cfg.attn_dropout_rate,
            name=name,
        )
        self.self_attn = mk_attn(True, "self_attn")
        self.self_attn_layer_norm = LayerNorm(cfg.layer_norm_epsilon, self.dtype, name="self_attn_layer_norm")
        self.cross_attn = mk_attn(False, "cross_attn")
        self.cross_attn_layer_norm = LayerNorm(cfg.layer_norm_epsilon, self.dtype, name="cross_attn_layer_norm")
        self.mlp = BartMLP(cfg.decoder_ffn_dim, cfg.d_model, cfg.dropout_rate, self.dtype, name="mlp")
        self.final_layer_norm = LayerNorm(cfg.layer_norm_epsilon, self.dtype, name="final_layer_norm")
        self.dropout = Dropout(cfg.dropout_rate)

    def __call__(
        self,
        hidden,
        self_bias,
        encoder_hidden,
        cross_bias,
        deterministic: bool = True,
        use_cache: bool = False,
        cross_kv=None,
        cache_positions=None,
    ):
        residual = hidden
        h = self.self_attn(
            hidden, bias=self_bias, use_cache=use_cache, deterministic=deterministic,
            cache_positions=cache_positions,
        )
        hidden = self.self_attn_layer_norm(self.dropout(h, deterministic, residual=residual))
        residual = hidden
        h = self.cross_attn(
            hidden, kv_hidden=encoder_hidden, bias=cross_bias, cross_kv=cross_kv,
            deterministic=deterministic,
        )
        hidden = self.cross_attn_layer_norm(self.dropout(h, deterministic, residual=residual))
        residual = hidden
        h = self.mlp(hidden, deterministic=deterministic)
        hidden = self.final_layer_norm(self.dropout(h, deterministic, residual=residual))
        return hidden


class BartForConditionalGeneration(nn.Module):
    config: BartConfig
    dtype: jnp.dtype = jnp.float32
    remat: bool = False
    remat_policy: str = "full"  # "full" | "dots" (utils/remat.py)

    def setup(self) -> None:
        cfg = self.config
        self.shared = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=self.dtype, name="shared")
        self.encoder_embed_positions = nn.Embed(
            cfg.max_position_embeddings + cfg.POSITION_OFFSET, cfg.d_model, dtype=self.dtype,
            name="encoder_embed_positions",
        )
        self.decoder_embed_positions = nn.Embed(
            cfg.max_position_embeddings + cfg.POSITION_OFFSET, cfg.d_model, dtype=self.dtype,
            name="decoder_embed_positions",
        )
        self.encoder_layernorm_embedding = LayerNorm(
            cfg.layer_norm_epsilon, self.dtype, name="encoder_layernorm_embedding"
        )
        self.decoder_layernorm_embedding = LayerNorm(
            cfg.layer_norm_epsilon, self.dtype, name="decoder_layernorm_embedding"
        )
        enc_layer = remat_block(BartEncoderLayer, (3,), self.remat_policy) if self.remat else BartEncoderLayer
        dec_layer = remat_block(BartDecoderLayer, (5, 6), self.remat_policy) if self.remat else BartDecoderLayer
        self.encoder_blocks = [
            enc_layer(cfg, dtype=self.dtype, name=f"encoder_block_{i}") for i in range(cfg.encoder_layers)
        ]
        self.decoder_blocks = [
            dec_layer(cfg, dtype=self.dtype, name=f"decoder_block_{i}") for i in range(cfg.decoder_layers)
        ]
        self.final_logits_bias = self.param(
            "final_logits_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32
        )
        self.dropout = Dropout(cfg.dropout_rate)

    def encode(self, input_ids, attention_mask=None, *, deterministic: bool = True):
        cfg = self.config
        pos = jnp.arange(input_ids.shape[1]) + cfg.POSITION_OFFSET
        hidden = self.shared(input_ids) * cfg.embed_scale + self.encoder_embed_positions(pos)[None]
        hidden = self.dropout(self.encoder_layernorm_embedding(hidden), deterministic=deterministic)
        hidden = constrain_hidden(hidden)
        bias = mask_to_bias(attention_mask) if attention_mask is not None else None
        for blk in self.encoder_blocks:
            hidden = constrain_hidden(blk(hidden, bias, deterministic))
        return hidden

    def cross_kv(self, encoder_hidden):
        """Per-decoder-layer cross-attention K/V, projected ONCE from the
        encoder output.  The decode loop's per-step cross projections
        (2·S·d_model² FLOPs per layer) dwarf everything else it does at
        summarization shapes; generation precomputes this tuple after
        ``encode`` and threads it through every decode step."""
        return tuple(
            blk.cross_attn.project_kv(encoder_hidden) for blk in self.decoder_blocks
        )

    def decode(
        self,
        decoder_input_ids,
        encoder_hidden,
        encoder_mask=None,
        decoder_attention_mask=None,
        *,
        deterministic: bool = True,
        use_cache: bool = False,
        cache_offset: int | jnp.ndarray = 0,
        max_kv_len: int | None = None,
        cross_kv=None,
    ):
        cfg = self.config
        q_len = decoder_input_ids.shape[1]
        # a (B,) cache_offset is the continuous-batching form: each serving
        # slot decodes at its own position (per-row position embeddings +
        # per-row cache writes)
        cache_positions = None
        off = jnp.asarray(cache_offset)
        if off.ndim == 1:
            cache_positions = off.astype(jnp.int32)
            pos = off[:, None] + jnp.arange(q_len)[None, :] + cfg.POSITION_OFFSET
            pos_embed = self.decoder_embed_positions(pos)  # (B, q, d)
        else:
            pos = jnp.arange(q_len) + cache_offset + cfg.POSITION_OFFSET
            pos_embed = self.decoder_embed_positions(pos)[None]
        hidden = self.shared(decoder_input_ids) * cfg.embed_scale + pos_embed
        hidden = self.dropout(self.decoder_layernorm_embedding(hidden), deterministic=deterministic)
        if use_cache:
            self_bias = None  # causal/validity handled inside cached attention
        else:
            # causal masking lives inside MultiHeadAttention (natively in the
            # flash kernel); only the padding mask is passed as a bias
            self_bias = (
                mask_to_bias(decoder_attention_mask)
                if decoder_attention_mask is not None
                else None
            )
        cross_bias = mask_to_bias(encoder_mask) if encoder_mask is not None else None
        hidden = constrain_hidden(hidden)
        for i, blk in enumerate(self.decoder_blocks):
            hidden = constrain_hidden(blk(
                hidden, self_bias, encoder_hidden, cross_bias, deterministic, use_cache,
                cross_kv=None if cross_kv is None else cross_kv[i],
                cache_positions=cache_positions,
            ))
        logits = constrain_logits(hidden @ self.shared.embedding.astype(self.dtype).T)
        return logits + self.final_logits_bias.astype(logits.dtype)

    def __call__(
        self,
        input_ids,
        attention_mask=None,
        decoder_input_ids=None,
        decoder_attention_mask=None,
        *,
        deterministic: bool = True,
    ):
        enc = self.encode(input_ids, attention_mask, deterministic=deterministic)
        return self.decode(
            decoder_input_ids,
            enc,
            encoder_mask=attention_mask,
            decoder_attention_mask=decoder_attention_mask,
            deterministic=deterministic,
        )


class PipelinedBart:
    """Train-time ``apply()`` adapter running BOTH BART stacks as GPipe
    pipelines over the ``stage`` mesh axis (parallel/pipeline.py) — the
    encoder pipeline drains fully, then its output rides the decoder
    pipeline as a per-example extra feeding every stage's cross-attention.

    Drop-in for ``BartForConditionalGeneration.apply`` in the train step's
    loss fn (same signature/logits) with the param tree holding
    ``stacked_encoder_blocks`` / ``stacked_decoder_blocks``
    (``stack_for_family("bart", ...)``).  Embeddings / logits run outside
    the pipelines under plain GSPMD; ``stage`` composes with data/fsdp and
    ``tensor`` (partial-manual shard_map), not ``sequence``.  Dropout is
    fully supported: pass ``deterministic=False`` with a ``dropout`` rng —
    the key is folded per (pipeline, microbatch, stage, layer) inside the
    stage loop so every layer of every microbatch draws an independent
    mask.  Training + teacher-forced scoring only (no KV-cache generation
    path).
    """

    def __init__(self, config: BartConfig, mesh, dtype=jnp.float32,
                 num_microbatches: int = 0, remat: bool = True,
                 schedule: str = "gpipe"):
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"unknown pipeline schedule {schedule!r}: must be gpipe, "
                "1f1b, or interleaved"
            )
        # known-bad schedule × sharding combos (1f1b×fsdp partitioner
        # crash, interleaved, sequence parallelism) are table rows in
        # analysis/composition.py — one declarative check instead of
        # scattered raises
        from distributed_llms_example_tpu.analysis.composition import (
            validate_composition,
        )

        validate_composition(
            family="bart", schedule=schedule, mesh_axes=dict(mesh.shape),
            flags=("pipelined",),
        )
        stages = mesh.shape.get("stage", 1)
        for n, what in ((config.encoder_layers, "encoder"), (config.decoder_layers, "decoder")):
            if n % max(stages, 1):
                raise ValueError(f"{n} {what} layers not divisible into {stages} stages")
        self.config = config
        self.mesh = mesh
        self.dtype = dtype
        self.num_microbatches = num_microbatches or max(stages, 1)
        self.remat = remat
        self.pipeline_schedule = schedule
        cfg = config
        self._shared = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=dtype)
        self._pos = nn.Embed(cfg.max_position_embeddings + cfg.POSITION_OFFSET, cfg.d_model, dtype=dtype)
        self._ln = LayerNorm(cfg.layer_norm_epsilon, dtype)
        self._enc_layer = BartEncoderLayer(cfg, dtype=dtype)
        self._dec_layer = BartDecoderLayer(cfg, dtype=dtype)

    def _embed(self, params, shared, ids, pos_key, ln_key):
        cfg = self.config
        pos = jnp.arange(ids.shape[1]) + cfg.POSITION_OFFSET
        h = shared * cfg.embed_scale + self._pos.apply({"params": params[pos_key]}, pos)[None]
        return constrain_hidden(self._ln.apply({"params": params[ln_key]}, h))

    def _dropout(self, x, key):
        from distributed_llms_example_tpu.parallel.pipeline import dropout

        return dropout(x, key, self.config.dropout_rate)

    def make_value_and_grad(self, label_smoothing: float = 0.0,
                            is_seq2seq: bool = True):
        """Twin-pipeline 1F1B training path: ``(params, batch, rng) ->
        (loss_sum, tokens, grads)`` with the fused schedule owning the
        backward (``pipeline_value_and_grad_seq2seq``).  Embeddings run
        outside under GSPMD with their own ``jax.vjp``; the tied LM head +
        ``final_logits_bias`` + CE run per-microbatch on the last stage's
        decoder chunk; the shared embedding's gradient sums its input-side
        and output-side contributions."""
        from distributed_llms_example_tpu.parallel.activation import activation_mesh
        from distributed_llms_example_tpu.parallel.pipeline_seq2seq import (
            pipeline_value_and_grad_seq2seq,
        )
        from distributed_llms_example_tpu.train.step import cross_entropy_sums

        assert is_seq2seq
        cfg = self.config

        def post_loss(pp, y, mb, key):
            # BART has no tail dropout: logits come straight off the last
            # decoder layer's final_layer_norm output (``decode``)
            del key
            logits = y["dec"] @ pp["shared"]["embedding"].astype(self.dtype).T
            logits = logits + pp["final_logits_bias"].astype(logits.dtype)
            return cross_entropy_sums(logits, mb["labels"], label_smoothing)

        def enc_fn(lp, h, ex, key=None):
            with activation_mesh(None):
                if key is None:
                    return self._enc_layer.apply({"params": lp}, h, ex.get("src_bias"), True)
                return self._enc_layer.apply(
                    {"params": lp}, h, ex.get("src_bias"), False, rngs={"dropout": key}
                )

        def dec_fn(lp, h, ex, key=None):
            # decoder self-attention bias is None in training (causality
            # lives in the attention impl; padded labels are masked in CE)
            with activation_mesh(None):
                if key is None:
                    return self._dec_layer.apply(
                        {"params": lp}, h, None, ex["enc"], ex.get("src_bias"), True
                    )
                return self._dec_layer.apply(
                    {"params": lp}, h, None, ex["enc"], ex.get("src_bias"),
                    False, rngs={"dropout": key},
                )

        embed_keys = (
            "shared", "encoder_embed_positions", "decoder_embed_positions",
            "encoder_layernorm_embedding", "decoder_layernorm_embedding",
        )

        def value_and_grad_sums(params, batch, rng=None):
            from distributed_llms_example_tpu.models.t5 import shift_right

            labels = batch["labels"]
            dec_ids = shift_right(labels, cfg.decoder_start_token_id, cfg.pad_token_id)
            embed_params = {k: params[k] for k in embed_keys}

            def embed_all(ep):
                sh = lambda ids: self._shared.apply({"params": ep["shared"]}, ids)  # noqa: E731
                eh = self._embed(ep, sh(batch["input_ids"]), batch["input_ids"],
                                 "encoder_embed_positions", "encoder_layernorm_embedding")
                dh = self._embed(ep, sh(dec_ids), dec_ids,
                                 "decoder_embed_positions", "decoder_layernorm_embedding")
                if rng is not None:
                    eh = self._dropout(eh, jax.random.fold_in(rng, 2))
                    dh = self._dropout(dh, jax.random.fold_in(rng, 3))
                return eh, dh

            (enc_h, dec_h), embed_vjp = jax.vjp(embed_all, embed_params)
            src_bias = (
                mask_to_bias(batch["attention_mask"])
                if batch.get("attention_mask") is not None else None
            )
            extras = {} if src_bias is None else {"src_bias": src_bias}
            post_params = {
                "shared": params["shared"],
                "final_logits_bias": params["final_logits_bias"],
            }
            (lsum, tokens, d_se, d_sd, d_pp, _d_seam, _d_dex, d_eh, d_dh) = (
                pipeline_value_and_grad_seq2seq(
                    enc_fn, dec_fn, post_loss,
                    params["stacked_encoder_blocks"], params["stacked_decoder_blocks"],
                    post_params, enc_h, dec_h, extras, {"labels": labels},
                    mesh=self.mesh, num_microbatches=self.num_microbatches,
                    checkpoint=self.remat,
                    rng=None if rng is None else jax.random.fold_in(rng, 7),
                )
            )
            (d_embed,) = embed_vjp((d_eh.astype(enc_h.dtype), d_dh.astype(dec_h.dtype)))
            grads = {
                **{k: d_embed[k] for k in embed_keys},
                "stacked_encoder_blocks": d_se,
                "stacked_decoder_blocks": d_sd,
                "final_logits_bias": d_pp["final_logits_bias"],
            }
            # tied embedding: input-side (both embed lookups) + output-side
            # (logits projection) gradient contributions add
            grads["shared"] = jax.tree.map(jnp.add, d_embed["shared"], d_pp["shared"])
            return lsum, tokens, grads

        return value_and_grad_sums

    def apply(self, variables, input_ids, attention_mask=None, decoder_input_ids=None,
              decoder_attention_mask=None, *, deterministic: bool = True, rngs=None):
        from distributed_llms_example_tpu.parallel.activation import activation_mesh
        from distributed_llms_example_tpu.parallel.pipeline import pipeline_apply

        rng = None
        if not deterministic and rngs and "dropout" in rngs and self.config.dropout_rate > 0:
            rng = rngs["dropout"]

        p = variables["params"]
        shared = lambda ids: self._shared.apply({"params": p["shared"]}, ids)  # noqa: E731
        enc_bias = mask_to_bias(attention_mask) if attention_mask is not None else None

        hidden = self._embed(p, shared(input_ids), input_ids,
                             "encoder_embed_positions", "encoder_layernorm_embedding")
        if rng is not None:
            hidden = self._dropout(hidden, jax.random.fold_in(rng, 2))

        def enc_fn(lp, h, ex, key=None):
            with activation_mesh(None):
                if key is None:
                    return self._enc_layer.apply({"params": lp}, h, ex.get("bias"), True)
                return self._enc_layer.apply(
                    {"params": lp}, h, ex.get("bias"), False, rngs={"dropout": key}
                )

        hidden = pipeline_apply(
            enc_fn, p["stacked_encoder_blocks"], hidden,
            {"bias": enc_bias} if enc_bias is not None else {},
            mesh=self.mesh, num_microbatches=self.num_microbatches, checkpoint=self.remat,
            rng=None if rng is None else jax.random.fold_in(rng, 0),
        )

        dh = self._embed(p, shared(decoder_input_ids), decoder_input_ids,
                         "decoder_embed_positions", "decoder_layernorm_embedding")
        if rng is not None:
            dh = self._dropout(dh, jax.random.fold_in(rng, 3))
        extras = {"enc": hidden}
        if enc_bias is not None:
            extras["cross_bias"] = enc_bias
        if decoder_attention_mask is not None:
            extras["self_bias"] = mask_to_bias(decoder_attention_mask)

        def dec_fn(lp, h, ex, key=None):
            with activation_mesh(None):
                if key is None:
                    return self._dec_layer.apply(
                        {"params": lp}, h, ex.get("self_bias"), ex["enc"], ex.get("cross_bias"), True
                    )
                return self._dec_layer.apply(
                    {"params": lp}, h, ex.get("self_bias"), ex["enc"], ex.get("cross_bias"),
                    False, rngs={"dropout": key},
                )

        dh = pipeline_apply(
            dec_fn, p["stacked_decoder_blocks"], dh, extras,
            mesh=self.mesh, num_microbatches=self.num_microbatches, checkpoint=self.remat,
            rng=None if rng is None else jax.random.fold_in(rng, 1),
        )
        logits = constrain_logits(dh @ p["shared"]["embedding"].astype(self.dtype).T)
        return logits + p["final_logits_bias"].astype(logits.dtype)
