"""ROUGE-1/2/L/Lsum with a Porter stemmer — no network, no extra deps.

The reference uses ``evaluate.load("rouge")`` (reference
train-accelerator.py:207) and ``metric.compute(use_stemmer=True)``
(train-accelerator.py:268), which at runtime downloads the
google-research ``rouge_score`` implementation from the HF hub.  This is a
self-contained reimplementation with the same semantics: lowercase,
``[a-z0-9]+`` tokenization, optional Porter stemming (applied to tokens
longer than 3 chars, as rouge_score does), F1 scores, and newline-split
union-LCS for rougeLsum.  Scores are fractions in [0, 1]; the reference's
variant C multiplies by 100 and rounds to 4dp (train-task.py:343-345),
which callers can do on top.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable, Sequence

_TOKEN_RE = re.compile(r"[a-z0-9]+")

_VOWELS = "aeiou"


def _is_consonant(word: str, i: int) -> bool:
    ch = word[i]
    if ch in _VOWELS:
        return False
    if ch == "y":
        return i == 0 or not _is_consonant(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Porter's m: number of VC sequences."""
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        vowel = not _is_consonant(stem, i)
        if not vowel and prev_vowel:
            m += 1
        prev_vowel = vowel
    return m


def _contains_vowel(stem: str) -> bool:
    return any(not _is_consonant(stem, i) for i in range(len(stem)))


def _ends_double_consonant(word: str) -> bool:
    return len(word) >= 2 and word[-1] == word[-2] and _is_consonant(word, len(word) - 1)


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (
        _is_consonant(word, len(word) - 3)
        and not _is_consonant(word, len(word) - 2)
        and _is_consonant(word, len(word) - 1)
        and word[-1] not in "wxy"
    )


def porter_stem(word: str) -> str:
    """The classic Porter (1980) stemming algorithm."""
    w = word
    if len(w) <= 2:
        return w

    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]

    # step 1b
    flag_1b = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed"):
        if _contains_vowel(w[:-2]):
            w = w[:-2]
            flag_1b = True
    elif w.endswith("ing"):
        if _contains_vowel(w[:-3]):
            w = w[:-3]
            flag_1b = True
    if flag_1b:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_consonant(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"

    # step 1c
    if w.endswith("y") and _contains_vowel(w[:-1]):
        w = w[:-1] + "i"

    # step 2
    step2 = [
        ("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
        ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
        ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
        ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
        ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
    ]
    for suf, rep in step2:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _measure(stem) > 0:
                w = stem + rep
            break

    # step 3
    step3 = [
        ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
        ("ical", "ic"), ("ful", ""), ("ness", ""),
    ]
    for suf, rep in step3:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if _measure(stem) > 0:
                w = stem + rep
            break

    # step 4
    step4 = [
        "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
        "ment", "ent", "ion", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
    ]
    for suf in step4:
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if suf == "ion" and not stem.endswith(("s", "t")):
                continue
            if _measure(stem) > 1:
                w = stem
            break

    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = _measure(stem)
        if m > 1 or (m == 1 and not _cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_consonant(w) and w.endswith("l"):
        w = w[:-1]
    return w


def tokenize(text: str, use_stemmer: bool = True) -> list[str]:
    toks = _TOKEN_RE.findall(text.lower())
    if use_stemmer:
        # rouge_score only stems tokens longer than 3 chars
        toks = [porter_stem(t) if len(t) > 3 else t for t in toks]
    return toks


def _f1(match: int, pred: int, ref: int) -> float:
    if pred == 0 or ref == 0:
        return 0.0
    p = match / pred
    r = match / ref
    return 2 * p * r / (p + r) if p + r else 0.0


def rouge_n(pred: Sequence[str], ref: Sequence[str], n: int) -> float:
    if len(pred) < n or len(ref) < n:
        return 0.0
    pc = Counter(tuple(pred[i : i + n]) for i in range(len(pred) - n + 1))
    rc = Counter(tuple(ref[i : i + n]) for i in range(len(ref) - n + 1))
    match = sum((pc & rc).values())
    return _f1(match, sum(pc.values()), sum(rc.values()))


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for i in range(1, len(a) + 1):
        cur = [0] * (len(b) + 1)
        ai = a[i - 1]
        for j in range(1, len(b) + 1):
            cur[j] = prev[j - 1] + 1 if ai == b[j - 1] else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


def rouge_l(pred: Sequence[str], ref: Sequence[str]) -> float:
    return _f1(_lcs_len(pred, ref), len(pred), len(ref))


def _union_lcs(pred_sents: list[list[str]], ref_sent: list[str]) -> set[tuple[int, str]]:
    """Positions (as (index, token)) of ref_sent covered by any pred sentence's LCS."""
    hit: set[int] = set()
    for ps in pred_sents:
        # recover one LCS alignment against ref_sent
        la, lb = len(ps), len(ref_sent)
        if not la or not lb:
            continue
        dp = [[0] * (lb + 1) for _ in range(la + 1)]
        for i in range(1, la + 1):
            for j in range(1, lb + 1):
                dp[i][j] = dp[i - 1][j - 1] + 1 if ps[i - 1] == ref_sent[j - 1] else max(dp[i - 1][j], dp[i][j - 1])
        i, j = la, lb
        while i > 0 and j > 0:
            if ps[i - 1] == ref_sent[j - 1]:
                hit.add(j - 1)
                i -= 1
                j -= 1
            elif dp[i - 1][j] >= dp[i][j - 1]:
                i -= 1
            else:
                j -= 1
    return {(j, ref_sent[j]) for j in hit}


def rouge_lsum(pred: str, ref: str, use_stemmer: bool = True) -> float:
    pred_sents = [tokenize(s, use_stemmer) for s in pred.splitlines() if s.strip()]
    ref_sents = [tokenize(s, use_stemmer) for s in ref.splitlines() if s.strip()]
    ref_total = sum(len(s) for s in ref_sents)
    pred_total = sum(len(s) for s in pred_sents)
    match = sum(len(_union_lcs(pred_sents, rs)) for rs in ref_sents)
    return _f1(match, pred_total, ref_total)


DEFAULT_TYPES = ("rouge1", "rouge2", "rougeL", "rougeLsum")


def compute(
    predictions: Iterable[str],
    references: Iterable[str],
    rouge_types: Sequence[str] = DEFAULT_TYPES,
    use_stemmer: bool = True,
) -> dict[str, float]:
    """Mean F1 per type over example pairs (fractions in [0,1])."""
    sums = {t: 0.0 for t in rouge_types}
    n = 0
    for pred, ref in zip(predictions, references):
        pt, rt = tokenize(pred, use_stemmer), tokenize(ref, use_stemmer)
        for t in rouge_types:
            if t == "rouge1":
                sums[t] += rouge_n(pt, rt, 1)
            elif t == "rouge2":
                sums[t] += rouge_n(pt, rt, 2)
            elif t == "rougeL":
                sums[t] += rouge_l(pt, rt)
            elif t == "rougeLsum":
                sums[t] += rouge_lsum(pred, ref, use_stemmer)
            else:
                raise ValueError(f"unknown rouge type {t!r}")
        n += 1
    if n == 0:
        return {t: 0.0 for t in rouge_types}
    return {t: s / n for t, s in sums.items()}
