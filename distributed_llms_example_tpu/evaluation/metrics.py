"""Cross-process metric aggregation.

Replaces the reference's two hand-rolled flavors — ``accelerator.gather`` +
mean (train-accelerator.py:135-140) and per-key ``dist.all_gather`` into a
tensor list + mean with ``epoch`` passed through (train-task.py:193-218) —
with one function over JAX multihost utilities.  Single-process runs are
the identity, so the same code path works everywhere.
"""

from __future__ import annotations

from typing import Mapping

import jax
import numpy as np

PASSTHROUGH_KEYS = ("epoch", "step")  # parity with train-task.py:214 ('epoch' takes first)


def aggregate_mean(metrics: Mapping[str, float]) -> dict[str, float]:
    """Mean of each metric across processes (pass-through for epoch/step)."""
    out = {k: float(v) for k, v in metrics.items()}
    if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform; the multi-host allgather below runs on every rank
        return out
    from jax.experimental import multihost_utils

    keys = sorted(k for k in out if k not in PASSTHROUGH_KEYS)
    if keys:
        vec = np.asarray([out[k] for k in keys], np.float32)
        gathered = multihost_utils.process_allgather(vec)  # (procs, n)
        mean = np.mean(gathered, axis=0)
        for k, v in zip(keys, mean):
            out[k] = float(v)
    return out
