"""Autoregressive generation: explicit prefill/decode split, greedy + beam.

The reference calls ``model.generate(max_length=128, num_beams=2)`` for its
live eval loop (reference train-accelerator.py:239-249).  On TPU the decode
loop must be a fixed-shape compiled program; here it is built from two
separately-compiled (and separately AOT-inspectable) pieces:

- **prefill** — everything that runs once per sequence: the encoder +
  once-per-sequence cross-attention K/V projection (seq2seq) or the prompt
  pass into the KV cache (decoder-only), plus zeroed cache buffers.
- **decode step** — ONE fixed-shape token step: read the cache, emit one
  token per row, write one K/V slot.  The static eval path drives it with
  a ``lax.fori_loop`` (``decode_loop`` — one compile, same per-token
  program); the continuous-batching engine (serving/engine.py) drives a
  jitted step per token from the host so it can admit/evict between steps.

The split is what the IR lint's ``prefill_in_decode_smell`` checks: the
compiled decode step must contain NO encoder/prefill-sized matmuls and
never re-project cross-attention K/V (the ``cross_kv``-computed-once
contract).  Cache buffers and cross-KV trees are pinned to the serving
layout (batch rows over data×fsdp, heads over tensor — ``CACHE_RULES``)
via ``constrain_cache``, so multi-chip decode shards the cache instead of
replicating it.

Beam search keeps a flattened (batch × beams) leading dim so every step is
one big MXU-friendly batch.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.parallel.activation import constrain_cache

NEG_INF = -1.0e7


def _init_cache(model: Any, params: Any, batch: int, max_len: int, enc: jnp.ndarray, enc_mask: jnp.ndarray):
    """Zero cache buffers for a (batch, max_len) decode, via eval_shape (no
    real forward pass)."""
    dummy = jnp.zeros((batch, max_len), jnp.int32)
    shapes = jax.eval_shape(
        lambda p: model.init(
            jax.random.PRNGKey(0), dummy, enc, enc_mask, use_cache=True, max_kv_len=max_len, method="decode"
        ),
        params,
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def abstract_cache(
    model: Any,
    abstract_params: Any,
    *,
    batch: int,
    max_new_tokens: int,
    src_len: int = 64,
    is_seq2seq: bool = True,
    kv_cache_dtype: str = "f32",
):
    """Shape-only decode-cache tree (ShapeDtypeStruct leaves) — the input
    the cache spec lint (``analysis/spec_lint.py lint_cache_sharding``)
    validates, built without weights or devices.  ``kv_cache_dtype``
    "int8" yields the quantized layout: s8 K/V buffers plus the per-head
    per-position ``key_scale``/``value_scale`` f32 leaves the scale rules
    in ``CACHE_RULES`` cover."""
    from distributed_llms_example_tpu.parallel.activation import kv_cache_context
    if is_seq2seq:
        def build(p):
            ids = jnp.zeros((batch, src_len), jnp.int32)
            mask = jnp.ones((batch, src_len), jnp.int32)
            enc = model.apply({"params": p}, ids, mask, method="encode")
            return model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((batch, max_new_tokens), jnp.int32),
                enc, mask, use_cache=True, max_kv_len=max_new_tokens,
                method="decode",
            )["cache"]
    else:
        def build(p):
            width = src_len + max_new_tokens
            return model.init(
                jax.random.PRNGKey(0), jnp.zeros((batch, width), jnp.int32),
                use_cache=True,
            )["cache"]

    with kv_cache_context(kv_cache_dtype):
        return jax.eval_shape(build, abstract_params)


# --------------------------------------------------------------- seq2seq


class Seq2SeqGenerator:
    """Prefill/decode split for encoder-decoder (BART/T5) generation.

    ``prefill`` runs the encoder, projects cross-attention K/V ONCE, and
    allocates sharded cache buffers; ``decode_step`` is the fixed-shape
    per-token program; ``decode_loop`` wraps it in a ``fori_loop`` for
    static batches; ``finalize`` extracts the output ids.  Greedy when
    ``num_beams == 1``, HF-parity beam search otherwise (banked finished
    beams, length-normalized scores — see ``_beam_step_select``)."""

    def __init__(self, model: Any, config: Any, max_new_tokens: int,
                 num_beams: int = 1, length_penalty: float = 1.0):
        self.model, self.config = model, config
        self.L, self.K = max_new_tokens, num_beams
        self.length_penalty = length_penalty
        self.eos, self.pad = config.eos_token_id, config.pad_token_id
        self.start = config.decoder_start_token_id
        self.forced_bos = getattr(config, "forced_bos_token_id", None)
        self.forced_eos = getattr(config, "forced_eos_token_id", None)

    # ---- once per sequence -------------------------------------------
    def prefill(self, params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> dict:
        B = input_ids.shape[0]
        enc = self.model.apply({"params": params}, input_ids, attention_mask, method="encode")
        # cross-attention K/V projected ONCE: per-step re-projection of the
        # full encoder output (2·S·d² per layer) would dominate decode —
        # the contract the IR lint's prefill_in_decode_smell pins
        ckv = constrain_cache(self.model.apply({"params": params}, enc, method="cross_kv"))
        t0 = jnp.zeros((), jnp.int32)
        if self.K > 1:
            # beams share the row's encoder output for DECODING (replicated
            # to the flat beam batch); cross-KV stays at batch B — the
            # attention folds the beam group next to heads so K/V stream
            # from HBM once per row per step (beam_grouped_attention)
            enc_rep = jnp.repeat(enc, self.K, axis=0)
            mask_rep = jnp.repeat(attention_mask, self.K, axis=0)
            cache = constrain_cache(
                _init_cache(self.model, params, B * self.K, self.L, enc_rep, mask_rep)
            )
            return {
                "t": t0,
                "cache": cache,
                "enc": enc_rep,
                "enc_mask": mask_rep,
                "ckv": ckv,
                "last": jnp.full((B * self.K, 1), self.start, jnp.int32),
                "state": _beam_init(B, self.K, self.L, self.pad),
            }
        cache = constrain_cache(
            _init_cache(self.model, params, B, self.L, enc, attention_mask)
        )
        return {
            "t": t0,
            "cache": cache,
            "enc": enc,
            "enc_mask": attention_mask,
            "ckv": ckv,
            "last": jnp.full((B, 1), self.start, jnp.int32),
            "out": jnp.full((B, self.L), self.pad, jnp.int32),
            "done": jnp.zeros((B,), bool),
        }

    # ---- once per token ----------------------------------------------
    def decode_step(self, params: Any, carry: dict) -> dict:
        t = carry["t"]
        logits, mut = self.model.apply(
            {"params": params, "cache": carry["cache"]},
            carry["last"],
            carry["enc"],
            carry["enc_mask"],
            use_cache=True,
            cache_offset=t,
            max_kv_len=self.L,
            cross_kv=carry["ckv"],
            method="decode",
            mutable=["cache"],
        )
        cache = constrain_cache(mut["cache"])
        if self.K > 1:
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)  # (B*K, V)
            V = logp.shape[-1]
            if self.forced_bos is not None:  # HF forced_bos_token_id processor
                forced_mask = jnp.full((V,), NEG_INF, jnp.float32).at[self.forced_bos].set(0.0)
                logp = jnp.where(t == 0, logp + forced_mask[None, :], logp)
            if self.forced_eos is not None:  # HF forced_eos_token_id: EOS at max length
                eos_mask = jnp.full((V,), NEG_INF, jnp.float32).at[self.forced_eos].set(0.0)
                logp = jnp.where(t == self.L - 1, logp + eos_mask[None, :], logp)
            B = carry["state"][0].shape[0]
            state, chosen, parents = _beam_step_select(
                logp, t, carry["state"], eos=self.eos, K=self.K,
                length_penalty=self.length_penalty,
            )
            cache = _gather_beams(cache, parents, B, self.K)
            return {
                **carry,
                "t": t + 1,
                "cache": cache,
                "last": chosen.reshape(B * self.K, 1),
                "state": state,
            }
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        if self.forced_bos is not None:
            nxt = jnp.where(t == 0, self.forced_bos, nxt)
        if self.forced_eos is not None:
            nxt = jnp.where(t == self.L - 1, self.forced_eos, nxt)
        nxt = jnp.where(carry["done"], self.pad, nxt)
        out = carry["out"].at[:, t].set(nxt)
        done = carry["done"] | (nxt == self.eos)
        return {
            **carry,
            "t": t + 1,
            "cache": cache,
            "last": nxt[:, None],
            "out": out,
            "done": done,
        }

    def decode_loop(self, params: Any, carry: dict) -> dict:
        return jax.lax.fori_loop(
            0, self.L, lambda i, c: self.decode_step(params, c), carry
        )

    def finalize(self, carry: dict) -> jnp.ndarray:
        if self.K > 1:
            # final decoder length = start token + L generated (banking at
            # step t uses t+1; the live-beam convention must match)
            return _beam_finalize(carry["state"], self.L + 1, self.length_penalty)
        return carry["out"]

    def run(self, params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        """Whole-program form (traceable; jit for the one-compile path)."""
        return self.finalize(self.decode_loop(params, self.prefill(params, input_ids, attention_mask)))


def make_greedy_generate(model: Any, config: Any, max_new_tokens: int) -> Callable:
    """Jittable greedy decoding: (params, input_ids, attention_mask) → ids
    of shape (batch, max_new_tokens), pad-filled after EOS."""
    return Seq2SeqGenerator(model, config, max_new_tokens, num_beams=1).run


def make_beam_search(
    model: Any,
    config: Any,
    max_new_tokens: int,
    num_beams: int = 2,
    length_penalty: float = 1.0,
) -> Callable:
    """Jittable beam search matching HF ``generate(num_beams=K)`` semantics:
    score = sum logprobs / (length ** length_penalty), finished beams
    banked when EOS is chosen, best finished (or live) beam returned."""
    return Seq2SeqGenerator(
        model, config, max_new_tokens, num_beams=num_beams, length_penalty=length_penalty
    ).run


# ----------------------------------------------------------- decoder-only


def _causal_prefill(
    model: Any, params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray, new_tokens: int
):
    """One-pass prompt prefill for decoder-only decode.

    Allocates cache buffers for prompt + generation, runs the prompt
    through once, and returns ``(cache, full_mask, lengths, first_logits)``
    where ``first_logits`` is each row's logits at its last *valid* prompt
    position.  Right-padded prompts are supported: RoPE positions follow
    the true sequence (cumsum over the mask), not the cache slot, and pad
    slots stay masked out of attention."""
    B, P = input_ids.shape
    width = P + new_tokens
    shapes = jax.eval_shape(
        lambda p: model.init(
            jax.random.PRNGKey(0), jnp.zeros((B, width), jnp.int32), use_cache=True
        ),
        params,
    )
    cache = constrain_cache(
        jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])
    )
    full_mask = jnp.concatenate([attention_mask, jnp.zeros((B, new_tokens), jnp.int32)], axis=1)
    lengths = jnp.sum(attention_mask, axis=1).astype(jnp.int32)
    prefill_pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0, None)
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        input_ids,
        full_mask,
        use_cache=True,
        positions=prefill_pos,
        mutable=["cache"],
    )
    first = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return constrain_cache(mut["cache"]), full_mask, lengths, first


class CausalGenerator:
    """Prefill/decode split for decoder-only (LLaMA-family) generation.

    ``prefill`` runs the right-padded prompt into the KV cache in one pass
    (beams share the prefix, so prefill compute is NOT multiplied by K);
    ``decode_step`` decodes one token per row with true-sequence RoPE
    positions.  Greedy or HF-parity beam search (reference live contract:
    ``num_beams=2``, train-accelerator.py:247)."""

    def __init__(self, model: Any, config: Any, max_new_tokens: int,
                 num_beams: int = 1, length_penalty: float = 1.0):
        self.model, self.config = model, config
        self.L, self.K = max_new_tokens, num_beams
        self.length_penalty = length_penalty
        self.eos, self.pad = config.eos_token_id, config.pad_token_id

    def prefill(self, params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> dict:
        B, P = input_ids.shape
        cache, full_mask, lengths, first = _causal_prefill(
            self.model, params, input_ids, attention_mask, self.L
        )
        if self.K > 1:
            logp0 = jax.nn.log_softmax(first.astype(jnp.float32), axis=-1)  # (B, V)
            # beams share the prefilled prompt: replicate cache rows K-ways
            cache = constrain_cache(
                jax.tree.map(lambda x: jnp.repeat(x, self.K, axis=0) if x.ndim > 0 else x, cache)
            )
            full_mask = jnp.repeat(full_mask, self.K, axis=0)  # (B*K, width)
            lengths_rep = jnp.repeat(lengths, self.K, axis=0)  # (B*K,)
            # token index 0: run the shared selection on the prefill logits —
            # with live_scores initialized to [0, -inf, ...] only beam 0's
            # distribution contributes, which is exactly the first HF step
            state = _beam_init(B, self.K, self.L, self.pad)
            state, chosen, parents = _beam_step_select(
                jnp.repeat(logp0, self.K, axis=0), 0, state,
                eos=self.eos, K=self.K, length_penalty=self.length_penalty,
                len_offset=P - 1,
            )
            cache = _gather_beams(cache, parents, B, self.K)  # parents all 0: no-op reorder
            return {
                "t": jnp.ones((), jnp.int32),
                "cache": cache,
                "full_mask": full_mask,
                "lengths": lengths_rep,
                "last": chosen.reshape(B * self.K, 1),
                "state": state,
            }
        nxt = jnp.argmax(first, axis=-1).astype(jnp.int32)
        return {
            "t": jnp.zeros((), jnp.int32),
            "cache": cache,
            "full_mask": full_mask,
            "lengths": lengths,
            "last": nxt,
            "out": jnp.full((B, self.L), self.pad, jnp.int32),
            "done": jnp.zeros((B,), bool),
        }

    def decode_step(self, params: Any, carry: dict) -> dict:
        t = carry["t"]
        P = carry["full_mask"].shape[1] - self.L
        if self.K > 1:
            # `last` is token index t-1; it occupies cache slot P + t - 1
            full_mask = carry["full_mask"].at[:, P + t - 1].set(1)
            logits, mut = self.model.apply(
                {"params": params, "cache": carry["cache"]},
                carry["last"],
                full_mask,
                use_cache=True,
                positions=(carry["lengths"] + t - 1)[:, None],
                mutable=["cache"],
            )
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
            B = carry["state"][0].shape[0]
            state, chosen, parents = _beam_step_select(
                logp, t, carry["state"], eos=self.eos, K=self.K,
                length_penalty=self.length_penalty, len_offset=P - 1,
            )
            cache = _gather_beams(constrain_cache(mut["cache"]), parents, B, self.K)
            return {
                **carry,
                "t": t + 1,
                "cache": cache,
                "last": chosen.reshape(B * self.K, 1),
                "full_mask": full_mask,
                "state": state,
            }
        out = carry["out"].at[:, t].set(carry["last"])
        full_mask = carry["full_mask"].at[:, P + t].set(1)
        logits, mut = self.model.apply(
            {"params": params, "cache": carry["cache"]},
            carry["last"][:, None],
            full_mask,
            use_cache=True,
            positions=(carry["lengths"] + t)[:, None],
            mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        done = carry["done"] | (carry["last"] == self.eos)
        nxt = jnp.where(done, self.pad, nxt)
        return {
            **carry,
            "t": t + 1,
            "cache": constrain_cache(mut["cache"]),
            "full_mask": full_mask,
            "last": nxt,
            "out": out,
            "done": done,
        }

    def decode_loop(self, params: Any, carry: dict) -> dict:
        t0 = 1 if self.K > 1 else 0  # beam prefill consumed token index 0
        return jax.lax.fori_loop(
            t0, self.L, lambda i, c: self.decode_step(params, c), carry
        )

    def finalize(self, carry: dict) -> jnp.ndarray:
        if self.K > 1:
            P = carry["full_mask"].shape[1] - self.L
            return _beam_finalize(carry["state"], P + self.L, self.length_penalty)
        return carry["out"]

    def run(self, params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        """Whole-program form (traceable; jit for the one-compile path)."""
        return self.finalize(self.decode_loop(params, self.prefill(params, input_ids, attention_mask)))


def make_causal_greedy(model: Any, config: Any, max_new_tokens: int) -> Callable:
    """Greedy decoding for decoder-only (causal) models.

    Prefills the prompt into the KV cache in one pass, then decodes one
    token at a time.  Right-padded prompts are supported (see
    ``_causal_prefill``).  With uniform-length prompts this matches HF
    ``generate`` exactly.
    """
    return CausalGenerator(model, config, max_new_tokens, num_beams=1).run


def make_causal_beam_search(
    model: Any,
    config: Any,
    max_new_tokens: int,
    num_beams: int = 2,
    length_penalty: float = 1.0,
) -> Callable:
    """Beam search for decoder-only models — HF-parity semantics shared
    with the seq2seq search via ``_beam_step_select``."""
    return CausalGenerator(
        model, config, max_new_tokens, num_beams=num_beams, length_penalty=length_penalty
    ).run


# ------------------------------------------------------- beam primitives


def _gather_beams(tree: Any, beam_idx: jnp.ndarray, batch: int, beams: int) -> Any:
    """Reorder the flattened (batch*beams, ...) leading dim by per-batch beam
    indices (batch, beams)."""
    flat_idx = (jnp.arange(batch)[:, None] * beams + beam_idx).reshape(-1)
    return jax.tree.map(lambda x: x[flat_idx] if x.ndim > 0 else x, tree)


def _beam_step_select(
    logp: jnp.ndarray,
    t: jnp.ndarray,
    state: tuple,
    *,
    eos: int,
    K: int,
    length_penalty: float,
    len_offset: int = 0,
) -> tuple:
    """One beam-search selection step from per-beam next-token logprobs.

    Shared by the seq2seq and causal searches so the HF-parity semantics
    live in exactly one place.  ``state`` is ``(live_scores, live_seqs,
    fin_scores, fin_seqs, row_done)``; ``logp`` is (B*K, V); ``t`` is the
    token index being chosen.  Matches HF BeamSearchScorer.process:

    - only EOS candidates ranked < num_beams among the top-2K are banked
      (``is_beam_token_worse_than_top_num_beams``);
    - a row is "done" (early_stopping=False) once it holds K banked
      hypotheses whose worst beats the best attainable continuation at the
      current length normalization; done rows stop banking;
    - the normalization length is ``t + 1 + len_offset``: HF divides by the
      full ``input_ids`` length, which for seq2seq is the decoder length
      (offset 0: start token + t generated) and for decoder-only includes
      the prompt (offset P - 1, so the length is P + t).
    """
    live_scores, live_seqs, fin_scores, fin_seqs, row_done = state
    B = live_scores.shape[0]
    V = logp.shape[-1]
    cand = live_scores[:, :, None] + logp.reshape(B, K, V)
    flat = cand.reshape(B, K * V)
    top_scores, top_idx = jax.lax.top_k(flat, 2 * K)  # (B, 2K)
    beam_idx = top_idx // V
    token = (top_idx % V).astype(jnp.int32)

    cand_seqs = jnp.take_along_axis(live_seqs, beam_idx[:, :, None], axis=1)  # (B, 2K, L)
    cand_seqs = cand_seqs.at[:, :, t].set(token)

    is_eos = token == eos
    rank_ok = jnp.arange(2 * K)[None, :] < K
    lp = jnp.asarray(t + 1 + len_offset, jnp.float32) ** length_penalty
    bankable = is_eos & rank_ok & ~row_done[:, None]
    fin_cand = jnp.where(bankable, top_scores / lp, NEG_INF)
    all_fin_scores = jnp.concatenate([fin_scores, fin_cand], axis=1)  # (B, 3K)
    all_fin_seqs = jnp.concatenate([fin_seqs, cand_seqs], axis=1)
    fin_scores_new, fin_keep = jax.lax.top_k(all_fin_scores, K)
    fin_seqs_new = jnp.take_along_axis(all_fin_seqs, fin_keep[:, :, None], axis=1)

    live_cand = jnp.where(is_eos, NEG_INF, top_scores)
    live_scores_new, live_keep = jax.lax.top_k(live_cand, K)
    live_seqs_new = jnp.take_along_axis(cand_seqs, live_keep[:, :, None], axis=1)
    chosen_tokens = jnp.take_along_axis(token, live_keep, axis=1)  # (B, K)
    parent_beams = jnp.take_along_axis(beam_idx, live_keep, axis=1)  # (B, K)

    has_k_banked = fin_scores_new[:, K - 1] > NEG_INF / 2
    # HF is_done uses the best overall candidate sum (next_scores.max(),
    # eos candidates included), not the best surviving live beam
    attainable = top_scores[:, 0] / lp
    row_done_new = row_done | (has_k_banked & (fin_scores_new[:, K - 1] >= attainable))

    new_state = (live_scores_new, live_seqs_new, fin_scores_new, fin_seqs_new, row_done_new)
    return new_state, chosen_tokens, parent_beams


def _beam_init(batch: int, K: int, L: int, pad: int) -> tuple:
    live_scores = jnp.tile(jnp.array([0.0] + [NEG_INF] * (K - 1), jnp.float32), (batch, 1))
    live_seqs = jnp.full((batch, K, L), pad, jnp.int32)
    fin_scores = jnp.full((batch, K), NEG_INF, jnp.float32)
    fin_seqs = jnp.full((batch, K, L), pad, jnp.int32)
    row_done = jnp.zeros((batch,), bool)
    return live_scores, live_seqs, fin_scores, fin_seqs, row_done


def _beam_finalize(state: tuple, final_len: int, length_penalty: float) -> jnp.ndarray:
    """Best sequence per row, HF finalize semantics: rows not yet done also
    consider their best live beam at max length, normalized by the full
    final sequence length (decoder length for seq2seq; prompt + generated
    for decoder-only)."""
    live_scores, live_seqs, fin_scores, fin_seqs, row_done = state
    none_finished = jnp.all(fin_scores <= NEG_INF / 2, axis=1)
    live_final = live_scores[:, 0] / (jnp.asarray(final_len, jnp.float32) ** length_penalty)
    take_live = ~row_done & (none_finished | (live_final > fin_scores[:, 0]))
    return jnp.where(take_live[:, None], live_seqs[:, 0], fin_seqs[:, 0])
