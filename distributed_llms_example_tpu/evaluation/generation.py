"""Autoregressive generation under jit: greedy and beam search.

The reference calls ``model.generate(max_length=128, num_beams=2)`` for its
live eval loop (reference train-accelerator.py:239-249) and 8 beams in the
dead test path (train-accelerator.py:95-101).  On TPU the decode loop must
be a fixed-shape compiled program: full-length KV cache buffers are
allocated up front, ``lax.fori_loop``/``while_loop`` steps write one token
per iteration, and finished sequences keep "decoding" pad tokens so shapes
never change.  Beam search keeps a flattened (batch × beams) leading dim so
every step is one big MXU-friendly batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

NEG_INF = -1.0e7


def _init_cache(model: Any, params: Any, batch: int, max_len: int, enc: jnp.ndarray, enc_mask: jnp.ndarray):
    """Zero cache buffers for a (batch, max_len) decode, via eval_shape (no
    real forward pass)."""
    dummy = jnp.zeros((batch, max_len), jnp.int32)
    shapes = jax.eval_shape(
        lambda p: model.init(
            jax.random.PRNGKey(0), dummy, enc, enc_mask, use_cache=True, max_kv_len=max_len, method="decode"
        ),
        params,
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def make_greedy_generate(model: Any, config: Any, max_new_tokens: int) -> Callable:
    """Jittable greedy decoding: (params, input_ids, attention_mask) → ids
    of shape (batch, max_new_tokens), pad-filled after EOS."""

    eos, pad, start = config.eos_token_id, config.pad_token_id, config.decoder_start_token_id
    forced_bos = getattr(config, "forced_bos_token_id", None)
    forced_eos = getattr(config, "forced_eos_token_id", None)
    L = max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B = input_ids.shape[0]
        enc = model.apply({"params": params}, input_ids, attention_mask, method="encode")
        cache = _init_cache(model, params, B, L, enc, attention_mask)

        def step(t, carry):
            cache, last, out, done = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last,
                enc,
                attention_mask,
                use_cache=True,
                cache_offset=t,
                max_kv_len=L,
                method="decode",
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            if forced_bos is not None:  # HF forced_bos_token_id processor
                nxt = jnp.where(t == 0, forced_bos, nxt)
            if forced_eos is not None:  # HF forced_eos_token_id: EOS at max length
                nxt = jnp.where(t == L - 1, forced_eos, nxt)
            nxt = jnp.where(done, pad, nxt)
            out = out.at[:, t].set(nxt)
            done = done | (nxt == eos)
            return mut["cache"], nxt[:, None], out, done

        out = jnp.full((B, L), pad, jnp.int32)
        last = jnp.full((B, 1), start, jnp.int32)
        done = jnp.zeros((B,), bool)
        _, _, out, _ = jax.lax.fori_loop(0, L, step, (cache, last, out, done))
        return out

    return generate


def make_causal_greedy(model: Any, config: Any, max_new_tokens: int) -> Callable:
    """Greedy decoding for decoder-only (causal) models.

    Prefills the prompt into the KV cache in one pass, then decodes one
    token at a time.  Right-padded prompts are supported: the first sampled
    token comes from each row's last *valid* position, and generated tokens
    occupy cache slots after the full prompt width (pad slots stay masked
    out of attention).  With uniform-length prompts this matches HF
    ``generate`` exactly.
    """
    eos, pad = config.eos_token_id, config.pad_token_id
    L = max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B, P = input_ids.shape
        width = P + L
        # cache buffers sized for prompt + generation
        shapes = jax.eval_shape(
            lambda p: model.init(
                jax.random.PRNGKey(0), jnp.zeros((B, width), jnp.int32), use_cache=True
            ),
            params,
        )
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])

        full_mask = jnp.concatenate([attention_mask, jnp.zeros((B, L), jnp.int32)], axis=1)
        lengths = jnp.sum(attention_mask, axis=1).astype(jnp.int32)  # valid prompt lengths
        # RoPE positions follow the true sequence, not the cache slot: pads
        # inside the prompt get position 0-ish (cumsum), generated tokens
        # continue at each row's own length
        prefill_pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0, None)
        # prefill
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            input_ids,
            full_mask,
            use_cache=True,
            positions=prefill_pos,
            mutable=["cache"],
        )
        cache = mut["cache"]
        first = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        nxt = jnp.argmax(first, axis=-1).astype(jnp.int32)

        def step(t, carry):
            cache, full_mask, last, out, done = carry
            out = out.at[:, t].set(last)
            full_mask = full_mask.at[:, P + t].set(1)
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last[:, None],
                full_mask,
                use_cache=True,
                positions=(lengths + t)[:, None],
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            done = done | (last == eos)
            nxt = jnp.where(done, pad, nxt)
            return mut["cache"], full_mask, nxt, out, done

        out = jnp.full((B, L), pad, jnp.int32)
        done = jnp.zeros((B,), bool)
        _, _, _, out, _ = jax.lax.fori_loop(0, L, step, (cache, full_mask, nxt, out, done))
        return out

    return generate


def _gather_beams(tree: Any, beam_idx: jnp.ndarray, batch: int, beams: int) -> Any:
    """Reorder the flattened (batch*beams, ...) leading dim by per-batch beam
    indices (batch, beams)."""
    flat_idx = (jnp.arange(batch)[:, None] * beams + beam_idx).reshape(-1)
    return jax.tree.map(lambda x: x[flat_idx] if x.ndim > 0 else x, tree)


def make_beam_search(
    model: Any,
    config: Any,
    max_new_tokens: int,
    num_beams: int = 2,
    length_penalty: float = 1.0,
) -> Callable:
    """Jittable beam search matching HF ``generate(num_beams=K)`` semantics:
    score = sum logprobs / (length ** length_penalty), finished beams
    banked when EOS is chosen, best finished (or live) beam returned."""

    eos, pad, start = config.eos_token_id, config.pad_token_id, config.decoder_start_token_id
    forced_bos = getattr(config, "forced_bos_token_id", None)
    forced_eos = getattr(config, "forced_eos_token_id", None)
    K, L = num_beams, max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B = input_ids.shape[0]
        enc = model.apply({"params": params}, input_ids, attention_mask, method="encode")
        # replicate encoder outputs per beam: (B*K, S, D)
        enc_rep = jnp.repeat(enc, K, axis=0)
        mask_rep = jnp.repeat(attention_mask, K, axis=0)
        cache = _init_cache(model, params, B * K, L, enc_rep, mask_rep)

        live_scores = jnp.tile(jnp.array([0.0] + [NEG_INF] * (K - 1), jnp.float32), (B, 1))
        live_seqs = jnp.full((B, K, L), pad, jnp.int32)
        fin_scores = jnp.full((B, K), NEG_INF, jnp.float32)
        fin_seqs = jnp.full((B, K, L), pad, jnp.int32)
        last = jnp.full((B * K, 1), start, jnp.int32)

        def step(t, carry):
            cache, last, live_scores, live_seqs, fin_scores, fin_seqs = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last,
                enc_rep,
                mask_rep,
                use_cache=True,
                cache_offset=t,
                max_kv_len=L,
                method="decode",
                mutable=["cache"],
            )
            cache = mut["cache"]
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)  # (B*K, V)
            V = logp.shape[-1]
            if forced_bos is not None:  # HF forced_bos_token_id processor
                forced_mask = jnp.full((V,), NEG_INF, jnp.float32).at[forced_bos].set(0.0)
                logp = jnp.where(t == 0, logp + forced_mask[None, :], logp)
            if forced_eos is not None:  # HF forced_eos_token_id: EOS at max length
                eos_mask = jnp.full((V,), NEG_INF, jnp.float32).at[forced_eos].set(0.0)
                logp = jnp.where(t == L - 1, logp + eos_mask[None, :], logp)
            cand = live_scores[:, :, None] + logp.reshape(B, K, V)  # (B, K, V)
            flat = cand.reshape(B, K * V)
            top_scores, top_idx = jax.lax.top_k(flat, 2 * K)  # (B, 2K)
            beam_idx = top_idx // V
            token = (top_idx % V).astype(jnp.int32)

            # candidate sequences with the new token written at position t
            cand_seqs = jnp.take_along_axis(live_seqs, beam_idx[:, :, None], axis=1)  # (B, 2K, L)
            cand_seqs = cand_seqs.at[:, :, t].set(token)

            is_eos = token == eos
            # bank finished candidates; HF normalizes by the sequence length
            # at add-time = start token + t prior tokens = t+1
            lp = jnp.asarray(t + 1, jnp.float32) ** length_penalty
            fin_cand = jnp.where(is_eos, top_scores / lp, NEG_INF)
            all_fin_scores = jnp.concatenate([fin_scores, fin_cand], axis=1)  # (B, 3K)
            all_fin_seqs = jnp.concatenate([fin_seqs, cand_seqs], axis=1)  # (B, 3K, L)
            fin_scores_new, fin_keep = jax.lax.top_k(all_fin_scores, K)
            fin_seqs_new = jnp.take_along_axis(all_fin_seqs, fin_keep[:, :, None], axis=1)

            # keep top-K live (non-eos) candidates
            live_cand = jnp.where(is_eos, NEG_INF, top_scores)
            live_scores_new, live_keep = jax.lax.top_k(live_cand, K)
            live_seqs_new = jnp.take_along_axis(cand_seqs, live_keep[:, :, None], axis=1)
            chosen_tokens = jnp.take_along_axis(token, live_keep, axis=1)  # (B, K)
            parent_beams = jnp.take_along_axis(beam_idx, live_keep, axis=1)  # (B, K)

            cache = _gather_beams(cache, parent_beams, B, K)
            last = chosen_tokens.reshape(B * K, 1)
            return cache, last, live_scores_new, live_seqs_new, fin_scores_new, fin_seqs_new

        carry = (cache, last, live_scores, live_seqs, fin_scores, fin_seqs)
        _, _, live_scores, live_seqs, fin_scores, fin_seqs = jax.lax.fori_loop(0, L, step, carry)

        # if nothing finished for a batch row, fall back to best live beam
        none_finished = jnp.all(fin_scores <= NEG_INF / 2, axis=1)
        return jnp.where(none_finished[:, None], live_seqs[:, 0], fin_seqs[:, 0])

    return generate
