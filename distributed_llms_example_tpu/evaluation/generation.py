"""Autoregressive generation under jit: greedy and beam search.

The reference calls ``model.generate(max_length=128, num_beams=2)`` for its
live eval loop (reference train-accelerator.py:239-249) and 8 beams in the
dead test path (train-accelerator.py:95-101).  On TPU the decode loop must
be a fixed-shape compiled program: full-length KV cache buffers are
allocated up front, ``lax.fori_loop``/``while_loop`` steps write one token
per iteration, and finished sequences keep "decoding" pad tokens so shapes
never change.  Beam search keeps a flattened (batch × beams) leading dim so
every step is one big MXU-friendly batch.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

NEG_INF = -1.0e7


def _init_cache(model: Any, params: Any, batch: int, max_len: int, enc: jnp.ndarray, enc_mask: jnp.ndarray):
    """Zero cache buffers for a (batch, max_len) decode, via eval_shape (no
    real forward pass)."""
    dummy = jnp.zeros((batch, max_len), jnp.int32)
    shapes = jax.eval_shape(
        lambda p: model.init(
            jax.random.PRNGKey(0), dummy, enc, enc_mask, use_cache=True, max_kv_len=max_len, method="decode"
        ),
        params,
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def make_greedy_generate(model: Any, config: Any, max_new_tokens: int) -> Callable:
    """Jittable greedy decoding: (params, input_ids, attention_mask) → ids
    of shape (batch, max_new_tokens), pad-filled after EOS."""

    eos, pad, start = config.eos_token_id, config.pad_token_id, config.decoder_start_token_id
    forced_bos = getattr(config, "forced_bos_token_id", None)
    forced_eos = getattr(config, "forced_eos_token_id", None)
    L = max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B = input_ids.shape[0]
        enc = model.apply({"params": params}, input_ids, attention_mask, method="encode")
        # cross-attention K/V projected ONCE: per-step re-projection of the
        # full encoder output (2·S·d² per layer) would dominate decode
        ckv = model.apply({"params": params}, enc, method="cross_kv")
        cache = _init_cache(model, params, B, L, enc, attention_mask)

        def step(t, carry):
            cache, last, out, done = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last,
                enc,
                attention_mask,
                use_cache=True,
                cache_offset=t,
                max_kv_len=L,
                cross_kv=ckv,
                method="decode",
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            if forced_bos is not None:  # HF forced_bos_token_id processor
                nxt = jnp.where(t == 0, forced_bos, nxt)
            if forced_eos is not None:  # HF forced_eos_token_id: EOS at max length
                nxt = jnp.where(t == L - 1, forced_eos, nxt)
            nxt = jnp.where(done, pad, nxt)
            out = out.at[:, t].set(nxt)
            done = done | (nxt == eos)
            return mut["cache"], nxt[:, None], out, done

        out = jnp.full((B, L), pad, jnp.int32)
        last = jnp.full((B, 1), start, jnp.int32)
        done = jnp.zeros((B,), bool)
        _, _, out, _ = jax.lax.fori_loop(0, L, step, (cache, last, out, done))
        return out

    return generate


def _causal_prefill(
    model: Any, params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray, new_tokens: int
):
    """One-pass prompt prefill for decoder-only decode.

    Allocates cache buffers for prompt + generation, runs the prompt
    through once, and returns ``(cache, full_mask, lengths, first_logits)``
    where ``first_logits`` is each row's logits at its last *valid* prompt
    position.  Right-padded prompts are supported: RoPE positions follow
    the true sequence (cumsum over the mask), not the cache slot, and pad
    slots stay masked out of attention."""
    B, P = input_ids.shape
    width = P + new_tokens
    shapes = jax.eval_shape(
        lambda p: model.init(
            jax.random.PRNGKey(0), jnp.zeros((B, width), jnp.int32), use_cache=True
        ),
        params,
    )
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])
    full_mask = jnp.concatenate([attention_mask, jnp.zeros((B, new_tokens), jnp.int32)], axis=1)
    lengths = jnp.sum(attention_mask, axis=1).astype(jnp.int32)
    prefill_pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0, None)
    logits, mut = model.apply(
        {"params": params, "cache": cache},
        input_ids,
        full_mask,
        use_cache=True,
        positions=prefill_pos,
        mutable=["cache"],
    )
    first = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return mut["cache"], full_mask, lengths, first


def make_causal_greedy(model: Any, config: Any, max_new_tokens: int) -> Callable:
    """Greedy decoding for decoder-only (causal) models.

    Prefills the prompt into the KV cache in one pass, then decodes one
    token at a time.  Right-padded prompts are supported (see
    ``_causal_prefill``).  With uniform-length prompts this matches HF
    ``generate`` exactly.
    """
    eos, pad = config.eos_token_id, config.pad_token_id
    L = max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B, P = input_ids.shape
        cache, full_mask, lengths, first = _causal_prefill(
            model, params, input_ids, attention_mask, L
        )
        nxt = jnp.argmax(first, axis=-1).astype(jnp.int32)

        def step(t, carry):
            cache, full_mask, last, out, done = carry
            out = out.at[:, t].set(last)
            full_mask = full_mask.at[:, P + t].set(1)
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last[:, None],
                full_mask,
                use_cache=True,
                positions=(lengths + t)[:, None],
                mutable=["cache"],
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            done = done | (last == eos)
            nxt = jnp.where(done, pad, nxt)
            return mut["cache"], full_mask, nxt, out, done

        out = jnp.full((B, L), pad, jnp.int32)
        done = jnp.zeros((B,), bool)
        _, _, _, out, _ = jax.lax.fori_loop(0, L, step, (cache, full_mask, nxt, out, done))
        return out

    return generate


def make_causal_beam_search(
    model: Any,
    config: Any,
    max_new_tokens: int,
    num_beams: int = 2,
    length_penalty: float = 1.0,
) -> Callable:
    """Beam search for decoder-only models (the reference's live eval
    contract is ``num_beams=2``, train-accelerator.py:247 — the round-1
    causal path was greedy-only).

    The prompt is prefilled once at batch ``B`` (beams share the prefix,
    so prefill compute is NOT multiplied by K); the cache is then
    replicated to the flattened (B*K) beam batch and decode steps follow
    the same banked-finished-beams selection as the seq2seq version.
    Right-padded prompts are supported exactly as in ``make_causal_greedy``
    (true-sequence RoPE positions, pad slots masked)."""
    eos, pad = config.eos_token_id, config.pad_token_id
    K, L = num_beams, max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B, P = input_ids.shape
        cache, full_mask, lengths, first = _causal_prefill(
            model, params, input_ids, attention_mask, L
        )
        logp0 = jax.nn.log_softmax(first.astype(jnp.float32), axis=-1)  # (B, V)

        # beams share the prefilled prompt: replicate cache rows K-ways
        cache = jax.tree.map(lambda x: jnp.repeat(x, K, axis=0) if x.ndim > 0 else x, cache)
        full_mask = jnp.repeat(full_mask, K, axis=0)  # (B*K, width)
        lengths_rep = jnp.repeat(lengths, K, axis=0)  # (B*K,)

        # token index 0: run the shared selection on the prefill logits —
        # with live_scores initialized to [0, -inf, ...] only beam 0's
        # distribution contributes, which is exactly the first HF step
        state = _beam_init(B, K, L, pad)
        state, chosen, parents = _beam_step_select(
            jnp.repeat(logp0, K, axis=0), 0, state,
            eos=eos, K=K, length_penalty=length_penalty, len_offset=P - 1,
        )
        cache = _gather_beams(cache, parents, B, K)  # parents all 0: no-op reorder
        last = chosen.reshape(B * K, 1)

        def step(t, carry):
            cache, last, full_mask, state = carry
            # `last` is token index t-1; it occupies cache slot P + t - 1
            full_mask = full_mask.at[:, P + t - 1].set(1)
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last,
                full_mask,
                use_cache=True,
                positions=(lengths_rep + t - 1)[:, None],
                mutable=["cache"],
            )
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
            state, chosen, parents = _beam_step_select(
                logp, t, state, eos=eos, K=K, length_penalty=length_penalty, len_offset=P - 1
            )
            cache = _gather_beams(mut["cache"], parents, B, K)
            return cache, chosen.reshape(B * K, 1), full_mask, state

        _, _, _, state = jax.lax.fori_loop(1, L, step, (cache, last, full_mask, state))
        return _beam_finalize(state, P + L, length_penalty)

    return generate


def _gather_beams(tree: Any, beam_idx: jnp.ndarray, batch: int, beams: int) -> Any:
    """Reorder the flattened (batch*beams, ...) leading dim by per-batch beam
    indices (batch, beams)."""
    flat_idx = (jnp.arange(batch)[:, None] * beams + beam_idx).reshape(-1)
    return jax.tree.map(lambda x: x[flat_idx] if x.ndim > 0 else x, tree)


def _beam_step_select(
    logp: jnp.ndarray,
    t: jnp.ndarray,
    state: tuple,
    *,
    eos: int,
    K: int,
    length_penalty: float,
    len_offset: int = 0,
) -> tuple:
    """One beam-search selection step from per-beam next-token logprobs.

    Shared by the seq2seq and causal searches so the HF-parity semantics
    live in exactly one place.  ``state`` is ``(live_scores, live_seqs,
    fin_scores, fin_seqs, row_done)``; ``logp`` is (B*K, V); ``t`` is the
    token index being chosen.  Matches HF BeamSearchScorer.process:

    - only EOS candidates ranked < num_beams among the top-2K are banked
      (``is_beam_token_worse_than_top_num_beams``);
    - a row is "done" (early_stopping=False) once it holds K banked
      hypotheses whose worst beats the best attainable continuation at the
      current length normalization; done rows stop banking;
    - the normalization length is ``t + 1 + len_offset``: HF divides by the
      full ``input_ids`` length, which for seq2seq is the decoder length
      (offset 0: start token + t generated) and for decoder-only includes
      the prompt (offset P - 1, so the length is P + t).
    """
    live_scores, live_seqs, fin_scores, fin_seqs, row_done = state
    B = live_scores.shape[0]
    V = logp.shape[-1]
    cand = live_scores[:, :, None] + logp.reshape(B, K, V)
    flat = cand.reshape(B, K * V)
    top_scores, top_idx = jax.lax.top_k(flat, 2 * K)  # (B, 2K)
    beam_idx = top_idx // V
    token = (top_idx % V).astype(jnp.int32)

    cand_seqs = jnp.take_along_axis(live_seqs, beam_idx[:, :, None], axis=1)  # (B, 2K, L)
    cand_seqs = cand_seqs.at[:, :, t].set(token)

    is_eos = token == eos
    rank_ok = jnp.arange(2 * K)[None, :] < K
    lp = jnp.asarray(t + 1 + len_offset, jnp.float32) ** length_penalty
    bankable = is_eos & rank_ok & ~row_done[:, None]
    fin_cand = jnp.where(bankable, top_scores / lp, NEG_INF)
    all_fin_scores = jnp.concatenate([fin_scores, fin_cand], axis=1)  # (B, 3K)
    all_fin_seqs = jnp.concatenate([fin_seqs, cand_seqs], axis=1)
    fin_scores_new, fin_keep = jax.lax.top_k(all_fin_scores, K)
    fin_seqs_new = jnp.take_along_axis(all_fin_seqs, fin_keep[:, :, None], axis=1)

    live_cand = jnp.where(is_eos, NEG_INF, top_scores)
    live_scores_new, live_keep = jax.lax.top_k(live_cand, K)
    live_seqs_new = jnp.take_along_axis(cand_seqs, live_keep[:, :, None], axis=1)
    chosen_tokens = jnp.take_along_axis(token, live_keep, axis=1)  # (B, K)
    parent_beams = jnp.take_along_axis(beam_idx, live_keep, axis=1)  # (B, K)

    has_k_banked = fin_scores_new[:, K - 1] > NEG_INF / 2
    # HF is_done uses the best overall candidate sum (next_scores.max(),
    # eos candidates included), not the best surviving live beam
    attainable = top_scores[:, 0] / lp
    row_done_new = row_done | (has_k_banked & (fin_scores_new[:, K - 1] >= attainable))

    new_state = (live_scores_new, live_seqs_new, fin_scores_new, fin_seqs_new, row_done_new)
    return new_state, chosen_tokens, parent_beams


def _beam_init(batch: int, K: int, L: int, pad: int) -> tuple:
    live_scores = jnp.tile(jnp.array([0.0] + [NEG_INF] * (K - 1), jnp.float32), (batch, 1))
    live_seqs = jnp.full((batch, K, L), pad, jnp.int32)
    fin_scores = jnp.full((batch, K), NEG_INF, jnp.float32)
    fin_seqs = jnp.full((batch, K, L), pad, jnp.int32)
    row_done = jnp.zeros((batch,), bool)
    return live_scores, live_seqs, fin_scores, fin_seqs, row_done


def _beam_finalize(state: tuple, final_len: int, length_penalty: float) -> jnp.ndarray:
    """Best sequence per row, HF finalize semantics: rows not yet done also
    consider their best live beam at max length, normalized by the full
    final sequence length (decoder length for seq2seq; prompt + generated
    for decoder-only)."""
    live_scores, live_seqs, fin_scores, fin_seqs, row_done = state
    none_finished = jnp.all(fin_scores <= NEG_INF / 2, axis=1)
    live_final = live_scores[:, 0] / (jnp.asarray(final_len, jnp.float32) ** length_penalty)
    take_live = ~row_done & (none_finished | (live_final > fin_scores[:, 0]))
    return jnp.where(take_live[:, None], live_seqs[:, 0], fin_seqs[:, 0])


def make_beam_search(
    model: Any,
    config: Any,
    max_new_tokens: int,
    num_beams: int = 2,
    length_penalty: float = 1.0,
) -> Callable:
    """Jittable beam search matching HF ``generate(num_beams=K)`` semantics:
    score = sum logprobs / (length ** length_penalty), finished beams
    banked when EOS is chosen, best finished (or live) beam returned."""

    eos, pad, start = config.eos_token_id, config.pad_token_id, config.decoder_start_token_id
    forced_bos = getattr(config, "forced_bos_token_id", None)
    forced_eos = getattr(config, "forced_eos_token_id", None)
    K, L = num_beams, max_new_tokens

    def generate(params: Any, input_ids: jnp.ndarray, attention_mask: jnp.ndarray) -> jnp.ndarray:
        B = input_ids.shape[0]
        enc = model.apply({"params": params}, input_ids, attention_mask, method="encode")
        # replicate encoder outputs per beam: (B*K, S, D)
        enc_rep = jnp.repeat(enc, K, axis=0)
        mask_rep = jnp.repeat(attention_mask, K, axis=0)
        # cross-attention K/V projected ONCE at batch B and kept there:
        # beams of a row share the encoder output, so the attention folds
        # the beam group next to heads (grouped_dot_product_attention) and
        # K/V stream from HBM once per row per step — neither the per-step
        # beam reorder nor a per-beam replica ever touches this tree
        ckv = model.apply({"params": params}, enc, method="cross_kv")
        cache = _init_cache(model, params, B * K, L, enc_rep, mask_rep)

        state = _beam_init(B, K, L, pad)
        last = jnp.full((B * K, 1), start, jnp.int32)

        def step(t, carry):
            cache, last, state = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache},
                last,
                enc_rep,
                mask_rep,
                use_cache=True,
                cache_offset=t,
                max_kv_len=L,
                cross_kv=ckv,
                method="decode",
                mutable=["cache"],
            )
            logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)  # (B*K, V)
            V = logp.shape[-1]
            if forced_bos is not None:  # HF forced_bos_token_id processor
                forced_mask = jnp.full((V,), NEG_INF, jnp.float32).at[forced_bos].set(0.0)
                logp = jnp.where(t == 0, logp + forced_mask[None, :], logp)
            if forced_eos is not None:  # HF forced_eos_token_id: EOS at max length
                eos_mask = jnp.full((V,), NEG_INF, jnp.float32).at[forced_eos].set(0.0)
                logp = jnp.where(t == L - 1, logp + eos_mask[None, :], logp)
            state, chosen, parents = _beam_step_select(
                logp, t, state, eos=eos, K=K, length_penalty=length_penalty
            )
            cache = _gather_beams(mut["cache"], parents, B, K)
            return cache, chosen.reshape(B * K, 1), state

        _, _, state = jax.lax.fori_loop(0, L, step, (cache, last, state))
        # final decoder length = start token + L generated (banking at step t
        # uses t+1; the live-beam convention must match)
        return _beam_finalize(state, L + 1, length_penalty)

    return generate
