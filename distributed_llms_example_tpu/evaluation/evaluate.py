"""The eval loop: jitted generation → decode → ROUGE → cross-host mean.

Mirrors the reference eval pass (train-accelerator.py:237-268): per batch,
``generate`` with beam search, pad/gather across ranks, replace label -100
with pad, decode, feed ROUGE; then aggregate across processes.  Here the
gather is unnecessary (each host scores its own slice and the means are
averaged — exactly what ``synchronize_and_aggregate_metrics`` ends up
computing in the reference), and generation is a fixed-shape jitted
program instead of eager beam decode.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np

from distributed_llms_example_tpu.data.batching import LABEL_PAD, BatchIterator
from distributed_llms_example_tpu.data.dataset import SummarizationDataset
from distributed_llms_example_tpu.data.tokenizer import Tokenizer
from distributed_llms_example_tpu.evaluation import rouge as rouge_mod
from distributed_llms_example_tpu.evaluation.generation import (
    CausalGenerator,
    Seq2SeqGenerator,
)
from distributed_llms_example_tpu.evaluation.metrics import aggregate_mean
from distributed_llms_example_tpu.parallel.activation import activation_mesh
from distributed_llms_example_tpu.train.step import put_batch


def host_rows(arr: Any) -> np.ndarray:
    """Rows of a batch-sharded global array owned by this host, as numpy.

    Single-process: the whole array.  Multi-host: concatenation of this
    host's addressable row shards (deduplicated across model-parallel
    replicas) — the analog of the reference's ``accelerator.gather`` +
    local slice (train-accelerator.py:257-258) without moving other hosts'
    rows over DCN.
    """
    if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform; single-host fast path
        return np.asarray(jax.device_get(arr))
    by_start: dict[int, np.ndarray] = {}
    for s in arr.addressable_shards:
        start = s.index[0].start or 0
        if start not in by_start:
            by_start[start] = np.asarray(s.data)
    return np.concatenate([by_start[k] for k in sorted(by_start)], axis=0)


@dataclasses.dataclass
class Evaluator:
    model: Any
    config: Any
    tokenizer: Tokenizer
    mesh: Any
    num_beams: int = 2
    max_new_tokens: int = 128
    length_penalty: float = 1.0
    is_seq2seq: bool = True

    def __post_init__(self) -> None:
        # prefill/decode SPLIT path: the encoder + cross-KV projection and
        # the per-token decode loop are separately compiled programs, each
        # carrying the sharded cache (batch rows over data×fsdp, heads over
        # tensor — constrain_cache) instead of whatever GSPMD would guess
        # for an unconstrained zeros-init.  Multi-chip eval thus decodes
        # with sharded params AND sharded serving state.
        cls = Seq2SeqGenerator if self.is_seq2seq else CausalGenerator
        self.generator = cls(
            self.model, self.config, self.max_new_tokens,
            num_beams=self.num_beams, length_penalty=self.length_penalty,
        )
        prefill = jax.jit(self.generator.prefill)
        decode = jax.jit(self.generator.decode_loop)
        finalize = jax.jit(self.generator.finalize)

        # tracing must see the mesh so the models' activation + cache
        # constraints bake into the compiled programs (same as the train step)
        def generate(params, ids, mask):
            with activation_mesh(self.mesh):
                carry = prefill(params, ids, mask)
                carry = decode(params, carry)
                return finalize(carry)

        self._generate = generate

    def _decode_batch(self, ids: np.ndarray) -> list[str]:
        eos, pad = self.config.eos_token_id, self.config.pad_token_id
        out = []
        for row in ids:
            toks = []
            for t in row.tolist():
                if t == eos:
                    break
                if t != pad:
                    toks.append(t)
            out.append(self.tokenizer.decode(toks))
        return out

    def run(
        self,
        params: Any,
        ds: SummarizationDataset,
        *,
        global_batch: int,
        bucket_multiple: int = 128,
        max_source_length: int = 1024,
    ) -> dict[str, float]:
        if not self.is_seq2seq:
            return self._run_causal(
                params, ds, global_batch=global_batch, bucket_multiple=bucket_multiple,
                max_source_length=max_source_length,
            )
        it = BatchIterator(
            ds,
            global_batch=global_batch,
            process_count=jax.process_count(),
            process_index=jax.process_index(),
            seed=0,
            shuffle=False,
            drop_last=False,
            bucket_multiple=bucket_multiple,
            max_source_length=max_source_length,
            max_target_length=self.max_new_tokens,
        )
        per_host = global_batch // jax.process_count()
        lo = jax.process_index() * per_host
        n = len(ds)
        preds: list[str] = []
        refs: list[str] = []
        seen = 0
        for batch in it.epoch(0):
            gb = put_batch({k: v for k, v in batch.items() if k != "labels"}, self.mesh)
            out = self._generate(params, gb["input_ids"], gb["attention_mask"])
            labels = batch["labels"]
            labels = np.where(labels == LABEL_PAD, self.config.pad_token_id, labels)
            if jax.process_count() == 1:  # pod-agreed: process_count() is pod-uniform fast path
                local_ids = host_rows(out)[lo : lo + per_host]
            else:
                local_ids = host_rows(out)
            # final wraparound batch: trim rows that duplicate the epoch start
            remaining = n - seen
            valid_global = min(global_batch, remaining)
            valid_here = int(np.clip(valid_global - lo, 0, per_host))
            preds.extend(self._decode_batch(local_ids[:valid_here]))
            refs.extend(self._decode_batch(labels[:valid_here]))
            seen += global_batch
        scores = rouge_mod.compute(preds, refs, use_stemmer=True)
        return aggregate_mean(scores)

    def _run_causal(
        self,
        params: Any,
        ds: Any,  # CausalLMDataset
        *,
        global_batch: int,
        bucket_multiple: int = 128,
        max_source_length: int = 1024,
    ) -> dict[str, float]:
        """Prompt-continuation eval for decoder-only models: generate from
        each prompt, ROUGE vs the reference target."""
        from distributed_llms_example_tpu.data.batching import bucket_len, pad_2d

        pad_id = self.config.pad_token_id
        per_host = global_batch // jax.process_count()
        lo = jax.process_index() * per_host
        n = len(ds)
        preds: list[str] = []
        refs: list[str] = []
        for start in range(0, n, global_batch):
            idx = [(start + i) % n for i in range(global_batch)]
            # bucket width from the GLOBAL batch (shape agreement across
            # hosts); materialize only this host's slice, like run()
            width = bucket_len(
                max(len(ds[i].prompt_ids) for i in idx), bucket_multiple, max_source_length
            )
            local_idx = idx[lo : lo + per_host]
            prompts = [ds[i].prompt_ids for i in local_idx]
            input_ids = pad_2d(prompts, width, pad_id)
            mask = np.zeros_like(input_ids)
            for r, p in enumerate(prompts):
                mask[r, : min(len(p), width)] = 1
            gb = put_batch({"input_ids": input_ids, "attention_mask": mask}, self.mesh)
            out = self._generate(params, gb["input_ids"], gb["attention_mask"])
            local_ids = host_rows(out)
            valid_here = int(np.clip(min(global_batch, n - start) - lo, 0, per_host))
            preds.extend(self._decode_batch(local_ids[:valid_here]))
            refs.extend(
                self.tokenizer.decode([t for t in ds[i].target_ids if t != self.config.eos_token_id])
                for i in local_idx[:valid_here]
            )
        scores = rouge_mod.compute(preds, refs, use_stemmer=True)
        return aggregate_mean(scores)
