"""The sharding-lint CLI — all three analysis passes from abstract inputs.

    python -m distributed_llms_example_tpu.analysis.lint \
        --model llama-2-7b --mesh fsdp=8 [--strict] [--json] [--no-ir]

Runs entirely CPU-safe: the model is resolved to abstract shapes
(``load_weights=False`` + ``eval_shape``), no parameter is ever
materialized.  Output is one finding per line (JSON lines with ``--json``,
reusing utils/jsonlog.py).  Exit status: nonzero when any ``error``
finding is present — or any ``warning`` too under ``--strict`` — so the
command slots straight into CI next to the memory audit.

The same passes run at trainer startup (launch/cli.py, ``--lint warn`` by
default) so an interactive run sees its typo'd spec before spending
minutes compiling.
"""

from __future__ import annotations

import argparse
import json
from typing import Any

from distributed_llms_example_tpu.analysis import (
    composition,
    divergence as divergence_mod,
    ir_lint,
    spec_lint,
)
from distributed_llms_example_tpu.analysis.findings import (
    Finding,
    count_by_severity,
    emit,
    has_errors,
)


def _resolve_axis_sizes(mesh_cfg: Any) -> dict[str, int]:
    """Axis sizes without touching devices: wildcards resolve against the
    attached device count when the product works out, else to 1 (the lint
    cares about the DECLARED sharding, not placement)."""
    import jax

    sizes = dict(mesh_cfg.axis_sizes())
    fixed = 1
    for v in sizes.values():
        if v != -1:
            fixed *= max(v, 1)
    n_dev = jax.device_count()
    for k, v in sizes.items():
        if v == -1:
            sizes[k] = max(1, n_dev // fixed) if n_dev % max(fixed, 1) == 0 else 1
    return sizes


def _parse_rules_json(text: str):
    """``[["pattern", ["fsdp", ["tensor", "expert"], null]], ...]`` →
    ShardingRules.  Lets operators lint a candidate rule set (or seed a
    violation in tests) without editing code."""
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.parallel.sharding import ShardingRules

    def entry(e):
        return tuple(e) if isinstance(e, list) else e

    raw = json.loads(text)
    return ShardingRules(rules=[(pat, P(*[entry(e) for e in spec])) for pat, spec in raw])


def run_passes(
    *,
    model: str,
    mesh_cfg: Any,
    schedule: str = "gpipe",
    rules: Any = None,
    fused_ce: bool = False,
    attention_impl: str = "",
    optim_impl: str = "",
    grad_compression: str = "",
    replicated_bytes_threshold: int = spec_lint.DEFAULT_REPLICATED_BYTES_THRESHOLD,
    run_ir: bool = True,
    global_batch: int = 8,
    src_len: int = 1024,
    tgt_len: int = 128,
    dtype: str = "bfloat16",
    remat: bool = False,
    grad_accum_steps: int = 1,
    serve: bool = False,
    kv_cache_dtype: str = "",
    prefill_buckets: tuple = (),
    reshard_from: Any = None,
    divergence: bool = False,
    memory: bool = False,
    hbm_budget_gib: float = 16.0,
) -> list[Finding]:
    """The analysis passes over one (model, mesh, config) triple.

    ``divergence`` adds the pod-agreement analysis: Layer 1 (the host-AST
    SPMD divergence lint, analysis/divergence.py) always; Layer 2 (the
    cross-program collective census over extra AOT-compiled variants,
    ir_lint.census_findings) when the IR pass runs.  On by default under
    ``--strict``.

    ``memory`` adds the static HBM account (obs/memprof.py) over the
    compiled train step: the bucketed peak composition as an info
    finding, and ``memory-over-budget`` (error) when the compiled peak
    does not fit ``hbm_budget_gib``.  Runs only where the IR pass can
    compile (same gates); skipped configs get a NAMED skip finding, so a
    skipped account never reads as a fitting one.  On by default under
    ``--strict``."""
    import jax

    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import default_rules

    findings: list[Finding] = []
    try:
        lm = load_model(model, load_weights=False)
    except ValueError as e:
        return [Finding("error", "cli", "unknown-model", str(e))]
    axis_sizes = _resolve_axis_sizes(mesh_cfg)

    # Pass 1 — spec lint over the abstract param tree
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    findings += spec_lint.lint_sharding_rules(
        rules if rules is not None else default_rules(),
        axis_sizes,
        a_params,
        replicated_bytes_threshold=replicated_bytes_threshold,
    )
    # the grad-accumulation layout contract: fp32 accumulators mirror the
    # param specs leaf for leaf (train/step.py accumulator_shardings)
    findings += spec_lint.lint_accumulator_mirror(
        a_params, rules if rules is not None else default_rules()
    )
    # the fused-optimizer layout contract: the adam moments (whose paths
    # END with the param path) resolve to the SAME specs as the params —
    # the fused apply shard_maps all four trees with one spec per leaf
    findings += spec_lint.lint_optimizer_moment_mirror(
        a_params, rules if rules is not None else default_rules()
    )
    # the grad-compression layout contract: every error-feedback leaf is
    # the param spec with the worker dim prefixed over the replica axes
    # (ops/quant_collectives.py error_feedback_specs)
    findings += spec_lint.lint_error_feedback_mirror(
        a_params, rules if rules is not None else default_rules()
    )
    # the resharding-restore proof (--reshard-from): cross-check a SAVED
    # topology (mesh config + optional processes/ef_workers — the facts a
    # checkpoint's mesh_layout payload records) against THIS mesh: every
    # leaf resolvable, mirrors re-derived, unmappable factorizations
    # (stage/expert moves) are errors — plus the reshard×pipelined
    # composition row when either side is staged
    if reshard_from is not None:
        saved_axes = (
            dict(reshard_from.get("axes", {}))
            if isinstance(reshard_from, dict)
            else _resolve_axis_sizes(reshard_from)
        )
        saved_layout = {
            "axes": saved_axes,
            "processes": (
                reshard_from.get("processes", 1)
                if isinstance(reshard_from, dict) else 1
            ),
            "ef_workers": (
                reshard_from.get("ef_workers", 0)
                if isinstance(reshard_from, dict) else 0
            ),
        }
        findings += spec_lint.lint_reshard_layout(
            saved_layout, axis_sizes, a_params,
            rules=rules if rules is not None else default_rules(),
        )
        # a stage>1 restore onto the SAME stage factorization is the
        # normal pipelined resume (the stacked-layout leaf guards row
        # order) — the composition row fires only when the stage axis
        # MOVED, matching the trainer's _check_reshardable judgement
        if saved_axes.get("stage", 1) != axis_sizes.get("stage", 1):
            from distributed_llms_example_tpu.analysis.composition import (
                reason_for,
            )

            findings.append(Finding(
                severity="error",
                pass_name="composition",
                code="reshard-pipelined",
                message=reason_for("reshard-pipelined"),
            ))

    # Serving passes (--serve): the KV-cache rule set validated like the
    # param rules, over the abstract decode cache — plus the decode rows
    # of the composition matrix and (below, with the IR pass) the compiled
    # decode step's prefill-in-decode scan
    serve_flags: tuple[str, ...] = ()
    if serve:
        from distributed_llms_example_tpu.evaluation.generation import (
            abstract_cache,
        )

        serve_flags = ("decode", "seq2seq" if lm.is_seq2seq else "causal")
        findings += spec_lint.lint_cache_sharding(
            abstract_cache(
                lm.module, a_params,
                batch=global_batch, max_new_tokens=tgt_len,
                src_len=src_len, is_seq2seq=lm.is_seq2seq,
                kv_cache_dtype=kv_cache_dtype or "f32",
            ),
            axis_sizes,
        )

    # grad-compression needs a replica leg to compress: workers == 1
    # means every step pays quantization noise and a params-sized fp32
    # residual for zero wire savings — reported HERE (and the ir pass
    # stands down below on the error) instead of as a misleading
    # int8-compression-missing on a program that was never wrong
    if grad_compression and grad_compression != "off":
        from distributed_llms_example_tpu.ops.quant_collectives import (
            GRAD_WORKER_AXES,
            worker_count,
        )

        if worker_count(axis_sizes) <= 1:
            findings.append(Finding(
                severity="error",
                pass_name="spec",
                code="grad-compression-no-replica-axis",
                message=(
                    f"--grad-compression int8 needs a replica axis > 1 "
                    f"(mesh axes {GRAD_WORKER_AXES} on {axis_sizes} give "
                    "1 worker group): there is no cross-replica gradient "
                    "leg to compress — drop the flag or add a data axis"
                ),
            ))

    # Pass 3 — composition matrix (cheap; run before the compile pass so a
    # known-crash combo is reported even when the compile would die)
    pipelined = axis_sizes.get("stage", 1) > 1
    findings += composition.check_composition(
        family=lm.family,
        schedule=schedule if pipelined else None,
        mesh_axes=axis_sizes,
        flags=composition.config_flags(
            pipelined=pipelined,
            fused_ce=fused_ce,
            attention_impl=attention_impl,
            num_experts=int(getattr(lm.config, "num_experts", 0) or 0),
            grad_accum_steps=grad_accum_steps,
            optim_impl=optim_impl,
            grad_compression=grad_compression,
        ) | set(serve_flags),
    )

    # Layer 1 of the pod-agreement analysis — the host-AST SPMD divergence
    # lint over the whole package.  Pure AST, no devices, milliseconds:
    # runs on every surface that asks for it (CLI --divergence/--strict,
    # trainer/serve startup lint).
    if divergence:
        div_findings, div_files = divergence_mod.analyze_tree()
        findings += div_findings
        findings.append(Finding(
            severity="info",
            pass_name="divergence",
            code="lint-coverage",
            message=(
                f"divergence pass scanned {div_files} file(s), "
                f"{sum(1 for f in div_findings if f.severity == 'error')} "
                "error(s)"
            ),
            context={"pass": "divergence", "files_scanned": div_files},
        ))

    # Pass 2 — lowered-program lint (needs real devices for the SPMD
    # partitioner; also meaningless for combos pass 3 already condemned).
    # Every AOT-compiled program in the lint set is tracked by NAME in the
    # coverage block: a program that cannot compile on this jax version or
    # host appears as a skipped_programs entry with its reason, never as a
    # silent gap that makes smell coverage look complete when it isn't.
    widths: tuple[int, ...] = ()
    if serve:
        widths = tuple(
            int(b) for b in prefill_buckets if 0 < int(b) < src_len
        ) + (src_len,)
    accum_variant = 2 if grad_accum_steps == 1 else 1
    comp_tag = f",{grad_compression}" if grad_compression and grad_compression != "off" else ""
    train_program = f"train_step[accum={grad_accum_steps}{comp_tag}]"
    planned: list[str] = [train_program]
    if divergence:
        planned.append(f"train_step[accum={accum_variant}{comp_tag}]")
    if serve:
        for width in widths:
            if divergence:
                planned.append(f"prefill[bucket={width}]")
            planned.append(f"decode[bucket={width}]")
    if divergence and reshard_from is not None:
        planned.append("train_step[reshard-saved]")
    programs_scanned: list[str] = []
    programs_skipped: list[dict[str, str]] = []
    ir_skip: list[str] = []

    def skip_all(reason: str) -> None:
        ir_skip.append(reason)
        findings.extend(ir_lint.skipped(reason))
        programs_skipped.extend(
            {"program": name, "reason": reason} for name in planned
        )

    mesh_size = 1
    for v in axis_sizes.values():
        mesh_size *= v
    if not run_ir:
        skip_all("--no-ir")
    elif has_errors(findings):
        skip_all("spec/composition errors make the compile moot")
    elif pipelined:
        skip_all(
            "stage>1 pipelines lower through shard_map schedules on "
            "jax-0.4.37; IR smell patterns for them are an open ROADMAP "
            "item"
        )
    elif mesh_size > jax.device_count():
        skip_all(
            f"mesh size {mesh_size} exceeds attached device count "
            f"{jax.device_count()} (run under "
            f"--xla_force_host_platform_device_count={mesh_size})"
        )
    else:
        from distributed_llms_example_tpu.core.config import MeshConfig

        hlo_texts: dict[str, str] | None = {} if divergence else None
        findings += ir_lint.lint_train_step(
            model,
            mesh_config=MeshConfig(**axis_sizes),
            global_batch=global_batch,
            src_len=src_len,
            tgt_len=tgt_len,
            dtype=dtype,
            remat=remat,
            grad_accum_steps=grad_accum_steps,
            optim_impl=optim_impl,
            grad_compression=grad_compression,
            collect=hlo_texts,
            program=train_program,
        )
        programs_scanned.append(train_program)
        census_pairs: list[tuple[str, str]] = []
        if divergence:
            # determinism probe: a SECOND independent compile of the base
            # train step must schedule the identical collective sequence
            # (per-rank compilation + nondeterministic ordering = pod hang)
            recompile: dict[str, str] = {}
            ir_lint.lint_train_step(
                model,
                mesh_config=MeshConfig(**axis_sizes),
                global_batch=global_batch,
                src_len=src_len,
                tgt_len=tgt_len,
                dtype=dtype,
                remat=remat,
                grad_accum_steps=grad_accum_steps,
                optim_impl=optim_impl,
                grad_compression=grad_compression,
                collect=recompile,
                program=train_program,
            )
            order = ir_lint.signature_order_finding(
                train_program,
                ir_lint.collective_signature(hlo_texts[train_program]),
                ir_lint.collective_signature(recompile[train_program]),
            )
            if order is not None:
                findings.append(order)
            # the accum twin: grad accumulation must not change WHICH
            # worker groups move together, only how often — its smell
            # findings are discarded (the operator's program is the base;
            # the twin exists for the census pairing)
            twin = f"train_step[accum={accum_variant}{comp_tag}]"
            ir_lint.lint_train_step(
                model,
                mesh_config=MeshConfig(**axis_sizes),
                global_batch=global_batch,
                src_len=src_len,
                tgt_len=tgt_len,
                dtype=dtype,
                remat=remat,
                grad_accum_steps=accum_variant,
                optim_impl=optim_impl,
                grad_compression=grad_compression,
                collect=hlo_texts,
                program=twin,
            )
            programs_scanned.append(twin)
            census_pairs.append((train_program, twin))
        if serve:
            # the compiled SERVING decode step(s): no encoder recompute,
            # no per-step cross-KV re-projection (prefill-in-decode), s8
            # cache operands under int8 — one compile per admission
            # bucket, since each bucket's prefill carry shapes its own
            # decode step
            for width in widths:
                decode_name = f"decode[bucket={width}]"
                prefill_name = f"prefill[bucket={width}]" if divergence else ""
                findings += ir_lint.lint_decode_step(
                    model,
                    mesh_config=MeshConfig(**axis_sizes),
                    slots=global_batch,
                    src_len=width,
                    max_new_tokens=tgt_len,
                    dtype=dtype,
                    kv_cache_dtype=kv_cache_dtype,
                    collect=hlo_texts,
                    program=decode_name,
                    prefill_program=prefill_name,
                )
                if prefill_name:
                    programs_scanned.append(prefill_name)
                    census_pairs.append((prefill_name, decode_name))
                    census_pairs.append((train_program, decode_name))
                programs_scanned.append(decode_name)
        if divergence and reshard_from is not None:
            # the reshard-restore TARGET is this mesh's train step (the
            # base program above); the SAVED topology's program joins the
            # census only when it can compile here — and pairs with the
            # target only when both slice the same device world
            saved_axes = dict(reshard_from.get("axes", {})) if isinstance(
                reshard_from, dict) else _resolve_axis_sizes(reshard_from)
            saved_size = 1
            for v in saved_axes.values():
                saved_size *= max(1, int(v))
            name = "train_step[reshard-saved]"
            if saved_axes.get("stage", 1) > 1:
                programs_skipped.append({
                    "program": name,
                    "reason": "saved topology is pipelined (stage>1): no "
                              "IR lowering on jax-0.4.37",
                })
            elif saved_size > jax.device_count():
                programs_skipped.append({
                    "program": name,
                    "reason": f"saved mesh size {saved_size} exceeds "
                              f"attached device count {jax.device_count()}",
                })
            else:
                ir_lint.lint_train_step(
                    model,
                    mesh_config=MeshConfig(**saved_axes),
                    global_batch=global_batch,
                    src_len=src_len,
                    tgt_len=tgt_len,
                    dtype=dtype,
                    remat=remat,
                    grad_accum_steps=grad_accum_steps,
                    optim_impl=optim_impl,
                    grad_compression=grad_compression,
                    collect=hlo_texts,
                    program=name,
                )
                programs_scanned.append(name)
                if saved_size == mesh_size:
                    census_pairs.append((train_program, name))
        if divergence and hlo_texts:
            findings += ir_lint.census_findings(
                {
                    n: ir_lint.collective_signature(text)
                    for n, text in hlo_texts.items()
                },
                census_pairs,
            )
    if memory:
        if ir_skip:
            # the static account rides the IR pass's compile gates: where
            # the train step cannot compile here, the account is SKIPPED
            # by name — never silently reported as fitting
            findings.append(Finding(
                severity="info",
                pass_name="memory",
                code="memory-account-skipped",
                message=f"static HBM account skipped: {ir_skip[0]}",
                context={"pass": "memory", "reason": ir_skip[0]},
            ))
        else:
            from distributed_llms_example_tpu.core.config import MeshConfig

            findings += _memory_findings(
                model,
                MeshConfig(**axis_sizes),
                global_batch=global_batch,
                src_len=src_len,
                tgt_len=tgt_len,
                dtype=dtype,
                remat=remat,
                grad_accum_steps=grad_accum_steps,
                grad_compression=grad_compression,
                hbm_budget_gib=hbm_budget_gib,
            )
    findings.append(Finding(
        severity="info",
        pass_name="ir",
        code="lint-coverage",
        message=(
            f"ir pass scanned {len(programs_scanned)} program(s), "
            f"skipped {len(programs_skipped)}"
            + (
                " — " + "; ".join(
                    f"{e['program']}: {e['reason']}" for e in programs_skipped
                ) if programs_skipped else ""
            )
        ),
        context={
            "pass": "ir",
            "programs_scanned": programs_scanned,
            "programs_skipped": programs_skipped,
        },
    ))
    return findings


def _memory_findings(
    model: str,
    mesh_config: Any,
    *,
    global_batch: int,
    src_len: int,
    tgt_len: int,
    dtype: str,
    remat: bool,
    grad_accum_steps: int,
    grad_compression: str,
    hbm_budget_gib: float,
) -> list[Finding]:
    """The static HBM account as lint findings: one info finding with the
    bucketed peak composition, plus ``memory-over-budget`` (error) when
    the compiled peak exceeds the budget.  A failed account is a NAMED
    warning, not a silent pass."""
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.obs import memprof

    try:
        account = memprof.static_memory_account(
            model,
            build_mesh(mesh_config),
            global_batch=global_batch,
            src_len=src_len,
            tgt_len=tgt_len,
            dtype=dtype,
            remat=remat,
            grad_accum_steps=grad_accum_steps,
            grad_compression=grad_compression,
            hbm_budget_gib=hbm_budget_gib,
        )
    except Exception as e:  # compile/account failure is a finding, not a crash
        return [Finding(
            severity="warning",
            pass_name="memory",
            code="memory-account-failed",
            message=f"static HBM account failed: {type(e).__name__}: "
                    f"{str(e)[:240]}",
            context={"pass": "memory"},
        )]
    buckets = dict(account["buckets_bytes"])
    top = max(buckets, key=lambda k: buckets[k]) if buckets else "other"
    findings = [Finding(
        severity="info",
        pass_name="memory",
        code="memory-account",
        message=(
            f"compiled train-step peak {account['peak_gib']} GiB "
            f"({account['peak_frac_of_budget']:.2f} of the "
            f"{account['hbm_budget_gib']} GiB budget); largest bucket "
            f"{top} = {buckets.get(top, 0) / memprof.GIB:.2f} GiB"
        ),
        context={
            "pass": "memory",
            "peak_bytes": account["peak_bytes"],
            "buckets_bytes": buckets,
            "hbm_budget_gib": account["hbm_budget_gib"],
            "hbm_headroom_gib": account["hbm_headroom_gib"],
            "fits_budget": account["fits_budget"],
            "additivity_gap_bytes": account["additivity_gap_bytes"],
        },
    )]
    if not account["fits_budget"]:
        findings.append(Finding(
            severity="error",
            pass_name="memory",
            code="memory-over-budget",
            message=(
                f"compiled train-step peak {account['peak_gib']} GiB "
                f"exceeds the {account['hbm_budget_gib']} GiB per-device "
                f"HBM budget ({account['peak_frac_of_budget']:.2f}x); "
                f"largest bucket {top} — shrink the batch, raise remat, "
                f"or shard further before launching"
            ),
            context={
                "pass": "memory",
                "peak_bytes": account["peak_bytes"],
                "hbm_budget_gib": account["hbm_budget_gib"],
            },
        ))
    return findings


def startup_lint(cfg: Any) -> list[Finding]:
    """Trainer-startup surface (launch/cli.py): passes 1 and 3 from the
    resolved TrainConfig — no AOT compile, milliseconds not minutes —
    plus Layer 1 of the pod-agreement analysis (the AST divergence lint;
    the HLO census needs the compile pass and stays on the CLI)."""
    return run_passes(
        model=cfg.model_ckpt,
        mesh_cfg=cfg.mesh,
        schedule=cfg.pipeline_schedule,
        fused_ce=cfg.fused_ce,
        attention_impl=cfg.attention_impl,
        optim_impl=cfg.optim_impl,
        grad_compression=getattr(cfg, "grad_compression", ""),
        run_ir=False,
        dtype=cfg.compute_dtype,
        remat=cfg.remat,
        grad_accum_steps=cfg.grad_accum_steps,
        divergence=True,
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dllm-lint",
        description="static sharding analysis over specs, lowered programs, "
                    "and parallelism compositions",
    )
    p.add_argument("--model", required=True, help="registry name or local HF checkpoint dir")
    p.add_argument("--mesh", type=str, default="data=-1", help="comma list axis=size")
    p.add_argument("--pipeline-schedule", type=str, default="gpipe",
                   choices=("gpipe", "1f1b", "interleaved"))
    p.add_argument("--fused-ce", action="store_true")
    p.add_argument("--attention-impl", type=str, default="",
                   choices=("", "auto", "flash", "ring", "xla"))
    p.add_argument("--optim-impl", type=str, default="",
                   choices=("", "auto", "fused", "xla"),
                   help="lint the step built with this optimizer apply; "
                        "'fused' additionally checks the in-place contract "
                        "(no f32 param-sized copies in the apply spans) on "
                        "the compiled program")
    p.add_argument("--grad-compression", type=str, default="",
                   choices=("", "off", "int8"),
                   help="lint the step built with this gradient-collective "
                        "compression; 'int8' additionally asserts the "
                        "compiled program carries s8 gradient collectives "
                        "and checks the error-feedback sharding contract")
    p.add_argument("--rules-json", type=str, default="",
                   help='lint this rule set instead of the defaults: '
                        '[["pattern", ["fsdp", null]], ...]')
    p.add_argument("--replicated-bytes-threshold", type=int,
                   default=spec_lint.DEFAULT_REPLICATED_BYTES_THRESHOLD)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--src-len", type=int, default=1024)
    p.add_argument("--tgt-len", type=int, default=128)
    p.add_argument("--dtype", type=str, default="bfloat16")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--grad-accum-steps", type=int, default=1,
                   help="lint the in-step grad-accumulation config: the "
                        "composition row (accum x stage>1) and, with the IR "
                        "pass, the once-per-step optimizer placement census")
    p.add_argument("--serve", action="store_true",
                   help="also lint the SERVING surfaces: cache sharding "
                        "rules over the abstract decode cache, the decode "
                        "composition rows, and (with the IR pass) the "
                        "compiled decode step's prefill-in-decode scan")
    p.add_argument("--kv-cache-dtype", type=str, default="",
                   choices=("", "f32", "int8"),
                   help="with --serve: lint the abstract cache at this KV "
                        "storage dtype (int8 adds the scale leaves to the "
                        "spec pass and requires s8 cache operands in the "
                        "compiled decode step — int8-kv-missing)")
    p.add_argument("--prefill-buckets", type=str, default="",
                   help="with --serve: comma list of admission widths; the "
                        "compiled decode-step scan runs once per bucket "
                        "(each bucket's prefill carry shapes its own step)")
    p.add_argument("--reshard-from", type=str, default="",
                   help="run the resharding-restore proof pass: the SAVED "
                        "topology's mesh as a comma list axis=size (what a "
                        "checkpoint's mesh_layout payload records), judged "
                        "against --mesh as the restore target")
    p.add_argument("--reshard-processes", type=int, default=1,
                   help="saved process count for --reshard-from")
    p.add_argument("--reshard-ef-workers", type=int, default=0,
                   help="saved error-feedback worker count for "
                        "--reshard-from (0 = no EF tree in the payload)")
    p.add_argument("--no-ir", action="store_true",
                   help="skip the lowered-program pass (no AOT compile)")
    p.add_argument("--divergence", action="store_true",
                   help="run the pod-agreement analysis: the host-AST SPMD "
                        "divergence lint (rank-divergent branches feeding "
                        "collectives) and, with the IR pass, the "
                        "cross-program collective-matching census over the "
                        "compiled lint set; implied by --strict")
    p.add_argument("--memory", action="store_true",
                   help="run the static HBM account (obs/memprof.py) over "
                        "the compiled train step: the bucketed peak "
                        "composition as an info finding, memory-over-budget "
                        "(error) when the compiled peak exceeds "
                        "--hbm-budget-gib; rides the IR pass's compile gates "
                        "(skipped by name where the step cannot compile "
                        "here); implied by --strict")
    p.add_argument("--hbm-budget-gib", type=float, default=16.0,
                   help="per-device HBM budget for --memory's over-budget "
                        "verdict (default 16.0 = one v5e core)")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail the run (implies --divergence "
                        "and --memory)")
    p.add_argument("--json", action="store_true", help="JSON-lines output")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    findings: list[Finding] = []
    mesh_cfg = rules = None
    try:
        from distributed_llms_example_tpu.core.config import parse_mesh_arg

        mesh_cfg = parse_mesh_arg(args.mesh)
    except ValueError as e:
        findings.append(Finding("error", "cli", "unknown-mesh-axis", str(e)))
    if args.rules_json:
        try:
            rules = _parse_rules_json(args.rules_json)
        except (ValueError, TypeError) as e:
            findings.append(Finding("error", "cli", "bad-rules-json", str(e)))
    reshard_from = None
    if args.reshard_from:
        try:
            from distributed_llms_example_tpu.core.config import parse_mesh_arg

            saved_sizes = dict(parse_mesh_arg(args.reshard_from).axis_sizes())
            wild = sorted(a for a, v in saved_sizes.items() if v == -1)
            if wild:
                # the saved topology is a HISTORICAL fact: resolving a
                # wildcard against THIS host's device count would lint a
                # factorization that was never saved
                findings.append(Finding(
                    "error", "cli", "reshard-from-wildcard",
                    f"--reshard-from must pin every axis explicitly "
                    f"(unresolved: {', '.join(wild)}): the saved topology "
                    "cannot be inferred from this host's device count — "
                    "read it from the checkpoint's recovery sidecar or "
                    "mesh_layout payload leaf",
                ))
            else:
                reshard_from = {
                    "axes": saved_sizes,
                    "processes": args.reshard_processes,
                    "ef_workers": args.reshard_ef_workers,
                }
        except ValueError as e:
            findings.append(Finding("error", "cli", "unknown-mesh-axis", str(e)))
    if not findings:
        findings = run_passes(
            model=args.model,
            mesh_cfg=mesh_cfg,
            schedule=args.pipeline_schedule,
            rules=rules,
            fused_ce=args.fused_ce,
            attention_impl=args.attention_impl,
            optim_impl=args.optim_impl,
            grad_compression=args.grad_compression,
            replicated_bytes_threshold=args.replicated_bytes_threshold,
            run_ir=not args.no_ir,
            global_batch=args.batch,
            src_len=args.src_len,
            tgt_len=args.tgt_len,
            dtype=args.dtype,
            remat=args.remat,
            grad_accum_steps=args.grad_accum_steps,
            serve=args.serve,
            kv_cache_dtype=args.kv_cache_dtype,
            prefill_buckets=tuple(
                int(b) for b in args.prefill_buckets.split(",") if b.strip()
            ),
            reshard_from=reshard_from,
            divergence=args.divergence or args.strict,
            memory=args.memory or args.strict,
            hbm_budget_gib=args.hbm_budget_gib,
        )
    emit(findings, as_json=args.json)
    counts = count_by_severity(findings)
    coverage = [f for f in findings if f.code == "lint-coverage"]
    if args.json:
        from distributed_llms_example_tpu.utils.jsonlog import log_json

        # the per-pass coverage block: what was scanned and — by NAME,
        # with a reason — what was not, so a skipped program can never
        # read as covered
        for f in coverage:
            log_json({"event": "lint_coverage", **f.context})
        log_json({
            "event": "lint_summary",
            **counts,
            "programs_scanned": sum(
                len(f.context.get("programs_scanned", ())) for f in coverage
            ),
            "programs_skipped": sum(
                len(f.context.get("programs_skipped", ())) for f in coverage
            ),
        })
    else:
        print(
            f"lint: {counts['error']} error(s), {counts['warning']} "
            f"warning(s), {counts['info']} info"
        )
    failed = counts["error"] > 0 or (args.strict and counts["warning"] > 0)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
