"""Finding: the unit of lint output.

Every pass produces a flat list of Findings; the CLI serializes them one
JSON line per finding (the Valohai metadata convention, utils/jsonlog.py)
so CI can grep ``"severity": "error"`` and operators can read the same
stream humans do.  Severity contract:

- ``error``   — will crash, hang, or silently waste HBM at scale (unknown
                axis, oversized replicated param, known-bad composition).
                Nonzero CLI exit.
- ``warning`` — smells that are sometimes intentional (dead rules, ragged
                fallbacks, IR promotion chains).  Nonzero exit only under
                ``--strict``.
- ``info``    — context the operator should see (pass skipped, collective
                census).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Mapping

SEVERITIES: tuple[str, ...] = ("error", "warning", "info")


@dataclasses.dataclass(frozen=True)
class Finding:
    severity: str
    pass_name: str  # "spec" | "ir" | "composition" | "cli"
    code: str  # stable machine-readable slug, e.g. "unknown-mesh-axis"
    message: str
    context: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {SEVERITIES}"
            )

    def to_json(self) -> dict:
        out = {
            "event": "lint_finding",
            "severity": self.severity,
            "pass": self.pass_name,
            "code": self.code,
            "message": self.message,
        }
        out.update(self.context)
        return out

    def render(self) -> str:
        return f"{self.severity}: [{self.pass_name}/{self.code}] {self.message}"


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == "error" for f in findings)


def count_by_severity(findings: Iterable[Finding]) -> dict[str, int]:
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] += 1
    return counts


def emit(findings: Iterable[Finding], *, as_json: bool, file=None) -> None:
    """Print findings, one per line: JSON lines (``log_json``, process-0
    gated like every other metadata producer) or the human rendering."""
    from distributed_llms_example_tpu.utils.jsonlog import log_json

    for f in findings:
        if as_json:
            log_json(f.to_json(), file=file)
        else:
            print(f.render(), file=file)
