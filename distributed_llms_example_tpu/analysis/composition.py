"""Pass 3 — the parallelism composition matrix.

The repo used to guard bad (schedule × sharding × model-family) combos
with ad-hoc ``raise`` statements scattered across the pipeline adapters,
the trainer, and the seq2seq executor — commit ``ac1288e`` alone added
three copies of the 1f1b×fsdp guard.  This module replaces them with ONE
declarative table: a known-bad combo is a ``BadCombo`` row, the adapters
call ``validate_composition`` at construction, the lint CLI calls
``check_composition`` for findings, and a new bad pair discovered at scale
is one table row — not another scatter of raises.

Matching model: a combo row fires when ALL of its conditions hold —

- ``schedules``:      pipeline schedule is one of these (None = any)
- ``families``:       model family is one of these (None = any)
- ``flags``:          every named flag is present.  Families imply flags
                      (bart/t5 → ``seq2seq``, llama → ``causal``) so deep
                      call sites that know the shape but not the family
                      (parallel/pipeline_seq2seq.py) can still match.
- ``axes_over_1``:    every listed mesh axis has size > 1
- ``axes_any_over_1``: at least one listed mesh axis has size > 1

Known flags: ``pipelined`` (a stage>1 pipeline adapter is in play),
``seq2seq``/``causal`` (family shape), ``moe`` (config has routed
experts), ``fused_ce`` (--fused-ce), ``ring`` (--attention-impl ring),
``forced_dense_attention`` (--attention-impl xla/flash), ``grad_accum``
(--grad-accum-steps > 1 — the in-step scan accumulation),
``fused_optim`` (an EXPLICIT --optim-impl fused; ``auto`` never sets
the flag because it resolves to xla wherever fused cannot run),
``grad_compression`` (--grad-compression int8 — the quantized gradient
collectives of ops/quant_collectives.py),
``decode`` (the KV-cache serving workload: prefill/decode split +
continuous batching — serving/engine.py and the Evaluator's split
path), ``router`` (the serve-router replica tier above the engines —
serving/router.py; implies ``decode`` per replica).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Mapping

from distributed_llms_example_tpu.analysis.findings import Finding

FAMILY_FLAGS: dict[str, tuple[str, ...]] = {
    "bart": ("seq2seq",),
    "t5": ("seq2seq",),
    "llama": ("causal",),
}


@dataclasses.dataclass(frozen=True)
class BadCombo:
    id: str
    reason: str
    schedules: tuple[str, ...] | None = None
    families: tuple[str, ...] | None = None
    flags: tuple[str, ...] = ()
    axes_over_1: tuple[str, ...] = ()
    axes_any_over_1: tuple[str, ...] = ()

    def matches(
        self,
        *,
        family: str | None,
        schedule: str | None,
        mesh_axes: Mapping[str, int],
        flags: frozenset[str],
    ) -> bool:
        if self.schedules is not None and schedule not in self.schedules:
            return False
        if self.families is not None and family not in self.families:
            return False
        if not set(self.flags) <= flags:
            return False
        if any(mesh_axes.get(a, 1) <= 1 for a in self.axes_over_1):
            return False
        if self.axes_any_over_1 and not any(
            mesh_axes.get(a, 1) > 1 for a in self.axes_any_over_1
        ):
            return False
        return True


@dataclasses.dataclass(frozen=True)
class GoodCombo:
    """A composition the test suite pins as working — documentation for
    operators and the lint's source for "recognized" info findings."""

    id: str
    notes: str
    schedules: tuple[str, ...] | None = None
    flags: tuple[str, ...] = ()
    axes: tuple[str, ...] = ()  # the axes this combo is validated to use


# Ordering matters: ``validate_composition`` raises the FIRST matching
# row's reason, so more specific rows go first.
KNOWN_BAD: tuple[BadCombo, ...] = (
    BadCombo(
        id="grad-accum-pipelined",
        flags=("grad_accum", "pipelined"),
        reason=(
            "--grad-accum-steps > 1 does not compose with stage>1 "
            "pipelines: the pipeline executors already microbatch inside "
            "their schedules (--pipeline-microbatches) — stacking the "
            "in-step accumulation scan on top double-accumulates the same "
            "memory trade for pure scan overhead; raise "
            "--pipeline-microbatches instead (the step owns accumulation "
            "on GSPMD meshes, the pipeline owns it under stage>1)"
        ),
    ),
    BadCombo(
        id="reshard-pipelined",
        flags=("reshard", "pipelined"),
        reason=(
            "topology-change resharding does not compose with stage>1 "
            "pipelines: the stacked-block STORAGE layout is a function of "
            "the stage count (interleaved packing puts each device's "
            "virtual-stage chunks contiguously), so restoring onto a "
            "resized stage axis silently permutes the model's layers — "
            "stage>1 owns its layout; restart on a slice with the SAME "
            "stage factorization (data/fsdp/tensor re-factorizations are "
            "the ones the resharding restore supports)"
        ),
    ),
    BadCombo(
        id="grad-compression-pipelined",
        flags=("grad_compression", "pipelined"),
        reason=(
            "--grad-compression int8 does not compose with stage>1 "
            "pipelines: the pipeline executors own their communication "
            "schedules (microbatch hops over the stage ring, their own "
            "gradient flow inside fused 1f1b schedules) — the replica-"
            "tiled backward the compression wraps has no seam there; run "
            "compression on GSPMD (data/fsdp/tensor) meshes"
        ),
    ),
    BadCombo(
        id="grad-compression-sequence",
        flags=("grad_compression",),
        axes_over_1=("sequence",),
        reason=(
            "--grad-compression int8 does not compose with sequence "
            "parallelism: ring attention runs as fully-manual shard_map "
            "regions that do not nest inside the replica-tiled backward "
            "(the vmapped per-worker value_and_grad clears the ambient "
            "mesh); drop the sequence axis or the compression flag"
        ),
    ),
    BadCombo(
        id="fused-optim-pipelined",
        flags=("fused_optim", "pipelined"),
        reason=(
            "--optim-impl fused does not compose with stage>1 pipelines: "
            "the fused apply dispatches its per-leaf shard_map from the "
            "param PartitionSpecs, and the pipelined stacked-block layout "
            "(stage-sharded leading layer dim, schedule-dependent storage "
            "order) is unproven under it — use --optim-impl auto (which "
            "resolves to the optax chain under a pipeline) or xla"
        ),
    ),
    BadCombo(
        id="router-pipelined",
        flags=("router",),
        axes_over_1=("stage",),
        reason=(
            "the serve-router replica pool stands on KV-cache decode "
            "engines, which stage>1 pipelines cannot run "
            "(decode-pipelined): replicas shard the REQUEST stream, not "
            "the model schedule — unstack pipelined params onto an "
            "fsdp/tensor mesh before serving, then replicate"
        ),
    ),
    BadCombo(
        id="decode-pipelined",
        flags=("decode",),
        axes_over_1=("stage",),
        reason=(
            "KV-cache decode does not run through stage>1 pipelines: the "
            "pipeline schedules are training/teacher-forced only (no cache "
            "path in their manual regions) — unstack the pipelined params "
            "onto an fsdp/tensor mesh to serve (the trainer's ROUGE eval "
            "does exactly this)"
        ),
    ),
    BadCombo(
        id="decode-sequence",
        flags=("decode",),
        axes_over_1=("sequence",),
        reason=(
            "KV-cache decode does not compose with sequence parallelism: "
            "a length-sharded cache would index slots with LOCAL shard "
            "positions (the same contract ops/mha.py enforces inside "
            "manual sequence regions); serve on data/fsdp/tensor axes — "
            "the cache shards batch rows and heads instead"
        ),
    ),
    BadCombo(
        id="seq2seq-1f1b-fsdp",
        schedules=("1f1b",),
        flags=("seq2seq",),
        axes_over_1=("stage", "fsdp"),
        reason=(
            "the fused seq2seq 1f1b schedule does not support fsdp>1: the "
            "XLA SPMD partitioner SIGABRTs (no diagnostic) compiling the "
            "twin chunk-pair program with dim-0-fsdp-sharded block params; "
            "use --pipeline-schedule gpipe on fsdp×stage meshes, or tensor "
            "parallelism with 1f1b"
        ),
    ),
    BadCombo(
        id="seq2seq-interleaved",
        schedules=("interleaved",),
        flags=("seq2seq",),
        reason=(
            "--pipeline-schedule interleaved currently supports decoder-only "
            "(llama) families only; the seq2seq families pipeline under "
            "gpipe or the fused twin-pipeline 1f1b"
        ),
    ),
    BadCombo(
        id="seq2seq-pipeline-sequence",
        flags=("seq2seq", "pipelined"),
        axes_over_1=("sequence",),
        reason=(
            "the seq2seq pipeline (stage>1) does not compose with sequence "
            "parallelism: ring attention for encoder/decoder stacks runs as "
            "its own fully-manual shard_map, which does not nest inside the "
            "pipeline's manual region"
        ),
    ),
    BadCombo(
        id="pipeline-sequence-moe",
        flags=("pipelined", "moe"),
        axes_over_1=("sequence",),
        reason=(
            "pipeline MoE (load-balance aux loss) does not compose with "
            "sequence parallelism: per-shard router statistics would need "
            "their own cross-sequence reduction"
        ),
    ),
    BadCombo(
        id="fused-ce-seq2seq",
        flags=("fused_ce", "seq2seq"),
        reason=(
            "--fused-ce supports causal (decoder-only) families; seq2seq "
            "models compute their loss from decoder logits directly"
        ),
    ),
    BadCombo(
        id="fused-ce-model-axes",
        flags=("fused_ce",),
        axes_any_over_1=("tensor", "stage", "sequence"),
        reason=(
            "--fused-ce does not compose with tensor/stage/sequence mesh "
            "axes: the vocab-chunked LM head wants an unsharded vocab dim "
            "and the standard (non-pipelined) loss path; use data/fsdp axes "
            "or drop the flag"
        ),
    ),
    BadCombo(
        id="ring-seq2seq-pipeline",
        flags=("ring", "seq2seq", "pipelined"),
        reason=(
            "--attention-impl ring composes with stage>1 only for the llama "
            "family (ONE manual region over {stage, sequence}); the seq2seq "
            "families run ring as its own fully-manual shard_map, which "
            "does not nest"
        ),
    ),
    BadCombo(
        id="dense-attention-stage-sequence",
        flags=("forced_dense_attention", "pipelined"),
        families=("llama",),
        axes_over_1=("stage", "sequence"),
        reason=(
            "--attention-impl xla/flash cannot run on a stage×sequence mesh "
            "(the pipeline's manual region executes ring attention only); "
            "use auto or ring"
        ),
    ),
)

# The combinations the test suite pins as working (tests/test_pipeline*.py,
# tests/test_train_step.py).  A requested combo matching neither table gets
# a "composition-unproven" warning from the lint — not an error: absence of
# evidence is a prompt to add a row, not a crash claim.
KNOWN_GOOD: tuple[GoodCombo, ...] = (
    GoodCombo(
        id="gspmd-data-fsdp-tensor-expert",
        axes=("data", "fsdp", "tensor", "expert"),
        notes="no pipeline: GSPMD partitions everything (all families)",
    ),
    GoodCombo(
        id="decode-gspmd",
        flags=("decode",),
        axes=("data", "fsdp", "tensor", "expert"),
        notes="KV-cache serving: cache slots shard batch rows over "
              "data×fsdp×expert and heads over tensor (CACHE_RULES); "
              "pinned by the continuous-batching determinism test on the "
              "8-device mesh",
    ),
    GoodCombo(
        id="router-gspmd",
        flags=("decode", "router"),
        axes=("data", "fsdp", "tensor", "expert"),
        notes="serve-router replica pool over N engines sharing one GSPMD "
              "mesh: session-affinity + queue-depth dispatch, "
              "crash/stall re-prefill pinned bit-identical to the "
              "single-engine oracle, graceful drain loses zero requests "
              "(tests/test_router.py)",
    ),
    GoodCombo(
        id="fused-optim-gspmd",
        flags=("fused_optim",),
        axes=("data", "fsdp", "tensor", "expert"),
        notes="fused clip+AdamW apply (ops/fused_optim.py): per-leaf "
              "shard_map on the param specs, composes with in-step grad "
              "accumulation (the apply consumes the scan's param-sharded "
              "fp32 accumulators); pinned equivalent to the optax chain "
              "(same op sequence, equal up to XLA float contraction) on "
              "the 8-device mesh (tests/test_fused_optim.py)",
    ),
    GoodCombo(
        id="grad-compression-gspmd",
        flags=("grad_compression",),
        axes=("data", "fsdp", "tensor"),
        notes="int8 quantized gradient collectives (ops/quant_collectives"
              ".py): per-worker partial grads tiled over the data axis, "
              "s8 all-to-all/all-gather wire, int-safe partial sums, "
              "error feedback in TrainState.ef; pinned on the 8-device "
              "data x fsdp x tensor mesh (tests/test_quant_collectives.py)",
    ),
    GoodCombo(
        id="grad-compression-accum",
        flags=("grad_compression", "grad_accum"),
        axes=("data", "fsdp", "tensor"),
        notes="compression x in-step grad accumulation: the scan "
              "accumulates fp32 TILED partial sums and the quantized "
              "reduction + error feedback apply ONCE at the optimizer-"
              "step boundary, after the microbatch accumulation — the "
              "once-per-step placement census covers the reduction's "
              "source spans, so the compiled program proves it",
    ),
    GoodCombo(
        id="sequence-parallel-unpipelined",
        axes=("data", "fsdp", "sequence", "tensor"),
        notes="ring/context parallelism without stages (all families)",
    ),
    GoodCombo(
        id="gpipe-all-families",
        schedules=("gpipe",),
        axes=("stage", "data", "fsdp", "tensor", "expert"),
        notes="gpipe composes with data/fsdp/tensor/expert (MoE aux rides "
              "out of the pipeline as an explicit output)",
    ),
    GoodCombo(
        id="1f1b-llama",
        schedules=("1f1b",),
        flags=("causal",),
        axes=("stage", "data", "fsdp", "tensor", "sequence"),
        notes="fused 1f1b, single chunk body: full axis composition",
    ),
    GoodCombo(
        id="1f1b-seq2seq-tensor",
        schedules=("1f1b",),
        flags=("seq2seq",),
        axes=("stage", "data", "tensor"),
        notes="twin-pipeline 1f1b: data/tensor compose; fsdp is the "
              "known-bad row seq2seq-1f1b-fsdp",
    ),
    GoodCombo(
        id="interleaved-llama",
        schedules=("interleaved",),
        flags=("causal",),
        axes=("stage", "data", "fsdp", "tensor"),
        notes="virtual-stage 1f1b, stage >= 2, decoder-only",
    ),
)


def config_flags(
    *,
    pipelined: bool,
    fused_ce: bool = False,
    attention_impl: str = "",
    num_experts: int = 0,
    grad_accum_steps: int = 1,
    optim_impl: str = "",
    grad_compression: str = "",
) -> set[str]:
    """Derive the composition-matrix flags from run configuration — the
    ONE mapping from config knobs to table flags, shared by the Trainer's
    startup validation and the lint CLI so they can never disagree about
    which combos are bad."""
    flags: set[str] = set()
    if pipelined:
        flags.add("pipelined")
    if fused_ce:
        flags.add("fused_ce")
    if num_experts > 0:
        flags.add("moe")
    if grad_accum_steps > 1:
        flags.add("grad_accum")
    if grad_compression and grad_compression != "off":
        flags.add("grad_compression")
    if optim_impl == "fused":
        # ONLY the explicit force: "auto" resolves to xla wherever fused
        # cannot run, so it must never trip the known-bad row
        flags.add("fused_optim")
    if attention_impl == "ring":
        flags.add("ring")
    elif attention_impl in ("xla", "flash"):
        flags.add("forced_dense_attention")
    return flags


def effective_flags(family: str | None, flags: Iterable[str] = ()) -> frozenset[str]:
    out = set(flags)
    out.update(FAMILY_FLAGS.get(family or "", ()))
    return frozenset(out)


def failing_combos(
    *,
    family: str | None = None,
    schedule: str | None = None,
    mesh_axes: Mapping[str, int],
    flags: Iterable[str] = (),
) -> list[BadCombo]:
    eff = effective_flags(family, flags)
    return [
        row
        for row in KNOWN_BAD
        if row.matches(family=family, schedule=schedule, mesh_axes=mesh_axes, flags=eff)
    ]


def reason_for(combo_id: str) -> str:
    """The table's message for a row id — deep guards (e.g. the seq2seq
    executor, which knows the shape but not the family) raise this text so
    the message cannot drift from the table."""
    for row in KNOWN_BAD:
        if row.id == combo_id:
            return row.reason
    raise KeyError(f"no known-bad combo {combo_id!r}")


def validate_composition(
    *,
    family: str | None = None,
    schedule: str | None = None,
    mesh_axes: Mapping[str, int],
    flags: Iterable[str] = (),
) -> None:
    """Raise ValueError with the first failing row's reason — the adapter-
    construction entry point (PipelinedLlama/Bart/T5, Trainer)."""
    bad = failing_combos(
        family=family, schedule=schedule, mesh_axes=mesh_axes, flags=flags
    )
    if bad:
        raise ValueError(bad[0].reason)


def check_composition(
    *,
    family: str | None = None,
    schedule: str | None = None,
    mesh_axes: Mapping[str, int],
    flags: Iterable[str] = (),
) -> list[Finding]:
    """The lint entry point: every failing row becomes an error finding;
    a pipelined combo matching no good row gets an unproven warning."""
    eff = effective_flags(family, flags)
    findings = [
        Finding(
            severity="error",
            pass_name="composition",
            code=row.id,
            message=row.reason,
            context={
                "family": family,
                "schedule": schedule,
                "mesh": dict(mesh_axes),
            },
        )
        for row in failing_combos(
            family=family, schedule=schedule, mesh_axes=mesh_axes, flags=flags
        )
    ]
    if findings or mesh_axes.get("stage", 1) <= 1:
        return findings

    def good_matches(row: GoodCombo) -> bool:
        if row.schedules is not None and schedule not in row.schedules:
            return False
        if not set(row.flags) <= eff:
            return False
        # every mesh axis actually in use must be one the row vouches for
        used = {a for a, n in mesh_axes.items() if n > 1}
        return used <= set(row.axes)

    matched = [row for row in KNOWN_GOOD if good_matches(row)]
    if matched:
        findings.append(
            Finding(
                severity="info",
                pass_name="composition",
                code="composition-recognized",
                message=f"combo matches known-good row {matched[0].id!r}: {matched[0].notes}",
                context={"good_id": matched[0].id},
            )
        )
    else:
        findings.append(
            Finding(
                severity="warning",
                pass_name="composition",
                code="composition-unproven",
                message=(
                    "requested schedule × sharding × family combo matches no "
                    "known-good table row; it may work, but nothing pins it — "
                    "add a KNOWN_GOOD row once validated"
                ),
                context={
                    "family": family,
                    "schedule": schedule,
                    "mesh": dict(mesh_axes),
                },
            )
        )
    return findings
