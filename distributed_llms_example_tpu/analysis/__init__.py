"""Static sharding analysis: lint passes over specs, lowered programs, and
parallelism compositions.

Declarative sharding fails silently or fatally — a typo'd PartitionSpec
axis replicates a 7B parameter until HBM blows, and bad schedule × sharding
compositions crash the XLA SPMD partitioner with no diagnostic (the
seq2seq 1f1b × fsdp SIGABRT).  This package catches those classes of
mistake from ABSTRACT inputs (ShapeDtypeStruct, no weights, CPU-safe):

- ``spec_lint``    — pass 1: ShardingRules vs mesh vs abstract param tree
- ``ir_lint``      — pass 2: smells in the compiled train-step HLO
- ``composition``  — pass 3: the known-valid/known-bad (schedule ×
                     sharding × family) table, also consulted by the
                     pipeline adapters at construction
- ``lint``         — the CLI gluing all three:
                     ``python -m distributed_llms_example_tpu.analysis.lint``
"""

from distributed_llms_example_tpu.analysis.findings import Finding, has_errors
from distributed_llms_example_tpu.analysis.composition import (
    KNOWN_BAD,
    check_composition,
    reason_for,
    validate_composition,
)

__all__ = [
    "Finding",
    "has_errors",
    "KNOWN_BAD",
    "check_composition",
    "reason_for",
    "validate_composition",
]
