"""Layer 1 of the pod-agreement static analysis: the SPMD divergence lint.

The deadliest bug class at pod scale is a *rank-divergent branch feeding a
collective*: one process takes a path the others don't, reaches (or skips)
a collective, and the pod deadlocks with every other rank parked inside an
all-reduce that will never complete.  PRs 6, 14, and 15 each shipped
review fixes for exactly this shape — a one-rank restore exception walking
only that rank back, a p0-only verify verdict never broadcast, a
metadata-less fallback ladder retrying a rank-varying number of times
before a collective.  This pass turns that hand-review discipline into a
machine check over the host-side Python of ``distributed_llms_example_tpu``.

The model is classic taint analysis, with the three registries **owned by
this spec** (not by convention — a helper is an agreement sanitizer
because it is listed here, and review of this file is review of the
pod-agreement contract):

- *Sources* (``SOURCES``): expressions whose value can differ per rank —
  ``jax.process_index()``, local file I/O results (``open``, ``os.path.
  exists``, ``os.listdir``...), and exception bindings (``except E as e``
  — an exception object exists only on the ranks that threw).  Note that
  ``jax.process_count()`` is deliberately NOT a source: it is pod-uniform
  (every rank computes the same value), so branches on it are taken
  identically everywhere.  The lexical rule 13 in scripts/repo_lint.py
  still fences WHERE such branches may be written.
- *Sanitizers* (``SANITIZERS``): the agreement helpers.  A value produced
  by (or an expression containing a call to) one of these is pod-agreed:
  every rank holds the same verdict afterwards, so branching on it is
  safe.  These are the heartbeat allgather channel and the MIN/MAX/
  broadcast-verdict helpers built on it.
- *Sinks* (``SINKS``): calls that execute or imply a collective — the
  compiled train/prefill/decode step invocations, checkpoint save/
  restore (orbax-style multi-host commit), the heartbeat channel itself,
  mesh (re)bootstrap, and global-batch assembly.  Reaching a sink on a
  rank-divergent path is an error.

Waiver: a line (the sink call, or the divergent branch header) annotated
``# pod-agreed: <mechanism>`` is exempt — the comment must NAME the
agreement mechanism, and rule 13 enforces the same pragma grammar
lexically.  The pragma is the paper trail the next reviewer reads.

Findings ride analysis/findings.py (pass_name ``"divergence"``) and the
lint driver (``analysis/lint.py --divergence``, on by default under
``--strict``); Layer 2 — the cross-program HLO collective census — lives
in analysis/ir_lint.py.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from distributed_llms_example_tpu.analysis.findings import Finding

# --------------------------------------------------------------------------
# The registries.  Owned by spec: a name is a source/sanitizer/sink because
# it appears here, with the rationale next to it.
# --------------------------------------------------------------------------

#: Rank-local value producers — call names whose result can differ per rank.
SOURCES: dict[str, str] = {
    "process_index": "jax.process_index() — the rank identity itself",
    "open": "local file handle/content — disk state is per-host",
    "exists": "os.path.exists — per-host filesystem probe",
    "isfile": "os.path.isfile — per-host filesystem probe",
    "isdir": "os.path.isdir — per-host filesystem probe",
    "listdir": "os.listdir — per-host directory listing",
    "scandir": "os.scandir — per-host directory listing",
    "glob": "glob.glob — per-host directory listing",
    "iglob": "glob.iglob — per-host directory listing",
    "stat": "os.stat — per-host file metadata",
    "getmtime": "os.path.getmtime — per-host file metadata",
    "getsize": "os.path.getsize — per-host file metadata",
    "read_text": "Path.read_text — per-host file content",
    "read_bytes": "Path.read_bytes — per-host file content",
}

#: Agreement helpers: expressions passing through these are pod-agreed.
#: The heartbeat allgather channel is the transport for all of them.
SANITIZERS: dict[str, str] = {
    "gather_probe": "obs/heartbeat.py — THE pod allgather channel; every "
                    "rank receives every rank's row",
    "process_allgather": "jax.experimental.multihost_utils — the primitive "
                         "under gather_probe",
    "agree_and_emit": "obs/health.py — anomaly agreement over gather_probe",
    "_agreed_step": "io/checkpoint.py — p0 verdict broadcast over the "
                    "heartbeat channel (row 0 IS the verdict)",
    "_agreed_count": "io/checkpoint.py — MAX across ranks; pod-aligned "
                     "attempt counts",
    "_agreed_ok": "io/checkpoint.py — MIN across ranks; one rank's failure "
                  "fails everyone together",
    "_preemption_agreed": "train/trainer.py — preemption verdict agreed "
                          "over process_allgather",
    "sync_global_devices": "jax.experimental.multihost_utils — a named "
                           "barrier every rank must reach",
    "broadcast_one_to_all": "jax.experimental.multihost_utils — p0's value "
                            "to every rank",
    "BatchIterator": "data/batching.py — pod-uniform by construction: the "
                     "epoch schedule derives from global facts (seed, "
                     "dataset length, global batch); process_index only "
                     "selects the local slice, so trip counts agree on "
                     "every rank",
}

#: Collective-implying calls: every rank must reach these together.
SINKS: dict[str, str] = {
    # compiled SPMD program invocations — jax.jit'd multi-host programs
    "train_step": "the compiled train step (train/step.py make_train_step)",
    "prefill": "the compiled prefill program (evaluation/generation.py)",
    "decode_step": "the compiled decode step (evaluation/generation.py)",
    "generate": "the prefill+decode loop (evaluation/generation.py)",
    "_generate": "the prefill+decode loop (evaluation/evaluate.py wrapper)",
    # checkpoint commit/restore — multi-host directory rename + agreement
    "save": "checkpoint save (io/checkpoint.py) — all ranks write, then "
            "agree on the commit",
    "restore_latest": "checkpoint restore (io/checkpoint.py) — all ranks "
                      "read the same agreed step",
    "restore_before": "checkpoint walk-back restore (io/checkpoint.py)",
    "delete_after": "checkpoint GC after walk-back (io/checkpoint.py)",
    "wait_until_finished": "async checkpoint barrier (io/checkpoint.py)",
    # the heartbeat/agreement channel itself IS a collective
    "beat": "obs/heartbeat.py — per-step pod heartbeat allgather",
    "gather_probe": "obs/heartbeat.py — pod allgather channel",
    "process_allgather": "multihost allgather primitive",
    "agree_and_emit": "anomaly agreement ride on gather_probe",
    "_agreed_step": "p0-verdict broadcast (heartbeat channel)",
    "_agreed_count": "MAX agreement (heartbeat channel)",
    "_agreed_ok": "MIN agreement (heartbeat channel)",
    "_preemption_agreed": "preemption agreement (process_allgather)",
    "sync_global_devices": "named multihost barrier",
    "broadcast_one_to_all": "p0 broadcast (multihost_utils)",
    # mesh lifecycle — every rank must (re)bootstrap together
    "build_mesh": "core/mesh.py — device mesh construction",
    "initialize_distributed": "jax.distributed init (core/mesh.py)",
    "reinitialize_distributed": "elastic rebootstrap (core/mesh.py)",
    # global batch assembly — make_array_from_process_local_data is a
    # cross-host rendezvous on the addressable-shard layout
    "put_batch": "train/step.py — global array assembly from local rows",
    "make_array_from_process_local_data": "jax global-array rendezvous",
}

#: Receivers whose methods never imply pod collectives even when the
#: attribute name collides with a sink (``np.save``, ``json.load``...).
_NONPOD_RECEIVERS = frozenset({
    "np", "numpy", "json", "jnp", "os", "io", "pickle", "plt", "math",
    "struct", "shutil", "logging", "re", "random",
})

_PRAGMA_RE = re.compile(r"#\s*pod-agreed:\s*(\S.*)")

_FUNCLIKE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _callee_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _receiver_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return fn.value.id
    return None


def _dotted_name(node: ast.AST) -> str | None:
    """``self.batches`` → "self.batches"; None for non-Name-based chains.
    Taint is tracked on these dotted strings so assigning to one instance
    attribute never taints the whole object."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_no_funcs(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class bodies —
    nested defs are analyzed as their own scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, _FUNCLIKE + (ast.ClassDef,)):
                continue
            stack.append(child)


def pragma_lines(src: str) -> dict[int, str]:
    """Line number → ``# pod-agreed:`` mechanism text."""
    out: dict[int, str] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            out[i] = m.group(1).strip()
    return out


class _Region:
    """One rank-divergent control region: why, where, and which finding
    code a sink inside it produces."""

    __slots__ = ("code", "reason", "line")

    def __init__(self, code: str, reason: str, line: int):
        self.code = code
        self.reason = reason
        self.line = line


class _FunctionPass:
    """Analyze ONE function body (or the module top level).

    Flow-insensitive taint: two convergence sweeps over assignments, then
    a structured walk of the statements tracking divergent regions and
    divergent early exits.  Nested function bodies are skipped — the
    driver analyzes them as their own scopes (closure taint is out of
    scope for this pass; rank-divergent closures have no instance in the
    tree and would taint through SOURCES locally anyway)."""

    def __init__(self, rel: str, pragmas: dict[int, str], qualname: str):
        self.rel = rel
        self.pragmas = pragmas
        self.qualname = qualname
        self.tainted: dict[str, str] = {}  # name → why it is rank-local
        self.findings: list[Finding] = []

    # -- taint over expressions ------------------------------------------

    def _expr_sanitized(self, expr: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Call) and _callee_name(n) in SANITIZERS
            for n in _walk_no_funcs(expr)
        )

    def _expr_taint(self, expr: ast.AST) -> str | None:
        """Why this expression is rank-local, or None.  An expression that
        routes through a sanitizer call is pod-agreed regardless of what
        feeds it — that is the whole point of the sanitizers."""
        if self._expr_sanitized(expr):
            return None
        for n in _walk_no_funcs(expr):
            if isinstance(n, ast.Name) and n.id in self.tainted:
                return self.tainted[n.id]
            if isinstance(n, ast.Attribute):
                dotted = _dotted_name(n)
                if dotted is not None and dotted in self.tainted:
                    return self.tainted[dotted]
            if isinstance(n, ast.Call):
                name = _callee_name(n)
                if name == "process_index":
                    return "jax.process_index()"
                if (
                    name in SOURCES
                    and name != "process_index"
                    and _receiver_name(n) not in ("json", "pickle", "struct")
                ):
                    return f"local file I/O ({name})"
        return None

    def _assign_names(self, target: ast.AST) -> list[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, ast.Attribute):
            dotted = _dotted_name(target)
            return [dotted] if dotted is not None else []
        if isinstance(target, (ast.Tuple, ast.List)):
            names: list[str] = []
            for elt in target.elts:
                names += self._assign_names(elt)
            return names
        if isinstance(target, ast.Starred):
            return self._assign_names(target.value)
        return []

    def _sweep_taint(self, body: list[ast.stmt]) -> None:
        """Two passes so taint assigned below a use still propagates.

        Tracks IMPLICIT flow as well as data flow: a name assigned under
        a rank-divergent branch (or inside an except handler — the
        per-rank exception path) holds a rank-dependent value even when
        the right-hand side is itself pod-uniform.  This is exactly the
        p0-only-verdict bug shape: ``ok`` computed only where
        ``process_index() == 0`` differs per rank until broadcast."""
        for _ in range(2):
            self._sweep(body, None)

    def _sweep(self, stmts: list[ast.stmt], ctx: str | None) -> None:
        for stmt in stmts:
            if isinstance(stmt, _FUNCLIKE + (ast.ClassDef,)):
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                inner = ctx
                why = self._expr_taint(stmt.test)
                if why is not None and not self._waived(stmt.lineno):
                    inner = ctx or (
                        f"assigned under a rank-divergent branch "
                        f"(line {stmt.lineno}: {why})"
                    )
                self._sweep(stmt.body, inner)
                self._sweep(stmt.orelse, inner)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                inner = ctx
                why = self._expr_taint(stmt.iter)
                if why is not None and not self._waived(stmt.lineno):
                    for name in self._assign_names(stmt.target):
                        self.tainted.setdefault(name, why)
                    inner = ctx or (
                        f"assigned under a rank-divergent loop "
                        f"(line {stmt.lineno}: {why})"
                    )
                self._sweep(stmt.body, inner)
                self._sweep(stmt.orelse, inner)
                continue
            if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                self._sweep(stmt.body, ctx)
                for handler in stmt.handlers:
                    if handler.name:
                        self.tainted.setdefault(
                            handler.name,
                            f"per-rank exception binding {handler.name!r}",
                        )
                    inner = ctx
                    if not self._waived(handler.lineno):
                        inner = ctx or (
                            "assigned inside an `except` handler (line "
                            f"{handler.lineno}) — the per-rank exception "
                            "path"
                        )
                    self._sweep(handler.body, inner)
                self._sweep(stmt.orelse, ctx)
                self._sweep(stmt.finalbody, ctx)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        why = self._expr_taint(item.context_expr)
                        if why is not None:
                            for name in self._assign_names(item.optional_vars):
                                self.tainted.setdefault(name, why)
                self._sweep(stmt.body, ctx)
                continue
            for node in _walk_no_funcs(stmt):
                value = targets = None
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    value, targets = node.value, [node.target]
                elif isinstance(node, ast.AugAssign):
                    value, targets = node.value, [node.target]
                elif isinstance(node, ast.NamedExpr):
                    value, targets = node.value, [node.target]
                if value is None:
                    continue
                names = []
                for t in targets:
                    names += self._assign_names(t)
                if self._expr_sanitized(value):
                    for name in names:
                        self.tainted.pop(name, None)
                    continue
                why = self._expr_taint(value) or ctx
                if why is not None:
                    for name in names:
                        self.tainted.setdefault(name, why)

    # -- the structured walk ---------------------------------------------

    def _waived(self, *lines: int) -> bool:
        return any(ln in self.pragmas for ln in lines)

    def _sink_calls(self, stmt: ast.stmt) -> list[tuple[str, int]]:
        out = []
        for n in _walk_no_funcs(stmt):
            if isinstance(n, ast.Call):
                name = _callee_name(n)
                if name in SINKS and _receiver_name(n) not in _NONPOD_RECEIVERS:
                    out.append((name, n.lineno))
        return out

    def _has_early_exit(self, body: list[ast.stmt]) -> int | None:
        """Line of a statement that escapes ``body`` early — return/raise
        anywhere (nested functions excluded), break/continue only when NOT
        swallowed by a loop inside the body itself."""

        def scan(stmts: list[ast.stmt], in_loop: bool) -> int | None:
            for stmt in stmts:
                if isinstance(stmt, (ast.Return, ast.Raise)):
                    return stmt.lineno
                if isinstance(stmt, (ast.Break, ast.Continue)) and not in_loop:
                    return stmt.lineno
                if isinstance(stmt, _FUNCLIKE + (ast.ClassDef,)):
                    continue
                enters_loop = isinstance(stmt, (ast.For, ast.AsyncFor, ast.While))
                for field in ("body", "orelse", "finalbody"):
                    hit = scan(getattr(stmt, field, []) or [],
                               in_loop or enters_loop)
                    if hit is not None:
                        return hit
                for handler in getattr(stmt, "handlers", []) or []:
                    hit = scan(handler.body, in_loop)
                    if hit is not None:
                        return hit
            return None

        return scan(body, False)

    def _report(self, code: str, sink: str, line: int, region: _Region) -> None:
        self.findings.append(Finding(
            severity="error",
            pass_name="divergence",
            code=code,
            message=(
                f"{self.rel}:{line}: collective-implying call `{sink}` "
                f"({SINKS[sink]}) on a rank-divergent path — "
                f"{region.reason} (branch at line {region.line}, in "
                f"{self.qualname}); every process must reach a collective "
                "together or the pod deadlocks.  Route the decision "
                "through an agreement sanitizer (see "
                "analysis/divergence.py SANITIZERS) or annotate the line "
                "`# pod-agreed: <mechanism>`."
            ),
            context={
                "file": self.rel, "line": line, "sink": sink,
                "divergent_line": region.line, "function": self.qualname,
            },
        ))

    def run(self, body: list[ast.stmt]) -> list[Finding]:
        self._sweep_taint(body)
        self._visit_body(body, None)
        return self.findings

    def _visit_body(self, body: list[ast.stmt], region: _Region | None) -> None:
        exited: _Region | None = None
        for stmt in body:
            if exited is not None and not self._waived(stmt.lineno):
                for sink, line in self._sink_calls(stmt):
                    if not self._waived(line):
                        self._report(
                            "rank-divergent-early-exit", sink, line, exited,
                        )
            self._visit_stmt(stmt, region)
            exited = exited or self._early_exit_region(stmt)

    def _early_exit_region(self, stmt: ast.stmt) -> _Region | None:
        """A tainted `if` whose body exits early splits the ranks: the
        survivors run everything after it, the exiting ranks don't."""
        if not isinstance(stmt, ast.If):
            return None
        why = self._expr_taint(stmt.test)
        if why is None or self._waived(stmt.lineno):
            return None
        exit_line = self._has_early_exit(stmt.body)
        if exit_line is None and stmt.orelse:
            exit_line = self._has_early_exit(stmt.orelse)
        if exit_line is None:
            return None
        return _Region(
            "rank-divergent-early-exit",
            f"ranks where `{ast.unparse(stmt.test)}` holds exit early "
            f"(line {exit_line}) on a rank-local condition ({why}) while "
            "the rest continue",
            stmt.lineno,
        )

    def _visit_stmt(self, stmt: ast.stmt, region: _Region | None) -> None:
        if isinstance(stmt, _FUNCLIKE + (ast.ClassDef,)):
            return  # own scope, analyzed separately
        if isinstance(stmt, ast.If):
            why = self._expr_taint(stmt.test)
            inner = region
            if why is not None and not self._waived(stmt.lineno):
                inner = region or _Region(
                    "rank-divergent-collective",
                    f"branch condition `{ast.unparse(stmt.test)}` is "
                    f"rank-local ({why})",
                    stmt.lineno,
                )
            self._visit_body(stmt.body, inner)
            self._visit_body(stmt.orelse, inner)
            return
        if isinstance(stmt, ast.While):
            why = self._expr_taint(stmt.test)
            inner = region
            if why is not None and not self._waived(stmt.lineno):
                inner = region or _Region(
                    "rank-divergent-loop",
                    f"loop condition `{ast.unparse(stmt.test)}` is "
                    f"rank-local ({why}) — ranks run different trip counts",
                    stmt.lineno,
                )
            self._visit_body(stmt.body, inner)
            self._visit_body(stmt.orelse, inner)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            why = self._expr_taint(stmt.iter)
            inner = region
            if why is not None and not self._waived(stmt.lineno):
                inner = region or _Region(
                    "rank-divergent-loop",
                    f"loop iterates over `{ast.unparse(stmt.iter)}`, which "
                    f"is rank-local ({why}) — ranks run different trip "
                    "counts",
                    stmt.lineno,
                )
            self._visit_body(stmt.body, inner)
            self._visit_body(stmt.orelse, inner)
            return
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            self._visit_body(stmt.body, region)
            for handler in stmt.handlers:
                inner = region
                if not self._waived(handler.lineno):
                    inner = region or _Region(
                        "rank-divergent-collective",
                        "inside an `except` handler — an exception exists "
                        "only on the ranks that threw, so this path runs "
                        "on a strict subset of the pod (capture the error "
                        "and agree on it after the try/except instead)",
                        handler.lineno,
                    )
                self._visit_body(handler.body, inner)
            self._visit_body(stmt.orelse, region)
            self._visit_body(stmt.finalbody, region)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._visit_body(stmt.body, region)
            return
        # leaf statement: report sinks when we are inside a divergent region
        if region is not None and not self._waived(stmt.lineno):
            for sink, line in self._sink_calls(stmt):
                if not self._waived(line):
                    self._report(region.code, sink, line, region)


def _functions(tree: ast.Module) -> Iterable[tuple[str, list[ast.stmt]]]:
    """Every analyzable scope in the module: the top level plus each
    (possibly nested) function body, with a readable qualname."""
    yield "<module>", tree.body

    def rec(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child.body
                yield from rec(child, f"{q}.")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
            else:
                yield from rec(child, prefix)

    yield from rec(tree, "")


def _may_diverge(body: list[ast.stmt]) -> bool:
    """One cheap pre-walk: a scope with no rank-local SOURCE call and no
    ``try`` (the per-rank exception path) can produce no taint, hence no
    divergent region, hence no finding — skip the full pass.  Nested
    function bodies are excluded exactly as the pass excludes them."""
    for stmt in body:
        for n in _walk_no_funcs(stmt):
            if isinstance(n, (ast.Try, getattr(ast, "TryStar", ast.Try))):
                return True
            if isinstance(n, ast.Call) and _callee_name(n) in SOURCES:
                return True
    return False


def analyze_source(src: str, rel: str) -> list[Finding]:
    """Run the divergence pass over one file's source text."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(
            "warning", "divergence", "unparseable",
            f"{rel}: not analyzable: {e}",
            context={"file": rel},
        )]
    pragmas = pragma_lines(src)
    findings: list[Finding] = []
    for qualname, body in _functions(tree):
        if _may_diverge(body):
            findings += _FunctionPass(rel, pragmas, qualname).run(body)
    findings.sort(key=lambda f: f.context.get("line", 0))
    return findings


def analyze_file(path: str, rel: str | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return analyze_source(src, rel or path)


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_TREE_CACHE: dict[str, tuple[tuple[Finding, ...], int]] = {}


def analyze_tree(root: str | None = None) -> tuple[list[Finding], int]:
    """The whole-package pass: (findings, files_scanned).  ``root``
    defaults to the installed ``distributed_llms_example_tpu`` package.
    Results are cached per root: the startup lint runs once per trainer
    AND once per serve engine in the same process, over a tree that
    cannot change under a running process."""
    root = os.path.abspath(root or package_root())
    if root in _TREE_CACHE:
        cached, scanned = _TREE_CACHE[root]
        return list(cached), scanned
    base = os.path.dirname(root)
    findings: list[Finding] = []
    scanned = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            findings += analyze_file(path, os.path.relpath(path, base))
            scanned += 1
    _TREE_CACHE[root] = (tuple(findings), scanned)
    return findings, scanned


def main(argv: list[str] | None = None) -> int:
    """Standalone entry point (the lint driver is the normal surface)."""
    import argparse

    from distributed_llms_example_tpu.analysis.findings import (
        count_by_severity, emit,
    )

    p = argparse.ArgumentParser(
        prog="dllm-divergence",
        description="SPMD divergence lint (Layer 1 of the pod-agreement "
                    "static analysis)",
    )
    p.add_argument("--root", default="", help="tree to scan (default: the "
                   "distributed_llms_example_tpu package)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    findings, scanned = analyze_tree(args.root or None)
    emit(findings, as_json=args.json)
    counts = count_by_severity(findings)
    print(
        f"divergence: {scanned} file(s), {counts['error']} error(s), "
        f"{counts['warning']} warning(s)"
    )
    return 1 if counts["error"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
