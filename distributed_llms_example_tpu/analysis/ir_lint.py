"""Pass 2 — lint the COMPILED train-step program for sharding smells.

The spec lint (pass 1) checks what the operator *declared*; this pass
checks what the compiler actually *built*.  The train step is lowered and
compiled ahead-of-time from abstract ShapeDtypeStruct arguments — no
weights are ever materialized (the same AOT plumbing as
utils/memory_audit.py) — and the post-optimization HLO text is scanned:

- ``full-param-all-gather``: an all-gather materializing ≥ threshold bytes
  on a mesh with NO model-sharding axes (pure data parallel keeps params
  replicated — any big gather is GSPMD resharding churn; error), or a
  gather ≥ 2× the largest single parameter on an fsdp mesh (the prefetch
  path gathers one param at a time; a mega-gather means XLA fused a
  whole-tree gather and the memory cliff is back; warning).
- ``bf16-matmul-promoted-to-f32``: a ``convert`` promoting a bf16 value to
  f32 that then feeds a ``dot`` — the hot-path precision-policy violation
  (core/precision.py supplies the (from, to) pair, so the pattern follows
  the ACTIVE policy).  fp32 *accumulation* of a bf16 dot is fine and not
  matched.
- ``degenerate-collective``: a collective whose replica groups are all
  singletons (or a self-loop collective-permute) — traffic over an axis
  the config says is size 1; usually a spec naming an axis the mesh
  doesn't actually split.
- ``host-transfer-in-step``: infeed/outfeed, ``is_host_transfer=true``
  send/recv/copy, or host-offloading custom-calls inside the step body —
  a host round-trip per step serializes async dispatch (error; the
  compiled-IR twin of scripts/repo_lint.py rule 4).

The text scanner is pure (string in, findings out) so tests can seed
violations deterministically; the compile driver wraps it.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Iterable, Mapping

from distributed_llms_example_tpu.analysis.findings import Finding

# HLO element-type byte widths (only what transformer programs produce).
_ITEMSIZE = {
    "f64": 8, "s64": 8, "u64": 8,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

# `  %name = f32[8,128]{1,0} opcode(...operands...)` — also matches
# layout-less and scalar forms; ROOT prefix optional.
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<dtype>[a-z]\w*)\[(?P<dims>[0-9,]*)\]\S*\s+"
    r"(?P<op>[\w\-]+)\("
)
# Async collective forms define a TUPLE: `%ags = (bf16[..], bf16[..])
# all-gather-start(...)` — the shape regex above cannot parse the leading
# paren, so tuple defs get their own pattern; the per-element shapes are
# re-parsed with _TUPLE_ELEM_RE (max element ≈ the gathered result size).
_TUPLE_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"\((?P<elems>[^)]*)\)\s+"
    r"(?P<op>[\w\-]+)\("
)
_TUPLE_ELEM_RE = re.compile(r"([a-z]\w*)\[([0-9,]*)\]")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
# jax stamps every lowered instruction with the originating scope path:
# metadata={op_name="jit(f)/jit(main)/Model/encoder/block_0/self_attn/..."}
_OP_NAME_RE = re.compile(r'op_name="(?P<op_name>[^"]*)"')
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\s*\{[^}]*\})*)\}")
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=\{(\{[^}]*\}(?:,\s*\{[^}]*\})*)\}")

_COLLECTIVE_OPS = (
    "all-gather", "all-gather-start", "all-reduce", "all-reduce-start",
    "reduce-scatter", "all-to-all", "collective-permute",
    "collective-permute-start",
)

# Ops that ALWAYS mean host traffic; send/recv/copy additionally carry an
# ``is_host_transfer=true`` attribute when they cross to the host (plain
# send/recv pairs can be legitimate device-to-device channel traffic on
# some backends, so only the attributed forms are flagged).
_HOST_TRANSFER_OPS = ("infeed", "outfeed")
_HOST_ATTRIBUTED_OPS = ("send", "send-done", "recv", "recv-done",
                        "copy-start", "copy-done")
# GSPMD/XLA host-offloading custom-call targets
_HOST_CUSTOM_CALLS = ("MoveToHost", "MoveToDevice", "PinToHost",
                      "annotate_device_placement")


def _bytes_of(dtype: str, dims: str) -> int:
    shape = [int(d) for d in dims.split(",") if d]
    return int(math.prod(shape)) * _ITEMSIZE.get(dtype, 4)


def _elems_of(dims: str) -> int:
    return int(math.prod([int(d) for d in dims.split(",") if d]))


@dataclasses.dataclass(frozen=True)
class HloInstr:
    """One parsed HLO instruction definition (post-optimization text).

    For tuple-shaped defs (async collective ``-start`` forms) ``bytes``/
    ``elems``/``dtype``/``dims`` describe the LARGEST tuple element — for
    an all-gather-start that is the gathered result, the size that
    matters for traffic and memory accounting alike.
    """

    name: str
    dtype: str
    dims: str
    op: str
    bytes: int
    elems: int
    operands: tuple[str, ...]
    line: str
    op_name: str = ""  # metadata scope path ("" when the text carries none)


def parse_hlo_instructions(hlo_text: str) -> dict[str, HloInstr]:
    """Instruction-name → parsed def, for every definition in the text.

    THE one HLO text parser: the lint passes below, the obs collective
    -traffic account (obs/gauges.py) and the device-time attribution
    index (obs/devprof.py via ``op_bucket_index``) all consume it, so
    their byte/bucket arithmetic cannot drift."""
    out: dict[str, HloInstr] = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line)
        if m:
            name = m.group("name")
            meta = _OP_NAME_RE.search(line)
            out[name] = HloInstr(
                name=name,
                dtype=m.group("dtype"),
                dims=m.group("dims"),
                op=m.group("op"),
                bytes=_bytes_of(m.group("dtype"), m.group("dims")),
                elems=_elems_of(m.group("dims")),
                operands=tuple(_OPERAND_RE.findall(line[m.end():])),
                line=line,
                op_name=meta.group("op_name") if meta else "",
            )
            continue
        t = _TUPLE_DEF_RE.match(line)
        if t:
            name = t.group("name")
            elems = _TUPLE_ELEM_RE.findall(t.group("elems"))
            if elems:
                dt, dims = max(elems, key=lambda e: _bytes_of(*e))
            else:
                dt, dims = "f32", ""
            meta = _OP_NAME_RE.search(line)
            out[name] = HloInstr(
                name=name,
                dtype=dt,
                dims=dims,
                op=t.group("op"),
                bytes=_bytes_of(dt, dims),
                elems=_elems_of(dims),
                operands=tuple(_OPERAND_RE.findall(line[t.end():])),
                line=line,
                op_name=meta.group("op_name") if meta else "",
            )
    return out


# --------------------------------------------------------------------------
# op_name scope → module bucket (shared with train/step.py's bucket_of_path
# and obs/devprof.py's device-time attribution — ONE name-matching table, so
# the health telemetry's param buckets and the profiler's device buckets can
# never disagree on what "attn" means).
# --------------------------------------------------------------------------

# Ordered: first match wins.  head before embed (an "lm_head" tied to the
# embedding table must not read as embed), embed before attn/mlp.
MODULE_BUCKET_PATTERNS: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("head", ("lm_head", "logits")),
    ("embed", ("embed", "shared", "wte", "wpe")),
    ("attn", ("attn", "attention")),
    ("mlp", ("mlp", "ffn", "feed_forward", "densereludense", "fc1", "fc2")),
)

# scope substrings that mark the optimizer/clip/health tail (optax traces
# carry no flax module scope, so these name fragments are the signal)
_OPTIMIZER_SCOPE_HINTS = (
    "adam", "optax", "optimizer", "opt_state", "fused_optim",
    "apply_updates", "clip_by_global_norm", "weight_decay",
)


def module_bucket_of(scope: str) -> str | None:
    """The coarse model-module bucket a scope/path string names, or None
    when it carries no module signal.  ``train.step.bucket_of_path``
    (param paths, falls back to "mlp" — a param bucket must be total) and
    ``obs/devprof`` (device op_name scopes, falls back to "other") both
    route through this table."""
    p = scope.lower()
    for bucket, needles in MODULE_BUCKET_PATTERNS:
        if any(n in p for n in needles):
            return bucket
    return None


def classify_op_scope(scope: str) -> str | None:
    """Device-account class for one HLO ``op_name`` scope: "optimizer"
    for the clip/AdamW/health tail, else the module bucket, else None
    (loss arithmetic, layout ops, scan plumbing — "other")."""
    p = scope.lower()
    if any(h in p for h in _OPTIMIZER_SCOPE_HINTS):
        return "optimizer"
    return module_bucket_of(p)


def base_collective_op(op: str) -> str | None:
    """"all-reduce-start.1" → "all-reduce"; None for non-collectives.
    Accepts instruction NAMES (trailing ".N" / ".clone" suffixes) as well
    as opcodes — trace events name device ops by instruction name."""
    base = op.split(".", 1)[0]
    for suffix in ("-start", "-done"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
    return base if base in (
        "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
        "collective-permute", "collective-broadcast",
    ) else None


# host↔device transfer opcodes — the "infeed" class of the device account
_INFEED_OPS = ("infeed", "outfeed", "send", "send-done", "recv", "recv-done")


def op_bucket_index(
    hlo: "str | Mapping[str, HloInstr]",
) -> dict[str, str]:
    """Instruction name → device-account bucket, from compiled HLO text
    (or an already-parsed instruction dict — a large model's HLO text is
    tens of MB and callers holding a parse must not pay it twice).

    The join key for backends whose profiler traces name device events by
    HLO *instruction* (CPU thunk runtime: ``args.hlo_op = "fusion.3"``)
    rather than by op_name scope: classify each instruction once —
    collective and infeed by opcode, everything else by its ``op_name``
    scope metadata."""
    instrs = parse_hlo_instructions(hlo) if isinstance(hlo, str) else hlo
    out: dict[str, str] = {}
    for name, instr in instrs.items():
        if base_collective_op(instr.op) is not None:
            out[name] = "collective"
        elif instr.op in _INFEED_OPS:
            out[name] = "infeed"
        else:
            bucket = classify_op_scope(instr.op_name) if instr.op_name else None
            out[name] = bucket or "other"
    return out


def model_tree_element_candidates(
    param_elems: Iterable[int], mesh_size: int
) -> set[int]:
    """Element counts a model-tree (parameter/gradient) tensor can carry
    in the compiled per-device program: each leaf's full count plus every
    even shard of it over a divisor of the mesh size.  Collectives whose
    tensors match one of these counts are gradient/parameter traffic; the
    rest move activations.  Shared by the IR lint census and the obs
    collective-traffic account so both classify identically."""
    divisors = [d for d in range(1, max(1, int(mesh_size)) + 1) if mesh_size % d == 0]
    out: set[int] = set()
    for e in param_elems:
        e = int(e)
        if e <= 0:
            continue
        for d in divisors:
            if e % d == 0:
                out.add(e // d)
    return out


# Wire-byte weights for the census estimate: a ring all-reduce moves ~2x
# its tensor bytes per device (a reduce-scatter phase plus an all-gather
# phase); reduce-scatter / all-gather / all-to-all / permute move ~1x the
# instruction's output bytes.  An ESTIMATE of relative wire cost from the
# instruction census — the device account (obs/devprof.py) measures the
# real thing; this exists so a compression A/B can be judged on bytes
# actually moved rather than on output-buffer sizes (an all-gather's
# output is W x what it moved).
_WIRE_WEIGHT = {"all-reduce": 2.0}


def quantized_gradient_census(
    instrs: Mapping[str, HloInstr],
    param_element_counts: Iterable[int],
    mesh_axes: Mapping[str, int],
) -> dict[str, Any]:
    """Census of GRADIENT-classified collectives split by element width —
    the compiled-program proof of ``--grad-compression int8``: the
    quantized program's gradient reduction rides s8 tensors (the
    quantize-reduce-dequantize wrapper's all-to-all / all-gather legs)
    where the fp32 program rode f32.  Returns per-dtype byte totals, the
    s8 instruction names, and ``gradient_wire_bytes`` (the
    direction-weighted estimate above) — ``tests`` and the obs gate
    compare it between the off and int8 programs (~4x on the replica
    leg).  Classification (element count matches a model-tree leaf or an
    even shard of one) is the SAME candidate set the byte account uses,
    so the two can never disagree."""
    mesh_size = 1
    for v in mesh_axes.values():
        mesh_size *= max(1, int(v))
    candidates = model_tree_element_candidates(param_element_counts, mesh_size)
    by_dtype: dict[str, int] = {}
    wire = 0.0
    s8_names: list[str] = []
    for name, instr in instrs.items():
        if instr.op not in _COLLECTIVE_OPS:
            continue
        touched = {instr.elems} | {
            instrs[o].elems for o in instr.operands if o in instrs
        }
        if not (touched & candidates):
            continue
        by_dtype[instr.dtype] = by_dtype.get(instr.dtype, 0) + instr.bytes
        base = instr.op[: -len("-start")] if instr.op.endswith("-start") else instr.op
        wire += _WIRE_WEIGHT.get(base, 1.0) * instr.bytes
        if instr.dtype == "s8":
            s8_names.append(name)
    return {
        "gradient_bytes_by_dtype": by_dtype,
        "gradient_wire_bytes": int(wire),
        "s8_gradient_collectives": s8_names,
    }


def int8_compression_missing_finding(
    census: Mapping[str, Any], grad_compression: str
) -> Finding | None:
    """Error when a program built with ``--grad-compression int8``
    carries NO s8 gradient collective: the partitioner folded the wire
    back to fp32 (a hoisted reshard, a dropped pin) and the run would
    silently pay uncompressed traffic while stamping itself compressed —
    the lint-time twin of ``scripts/obs_gate.py
    --max-gradient-bytes-per-step``."""
    if grad_compression != "int8":
        return None
    if census.get("s8_gradient_collectives"):
        return None
    return Finding(
        severity="error",
        pass_name="ir",
        code="int8-compression-missing",
        message=(
            "the step was built with --grad-compression int8 but the "
            "compiled program contains no s8 gradient collective — the "
            "partitioner folded the quantized wire back to fp32 (hoisted "
            "reshard or dropped sharding pin); the run would pay full "
            f"fp32 gradient traffic ({census.get('gradient_bytes_by_dtype')})"
        ),
        context=dict(census),
    )


def int8_kv_missing_finding(
    instrs: Mapping[str, HloInstr],
    kv_cache_dtype: str,
    *,
    min_elems: int = 1024,
) -> Finding | None:
    """Error when a decode program built with ``--kv-cache-dtype int8``
    carries NO s8 cache operand: the quantized buffers never reached the
    compiled step (a dropped context, a stale f32 cache tree) and every
    decode pays full f32 HBM traffic while stamping itself int8 — the
    decode-census twin of ``int8-compression-missing`` (PR 12).  The
    predicate is deliberately simple: any s8 instruction at cache scale
    (``min_elems`` keeps a stray byte-wide scalar from vouching for the
    whole cache); a correctly built int8 decode step carries its cache
    parameters, the updated buffers, and their scatter ops all in s8."""
    if kv_cache_dtype != "int8":
        return None
    s8 = [
        name
        for name, instr in instrs.items()
        if instr.dtype == "s8" and instr.elems >= min_elems
    ]
    if s8:
        return None
    return Finding(
        severity="error",
        pass_name="ir",
        code="int8-kv-missing",
        message=(
            "the decode step was built with --kv-cache-dtype int8 but the "
            "compiled program carries no cache-sized s8 operand — the "
            "quantized cache never reached the compiled step (dropped "
            "kv_cache_context, stale f32 cache tree); decode would pay "
            "full f32 cache traffic while stamping itself int8"
        ),
    )


def account_gradient_bytes_by_op(account: Mapping[str, Any]) -> dict[str, int]:
    """Adapter: the obs collective-traffic account (obs/gauges.py
    ``collective_traffic`` — per-op dicts with ``gradient_bytes``) →
    the flat ``{op: gradient_bytes}`` map the reduce-scatter predicate
    consumes, so the SAME predicate runs over the IR census and the
    runtime account."""
    out: dict[str, int] = {}
    for op, slot in account.items():
        if isinstance(slot, Mapping) and "gradient_bytes" in slot:
            out[op] = int(slot["gradient_bytes"])
    return out


def reduce_scatter_smell(
    gradient_bytes_by_op: Mapping[str, int],
    mesh_axes: Mapping[str, Any],
    *,
    ratio: float = 2.0,
    min_bytes: int = 1 << 20,
) -> Finding | None:
    """The ROADMAP reduce-scatter smell as a PURE predicate over a
    gradient-byte account: on an fsdp mesh, gradient bytes riding
    all-reduce ≫ bytes riding reduce-scatter means the partitioner kept
    the gradients replicated through the reduction — the 2× gradient-
    traffic anti-pattern (arxiv 2004.13336).  ``-start`` async forms are
    folded into their base op; ``min_bytes`` keeps toy programs quiet.
    Works identically over the IR census's ``gradient_bytes_by_op`` and
    the obs runtime account (via ``account_gradient_bytes_by_op``)."""
    if int(mesh_axes.get("fsdp", 1) or 1) <= 1:
        return None
    merged: dict[str, int] = {}
    for op, b in gradient_bytes_by_op.items():
        base = op[: -len("-start")] if op.endswith("-start") else op
        merged[base] = merged.get(base, 0) + int(b)
    ar = merged.get("all-reduce", 0)
    rs = merged.get("reduce-scatter", 0)
    if ar < max(int(min_bytes), int(ratio * max(rs, 1))):
        return None
    return Finding(
        severity="warning",
        pass_name="ir",
        code="gradient-all-reduce-not-reduce-scatter",
        message=(
            f"{ar / 1024**2:.1f} MiB of gradient bytes ride all-reduce vs "
            f"{rs / 1024**2:.1f} MiB on reduce-scatter on an fsdp mesh "
            f"(fsdp={mesh_axes.get('fsdp')}) — sharded gradients should "
            "reduce-scatter; an all-reduce keeps them replicated through "
            "the reduction and pays ~2× the gradient traffic"
        ),
        context={
            "all_reduce_gradient_bytes": ar,
            "reduce_scatter_gradient_bytes": rs,
            "ratio_threshold": ratio,
        },
    )


# --------------------------------------------------------------------------
# Computation-level HLO structure (the once-per-step placement pass needs to
# know WHICH loop body an instruction lives in, which the flat parse above
# deliberately ignores).
# --------------------------------------------------------------------------

# `%name (params...) -> result {` — computation header (ENTRY optional).
_COMP_HEAD_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s*\(.*\{\s*$")
# references to other computations from inside an instruction line
_CALLED_RE = re.compile(
    r"(?:to_apply|body|condition|calls|branch_computations)="
    r"(?:\{([^}]*)\}|%?([\w.\-]+))"
)
_WHILE_BODY_RE = re.compile(r"\bwhile\(.*?body=%?([\w.\-]+)")
_SOURCE_LINE_RE = re.compile(r'source_file="(?P<file>[^"]+)"\s+source_line=(?P<line>\d+)')


def split_computations(hlo_text: str) -> dict[str, list[str]]:
    """HLO text → {computation name: its instruction lines}."""
    out: dict[str, list[str]] = {}
    current: list[str] | None = None
    for line in hlo_text.splitlines():
        m = _COMP_HEAD_RE.match(line)
        if m is not None and line.rstrip().endswith("{"):
            current = out.setdefault(m.group("name"), [])
            continue
        if line.strip() == "}":
            current = None
            continue
        if current is not None:
            current.append(line)
    return out


def _called_names(lines: Iterable[str]) -> set[str]:
    names: set[str] = set()
    for line in lines:
        for grouped, single in _CALLED_RE.findall(line):
            if single:
                names.add(single)
            else:
                names.update(n.strip().lstrip("%") for n in grouped.split(",") if n.strip())
    return names


def loop_body_computations(hlo_text: str) -> set[str]:
    """Names of every computation reachable from a ``while`` body — i.e.
    code that executes ONCE PER LOOP ITERATION.  The grad-accumulation
    scan lowers to a while; so do unrelated loops (gather/sort helpers),
    which is fine: the once-per-step contract is that the optimizer tail
    sits inside NO loop at all."""
    comps = split_computations(hlo_text)
    roots: set[str] = set()
    for lines in comps.values():
        for line in lines:
            m = _WHILE_BODY_RE.search(line)
            if m:
                roots.add(m.group(1))
    reachable: set[str] = set()
    frontier = list(roots)
    while frontier:
        name = frontier.pop()
        if name in reachable or name not in comps:
            continue
        reachable.add(name)
        frontier.extend(_called_names(comps[name]))
    return reachable


# Copies below this element count are excluded from the in-place census:
# XLA's layout assignment inserts small transpose-normalization copies
# around fused elementwise ops (observed: 512-element relayouts on tiny
# fallback leaves), which are noise next to the contract's target — a
# param-scale second fp32 buffer.  256 KiB fp32; every 7B-class leaf
# shard sits orders of magnitude above it.
MIN_COPY_CENSUS_ELEMS = 1 << 16


def once_per_step_placement(
    hlo_text: str,
    spans: Iterable[tuple[str, int, int]],
    param_elems: Iterable[int] | None = None,
    *,
    min_copy_elems: int = MIN_COPY_CENSUS_ELEMS,
) -> dict[str, Any]:
    """Census of the optimizer/clip/health block's placement in the
    compiled program, from instruction source metadata.

    ``spans`` is ``train.step.once_per_step_source_spans()`` — the
    ``(file, first_line, last_line)`` ranges of the code that must run
    exactly once per optimizer step.  jax stamps each HLO instruction
    with its originating source line, so counting span-attributed
    instructions inside loop-body computations proves (or refutes) that
    the optimizer apply stayed OUT of the grad-accumulation scan — on
    the real compiled program, regardless of ``grad_accum_steps``.

    ``param_elems`` (full per-leaf element counts of the model's param
    tree) extends the census with the IN-PLACE contract of the fused
    optimizer apply: span-attributed f32 ``copy`` instructions whose
    element count matches a parameter leaf are counted as
    ``fp32_param_copies`` — a fused apply that genuinely updates in
    place (``input_output_aliases``) shows zero; a copy there means the
    compiler materialized a second fp32 param buffer the fusion exists
    to avoid.

    Returns ``{"total": N, "in_loop": M, "in_loop_examples": [...]}``
    (plus ``fp32_param_copies``/``fp32_copy_examples`` when
    ``param_elems`` is given); a healthy step has ``total > 0`` (the
    block exists) and ``in_loop == 0`` (none of it slid into a loop
    body)."""
    span_list = [(str(f), int(a), int(b)) for f, a, b in spans]
    elem_set = {int(e) for e in param_elems} if param_elems is not None else None

    def in_spans(fname: str, line: int) -> bool:
        return any(fname.endswith(f) or f.endswith(fname) or fname == f for f, a, b in span_list if a <= line <= b)

    comps = split_computations(hlo_text)
    loop_comps = loop_body_computations(hlo_text)
    total = 0
    in_loop = 0
    examples: list[str] = []
    copies = 0
    copy_examples: list[str] = []
    for cname, lines in comps.items():
        for line in lines:
            m = _SOURCE_LINE_RE.search(line)
            if not m or not in_spans(m.group("file"), int(m.group("line"))):
                continue
            total += 1
            d = _DEF_RE.match(line)
            if cname in loop_comps:
                in_loop += 1
                if len(examples) < 8:
                    examples.append(
                        f"{cname}:%{d.group('name')}" if d else cname
                    )
            if elem_set is None:
                continue
            # sync `copy` parses via _DEF_RE; the async `copy-start` form
            # defines a TUPLE shape only _TUPLE_DEF_RE can read (largest
            # element = the copied buffer)
            name = op = dtype = None
            elems = 0
            if d is not None:
                name, op = d.group("name"), d.group("op")
                dtype, elems = d.group("dtype"), _elems_of(d.group("dims"))
            else:
                t = _TUPLE_DEF_RE.match(line)
                if t is not None and t.group("op") == "copy-start":
                    name, op = t.group("name"), "copy-start"
                    pairs = _TUPLE_ELEM_RE.findall(t.group("elems"))
                    if pairs:
                        dtype, dims = max(pairs, key=lambda e: _bytes_of(*e))
                        elems = _elems_of(dims)
            if (
                op in ("copy", "copy-start")
                and dtype == "f32"
                and elems >= min_copy_elems
                and elems in elem_set
            ):
                copies += 1
                if len(copy_examples) < 8:
                    copy_examples.append(f"{cname}:%{name}")
    out: dict[str, Any] = {
        "total": total, "in_loop": in_loop, "in_loop_examples": examples,
    }
    if elem_set is not None:
        out["fp32_param_copies"] = copies
        out["fp32_copy_examples"] = copy_examples
    return out


def in_place_apply_finding(
    hlo_text: str,
    spans: Iterable[tuple[str, int, int]],
    param_elems: Iterable[int],
    *,
    min_copy_elems: int = MIN_COPY_CENSUS_ELEMS,
) -> Finding | None:
    """The fused-apply in-place contract as a finding: warning when any
    span-attributed f32 param-sized ``copy`` survived in the compiled
    program — the buffer aliasing the fused kernel declares
    (``input_output_aliases``) should leave none.  ``param_elems`` is
    matched against the PER-DEVICE program's buffer sizes: multi-device
    callers must pass ``model_tree_element_candidates(full_counts,
    mesh_size)`` (as ``lint_train_step`` does), or sharded leaves'
    copies are invisible.  A warning, not an error: XLA legitimately
    inserts copies around donation on some backends, and a copy costs
    bandwidth, not correctness.  Copies under ``min_copy_elems`` are
    ignored — layout-normalization relayouts of small leaves are not the
    bandwidth the contract protects."""
    census = once_per_step_placement(
        hlo_text, spans, param_elems, min_copy_elems=min_copy_elems
    )
    if not census.get("fp32_param_copies"):
        return None
    return Finding(
        severity="warning",
        pass_name="ir",
        code="optimizer-param-copy",
        message=(
            f"{census['fp32_param_copies']} f32 param-sized copy "
            f"instruction(s) in the optimizer-apply span (e.g. "
            f"{census['fp32_copy_examples'][:3]}) — the fused apply "
            "declares in-place aliasing precisely so no second fp32 "
            "param buffer is materialized per step"
        ),
        context=census,
    )


def once_per_step_finding(
    hlo_text: str, spans: Iterable[tuple[str, int, int]]
) -> Finding | None:
    """The placement census as a finding: error when any optimizer-block
    instruction landed inside a loop body (it would re-run every
    microbatch — the non-layer overhead grad accumulation exists to
    amortize), or when NO instruction carries the block's source spans
    (the metadata went missing and the census proves nothing)."""
    census = once_per_step_placement(hlo_text, spans)
    if census["in_loop"]:
        return Finding(
            severity="error",
            pass_name="ir",
            code="optimizer-in-scan-body",
            message=(
                f"{census['in_loop']} optimizer/health instruction(s) were "
                "scheduled inside a loop body (e.g. "
                f"{census['in_loop_examples'][:3]}) — clip/AdamW/health must "
                "run once per optimizer step, after the grad-accumulation "
                "scan, not once per microbatch"
            ),
            context=census,
        )
    if census["total"] == 0:
        return Finding(
            severity="warning",
            pass_name="ir",
            code="optimizer-census-empty",
            message=(
                "no instruction carries the optimizer-apply-block source "
                "spans — source metadata is missing from this HLO text, so "
                "the once-per-step placement cannot be proven"
            ),
            context=census,
        )
    return None


def collective_permute_chain_depth(instrs: Mapping[str, HloInstr]) -> int:
    """Longest dependency chain of collective-permutes in the parsed
    instruction graph: the number of permutes on the longest operand path
    ending at (and including) each permute.  Data moved around a
    pipeline's stage ring needs at most one hop per ring edge; a chain
    longer than the ring means some tensor was permuted around more than
    once — a resharded pipeline hop."""
    permute_ops = ("collective-permute", "collective-permute-start")
    depth: dict[str, int] = {}

    # iterative post-order: a real compiled step's operand chains run far
    # past Python's recursion limit (one frame per instruction would
    # RecursionError on any 7B program), so expand-then-combine on an
    # explicit stack.  ``on_path`` guards cycles (HLO is a DAG, but a
    # malformed text must not hang the lint): a back-edge operand scores 0.
    for root in instrs:
        if root in depth:
            continue
        stack: list[tuple[str, bool]] = [(root, False)]
        on_path: set[str] = set()
        while stack:
            name, expanded = stack.pop()
            if expanded:
                on_path.discard(name)
                instr = instrs[name]
                child = max((depth.get(o, 0) for o in instr.operands), default=0)
                depth[name] = child + (1 if instr.op in permute_ops else 0)
                continue
            if name in depth or name not in instrs or name in on_path:
                continue
            on_path.add(name)
            stack.append((name, True))
            for o in instrs[name].operands:
                if o not in depth and o in instrs and o not in on_path:
                    stack.append((o, False))
    return max(depth.values(), default=0)


def ppermute_chain_smell(
    instrs: Mapping[str, HloInstr], mesh_axes: Mapping[str, int]
) -> Finding | None:
    """The ROADMAP smell: a collective-permute chain longer than the
    stage ring.  A pipeline with S stages moves activations/gradients at
    most S hops around the ring per pass; a longer chain means a tensor
    was resharded through extra permute hops (usually a spec mismatch
    between stages making GSPMD route data the long way around).

    Gated to meshes where the stage ring is the ONLY permute ring: with
    sequence/context parallelism in play, ring attention and halo
    exchanges legitimately chain one permute per layer (depth ≫ stage)
    and HLO text does not say which axis a permute's pairs ride — the
    stage-ring bound would fire on every deep network."""
    stage = int(mesh_axes.get("stage", 1) or 1)
    if stage <= 1:
        return None
    if int(mesh_axes.get("sequence", 1) or 1) > 1:
        return None
    if not any(
        i.op in ("collective-permute", "collective-permute-start")
        for i in instrs.values()
    ):
        return None
    longest = collective_permute_chain_depth(instrs)
    if longest <= stage:
        return None
    return Finding(
        severity="warning",
        pass_name="ir",
        code="ppermute-chain-exceeds-stage-ring",
        message=(
            f"a collective-permute dependency chain of length {longest} "
            f"exceeds the stage ring (stage={stage}) — data is being "
            "permuted around the pipeline more than one full pass, i.e. a "
            "resharded pipeline hop (a spec mismatch between stages makes "
            "GSPMD route tensors the long way around the ring)"
        ),
        context={"chain_length": longest, "stage": stage},
    )


def prefill_in_decode_smell(
    instrs: Mapping[str, HloInstr],
    *,
    enc_len: int,
    batch: int,
    heads: int,
    q_len: int = 1,
    margin: float = 2.0,
) -> Finding | None:
    """The serving twin of the once-per-step census: error when the
    compiled DECODE-STEP program contains encoder/prefill-sized matmuls.

    Contract: prefill runs the encoder and projects cross-attention K/V
    exactly ONCE per sequence (``cross_kv``-computed-once); the per-token
    decode step may only read them.  The largest legitimate tensor with an
    ``enc_len`` dimension a decode step PRODUCES in a dot is the
    cross-attention score block — ``batch·heads·q_len·enc_len`` elements.
    A re-projected cross K/V is ``head_dim/q_len`` times that; a re-run
    encoder matmul (d_model/d_ff wide) is orders of magnitude past it.  So
    the predicate is: any ``dot`` whose output shape carries a dim equal
    to ``enc_len`` AND whose element count exceeds ``margin ×`` the score
    bound.  ``enc_len`` is the encoder length (seq2seq) or the cache/mask
    width (causal — a re-run prompt pass shows the same signature).  Pure
    over parsed instructions; ``lint_decode_step`` wires it to the real
    AOT-compiled step."""
    bound = margin * batch * heads * max(q_len, 1) * enc_len
    offenders: list[str] = []
    for name, instr in instrs.items():
        if instr.op != "dot":
            continue
        dims = [int(d) for d in instr.dims.split(",") if d]
        if enc_len in dims and instr.elems > bound:
            offenders.append(name)
    if not offenders:
        return None
    worst = max(offenders, key=lambda n: instrs[n].elems)
    return Finding(
        severity="error",
        pass_name="ir",
        code="prefill-in-decode",
        message=(
            f"{len(offenders)} dot(s) in the compiled decode step produce "
            f"prefill-sized tensors (an {enc_len}-long dim at "
            f"{instrs[worst].elems} elements, e.g. %{worst}) — the decode "
            "step is re-running encoder/prefill compute or re-projecting "
            "cross-attention K/V every token; prefill computes those ONCE "
            "per sequence (the cross_kv contract)"
        ),
        context={
            "count": len(offenders),
            "instructions": offenders[:8],
            "bound_elems": int(bound),
        },
    )


def host_transfer_instructions(instrs: Mapping[str, HloInstr]) -> list[str]:
    """Names of instructions that move data between host and device —
    the ROADMAP "host-transfer ops inside the step body" smell.  Pure
    predicate over parsed instructions (shared by the IR pass and tests):
    infeed/outfeed always; send/recv/copy only when the instruction is
    attributed ``is_host_transfer=true``; host-offloading custom-calls
    (MoveToHost / MoveToDevice / annotate_device_placement)."""
    out: list[str] = []
    for name, instr in instrs.items():
        if instr.op in _HOST_TRANSFER_OPS:
            out.append(name)
        elif instr.op in _HOST_ATTRIBUTED_OPS and "is_host_transfer=true" in instr.line:
            out.append(name)
        elif instr.op == "custom-call" and any(
            t in instr.line for t in _HOST_CUSTOM_CALLS
        ):
            out.append(name)
    return out


def scan_hlo_text(
    hlo_text: str,
    *,
    mesh_axes: Mapping[str, int],
    promotion_smell: tuple[str, str] | None = None,
    largest_param_bytes: int = 0,
    gather_bytes_threshold: int = 16 * 1024**2,
    param_element_counts: Iterable[int] | None = None,
    decode_contract: Mapping[str, int] | None = None,
    grad_compression: str = "",
    kv_cache_dtype: str = "",
) -> list[Finding]:
    """Scan post-optimization HLO text.  Pure function of the text.

    ``param_element_counts`` (full per-leaf element counts of the model's
    parameter tree) additionally splits the collective census byte totals
    into gradient/parameter vs activation traffic.

    ``decode_contract`` marks the text as a SERVING decode step and runs
    ``prefill_in_decode_smell`` over it; keys: ``enc_len``, ``batch``,
    ``heads``, optional ``q_len``/``margin``.  ``kv_cache_dtype``
    ("int8") additionally asserts the program carries s8 cache operands
    (``int8_kv_missing_finding``)."""
    findings: list[Finding] = []
    instrs = parse_hlo_instructions(hlo_text)
    defs = {n: (i.dtype, i.dims, i.op) for n, i in instrs.items()}
    sizes = {n: i.bytes for n, i in instrs.items()}
    operands = {n: list(i.operands) for n, i in instrs.items()}
    lines = hlo_text.splitlines()

    model_sharded = any(
        mesh_axes.get(a, 1) > 1 for a in ("fsdp", "tensor", "expert", "stage")
    )

    # ---- all-gather size accounting ------------------------------------
    gathers = [
        (name, sizes[name])
        for name, (_, _, op) in defs.items()
        if op in ("all-gather", "all-gather-start")
    ]
    big = [(n, b) for n, b in gathers if b >= gather_bytes_threshold]
    if big and not model_sharded:
        worst = max(big, key=lambda t: t[1])
        findings.append(Finding(
            severity="error",
            pass_name="ir",
            code="full-param-all-gather",
            message=(
                f"{len(big)} all-gather(s) materialize ≥ "
                f"{gather_bytes_threshold / 1024**2:.0f} MiB (largest "
                f"{worst[1] / 1024**2:.1f} MiB at %{worst[0]}) on a mesh with "
                "no model-sharding axes — params should already be "
                "replicated; this is GSPMD resharding churn from a spec "
                "mismatch"
            ),
            context={"count": len(big), "max_bytes": worst[1]},
        ))
    elif largest_param_bytes and gathers:
        mega = [(n, b) for n, b in gathers if b > 2 * largest_param_bytes]
        if mega:
            worst = max(mega, key=lambda t: t[1])
            findings.append(Finding(
                severity="warning",
                pass_name="ir",
                code="fused-mega-all-gather",
                message=(
                    f"an all-gather materializes {worst[1] / 1024**2:.1f} MiB "
                    f"(> 2× the largest single parameter, "
                    f"{largest_param_bytes / 1024**2:.1f} MiB) at %{worst[0]} "
                    "— the fsdp prefetch path gathers one param at a time; a "
                    "fused whole-tree gather brings the replicated-memory "
                    "cliff back"
                ),
                context={"count": len(mega), "max_bytes": worst[1]},
            ))

    # ---- precision policy: convert(from→to) feeding a dot --------------
    if promotion_smell is not None:
        src_dt, dst_dt = promotion_smell
        promoted = {
            name
            for name, (dt, _, op) in defs.items()
            if op == "convert"
            and dt == dst_dt
            and any(defs.get(o, ("",))[0] == src_dt for o in operands[name])
        }
        bad_dots = [
            name
            for name, (_, _, op) in defs.items()
            if op == "dot" and any(o in promoted for o in operands[name])
        ]
        if bad_dots:
            findings.append(Finding(
                severity="warning",
                pass_name="ir",
                code="matmul-precision-promotion",
                message=(
                    f"{len(bad_dots)} dot(s) consume operands promoted "
                    f"{src_dt}→{dst_dt} (e.g. %{bad_dots[0]}) — hot-path "
                    f"matmuls should run in {src_dt} per the precision "
                    f"policy; {dst_dt} is for reductions"
                ),
                context={"count": len(bad_dots), "instructions": bad_dots[:8]},
            ))

    # ---- host transfers inside the step body ---------------------------
    host_xfers = host_transfer_instructions(instrs)
    if host_xfers:
        findings.append(Finding(
            severity="error",
            pass_name="ir",
            code="host-transfer-in-step",
            message=(
                f"{len(host_xfers)} host-transfer op(s) inside the compiled "
                f"train step (e.g. %{host_xfers[0]}) — a host round-trip on "
                "the step body serializes async dispatch every single step; "
                "device→host conversions belong at the log cadence "
                "(the invariant scripts/repo_lint.py rule 4 guards on the "
                "Python side)"
            ),
            context={"count": len(host_xfers), "instructions": host_xfers[:8]},
        ))

    # ---- prefill-sized compute inside a decode step --------------------
    if decode_contract is not None:
        smell = prefill_in_decode_smell(instrs, **decode_contract)
        if smell is not None:
            findings.append(smell)

    # ---- int8 KV cache actually present in the decode step -------------
    kv_missing = int8_kv_missing_finding(instrs, kv_cache_dtype)
    if kv_missing is not None:
        findings.append(kv_missing)

    # ---- collective-permute chains vs the stage ring -------------------
    chain = ppermute_chain_smell(instrs, mesh_axes)
    if chain is not None:
        findings.append(chain)

    # ---- degenerate collectives ----------------------------------------
    degenerate: list[str] = []
    for line in lines:
        m = _DEF_RE.match(line) or _TUPLE_DEF_RE.match(line)
        if not m or m.group("op") not in _COLLECTIVE_OPS:
            continue
        rg = _REPLICA_GROUPS_RE.search(line)
        if rg:
            groups = re.findall(r"\{([^}]*)\}", rg.group(1) if "{" in rg.group(1) else rg.group(0))
            if groups and all(len([x for x in g.split(",") if x.strip()]) <= 1 for g in groups):
                degenerate.append(m.group("name"))
                continue
        st = _SOURCE_TARGET_RE.search(line)
        if st:
            pairs = re.findall(r"\{(\d+),\s*(\d+)\}", st.group(0))
            if pairs and all(a == b for a, b in pairs):
                degenerate.append(m.group("name"))
    if degenerate:
        findings.append(Finding(
            severity="warning",
            pass_name="ir",
            code="degenerate-collective",
            message=(
                f"{len(degenerate)} collective(s) have singleton replica "
                f"groups / self-loop permutes (e.g. %{degenerate[0]}) — "
                "communication over an axis of size 1; usually a spec names "
                "an axis the mesh does not actually split"
            ),
            context={"count": len(degenerate), "instructions": degenerate[:8]},
        ))

    # ---- census ---------------------------------------------------------
    census: dict[str, int] = {}
    bytes_by_op: dict[str, int] = {}
    for name, (_, _, op) in defs.items():
        if op in _COLLECTIVE_OPS:
            census[op] = census.get(op, 0) + 1
            bytes_by_op[op] = bytes_by_op.get(op, 0) + sizes[name]
    context: dict[str, Any] = {"census": census, "bytes_by_op": bytes_by_op}
    if param_element_counts is not None:
        mesh_size = 1
        for v in mesh_axes.values():
            mesh_size *= max(1, int(v))
        candidates = model_tree_element_candidates(param_element_counts, mesh_size)
        grad_bytes: dict[str, int] = {}
        for name, instr in instrs.items():
            if instr.op not in _COLLECTIVE_OPS:
                continue
            touched = {instr.elems} | {
                instrs[o].elems for o in instr.operands if o in instrs
            }
            if touched & candidates:
                grad_bytes[instr.op] = grad_bytes.get(instr.op, 0) + instr.bytes
        context["gradient_bytes_by_op"] = grad_bytes
        # element-width split of the same classification (the int8
        # compression proof) + the direction-weighted wire estimate
        quant_census = quantized_gradient_census(
            instrs, param_element_counts, mesh_axes
        )
        context.update(quant_census)
        missing = int8_compression_missing_finding(quant_census, grad_compression)
        if missing is not None:
            findings.append(missing)
        smell = reduce_scatter_smell(grad_bytes, mesh_axes)
        if smell is not None:
            findings.append(smell)
    findings.append(Finding(
        severity="info",
        pass_name="ir",
        code="collective-census",
        message=(
            "collectives in the compiled step: "
            + (", ".join(f"{k}×{v}" for k, v in sorted(census.items())) or "none")
        ),
        context=context,
    ))
    return findings


def lint_train_step(
    model_name: str,
    *,
    mesh_config: Any = None,
    global_batch: int = 8,
    src_len: int = 1024,
    tgt_len: int = 128,
    dtype: str = "bfloat16",
    remat: bool = False,
    grad_accum_steps: int = 1,
    optim_impl: str = "",
    grad_compression: str = "",
    gather_bytes_threshold: int = 16 * 1024**2,
    collect: "dict[str, str] | None" = None,
    program: str = "train_step",
) -> list[Finding]:
    """AOT-compile the sharded train step from abstract args and scan it.

    ``collect`` (divergence census mode): the post-optimization HLO text
    is stored under ``collect[program]`` so the cross-program collective
    census reads the SAME compile this pass scanned.

    ``optim_impl`` builds the step with that optimizer apply (e.g.
    ``"fused"`` — the Pallas clip+AdamW path); the fused program is
    additionally checked against the IN-PLACE contract
    (``in_place_apply_finding``: no f32 param-sized copies in the
    apply's source spans).

    Needs a real device mesh (the SPMD partitioner inserts the collectives
    this pass looks for at compile time); callers skip the pass when the
    requested mesh exceeds the attached device count.
    """
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.core.precision import Policy, parse_dtype
    from distributed_llms_example_tpu.utils.memory_audit import (
        aot_compile_train_step,
    )

    mesh = build_mesh(mesh_config or MeshConfig())
    # the ONE abstract-compile recipe, shared with the memory audit so the
    # program linted here is the program audited there
    compiled, lm, a_params, _, _ = aot_compile_train_step(
        model_name, mesh,
        global_batch=global_batch, src_len=src_len, tgt_len=tgt_len,
        dtype=dtype, remat=remat, grad_accum_steps=grad_accum_steps,
        optim_impl=optim_impl, grad_compression=grad_compression,
    )
    text = compiled.as_text()
    if collect is not None:
        collect[program] = text
    leaves = jax.tree.leaves(a_params)
    largest_param = max(
        (int(math.prod(x.shape)) * x.dtype.itemsize for x in leaves),
        default=0,
    )
    policy = Policy(compute_dtype=parse_dtype(dtype))
    findings = scan_hlo_text(
        text,
        mesh_axes=dict(mesh.shape),
        promotion_smell=policy.matmul_promotion_smell(),
        largest_param_bytes=largest_param,
        gather_bytes_threshold=gather_bytes_threshold,
        param_element_counts=[int(math.prod(x.shape)) for x in leaves],
        grad_compression=grad_compression,
    )
    if grad_accum_steps > 1 or optim_impl:
        from distributed_llms_example_tpu.train.step import (
            once_per_step_source_spans,
        )

        spans = once_per_step_source_spans()
        if grad_accum_steps > 1:
            # grad accumulation adds its own compiled-program contract:
            # the clip/AdamW/health tail must sit OUTSIDE the microbatch
            # scan
            placement = once_per_step_finding(text, spans)
            if placement is not None:
                findings.append(placement)
        from distributed_llms_example_tpu.ops.fused_optim import resolve_impl

        if optim_impl and resolve_impl(optim_impl) == "fused":
            # the fused apply's IN-PLACE contract: no f32 param-sized
            # copy instruction in the apply's source spans (the xla path
            # is not held to it — XLA legitimately copies around its
            # unaliased buffers there).  The compiled text is the
            # PER-DEVICE program, so a sharded leaf's buffers carry
            # shard-sized element counts — expand the full counts with
            # the same full-plus-even-shard candidate set the traffic
            # classifier uses, or sharded-leaf copies are invisible on
            # any multi-device mesh
            mesh_size = 1
            for v in dict(mesh.shape).values():
                mesh_size *= max(1, int(v))
            inplace = in_place_apply_finding(
                text, spans,
                model_tree_element_candidates(
                    [int(math.prod(x.shape)) for x in leaves], mesh_size
                ),
            )
            if inplace is not None:
                findings.append(inplace)
    return findings


def decode_heads(config: Any) -> int:
    """Decoder attention head count across the model families' config
    spellings (bart/t5/llama) — the heads term of the decode contract."""
    for attr in ("decoder_attention_heads", "num_heads", "num_attention_heads"):
        n = getattr(config, attr, None)
        if n:
            return int(n)
    return 1


def lint_decode_step(
    model_name: str,
    *,
    mesh_config: Any = None,
    slots: int = 8,
    src_len: int = 64,
    max_new_tokens: int = 16,
    dtype: str = "float32",
    kv_cache_dtype: str = "",
    collect: "dict[str, str] | None" = None,
    program: str = "decode",
    prefill_program: str = "",
) -> list[Finding]:
    """AOT-compile the SERVING decode step (the per-token program of the
    prefill/decode split, evaluation/generation.py) from abstract args and
    scan it: ``prefill_in_decode_smell`` (no encoder recompute, no
    per-step cross-KV re-projection) plus host transfers and the
    collective census.  The prefill carry is ``eval_shape``-derived — no
    weights, same recipe as ``lint_train_step``.  ``src_len`` is the
    admission width, so callers loop it over every ``--prefill-buckets``
    entry to prove each bucket's decode step clean.  ``kv_cache_dtype``
    "int8" builds the step under ``kv_cache_context`` and additionally
    requires s8 cache operands in the compiled text
    (``int8_kv_missing_finding``)."""
    import jax

    from distributed_llms_example_tpu.core.config import MeshConfig
    from distributed_llms_example_tpu.core.mesh import build_mesh
    from distributed_llms_example_tpu.core.precision import parse_dtype
    from distributed_llms_example_tpu.evaluation.generation import (
        CausalGenerator,
        Seq2SeqGenerator,
    )
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.activation import (
        activation_mesh,
        kv_cache_context,
    )

    mesh = build_mesh(mesh_config or MeshConfig())
    lm = load_model(model_name, load_weights=False, dtype=parse_dtype(dtype))
    a_params = jax.eval_shape(lambda: lm.init_params(0))
    cls = Seq2SeqGenerator if lm.is_seq2seq else CausalGenerator
    gen = cls(lm.module, lm.config, max_new_tokens, num_beams=1)
    ids = jax.ShapeDtypeStruct((slots, src_len), jnp_int32())
    mask = jax.ShapeDtypeStruct((slots, src_len), jnp_int32())
    with activation_mesh(mesh), kv_cache_context(kv_cache_dtype or "f32"):
        a_carry = jax.eval_shape(gen.prefill, a_params, ids, mask)
        compiled = jax.jit(gen.decode_step).lower(a_params, a_carry).compile()
        if collect is not None and prefill_program:
            # census mode also wants the PREFILL program's signature —
            # compiled from the same abstract args, the other half of the
            # prefill/decode pair the census cross-checks
            collect[prefill_program] = (
                jax.jit(gen.prefill).lower(a_params, ids, mask)
                .compile().as_text()
            )
    text = compiled.as_text()
    if collect is not None:
        collect[program] = text
    # causal decode attends the full prompt+generation cache width; a
    # re-run prompt pass shows up at the same width
    enc_len = src_len if lm.is_seq2seq else src_len + max_new_tokens
    return scan_hlo_text(
        text,
        mesh_axes=dict(mesh.shape),
        decode_contract={
            "enc_len": enc_len,
            "batch": slots,
            "heads": decode_heads(lm.config),
            "q_len": 1,
        },
        kv_cache_dtype=kv_cache_dtype,
    )


def jnp_int32():
    import jax.numpy as jnp

    return jnp.int32


def skipped(reason: str) -> list[Finding]:
    return [Finding(
        severity="info",
        pass_name="ir",
        code="ir-pass-skipped",
        message=f"lowered-program lint skipped: {reason}",
    )]


# --------------------------------------------------------------------------
# Layer 2 of the pod-agreement static analysis: the cross-program
# collective-matching census.  Every AOT-compiled program in the lint set
# (train step across accum/compression variants, prefill, decode, the
# reshard-restore target) is reduced to its ORDERED collective signature —
# (op kind, replica_groups, channel id, operand bytes) in program text
# order — and the census errors on nondeterministic ordering (two compiles
# of the same program disagree) or on paired programs whose worker-group
# factorizations are incompatible (e.g. expert all-to-all groups vs
# --grad-compression worker groups slicing the same devices differently).
# Layer 1 — the host-AST divergence lint — lives in analysis/divergence.py.
# --------------------------------------------------------------------------

_CHANNEL_ID_RE = re.compile(r"channel_id=(\d+)")
# newer XLA also prints the iota form: replica_groups=[4,2]<=[8]
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(?P<dims>[0-9,]+)\]<=\[(?P<perm>[0-9,()T]+)\]"
)


@dataclasses.dataclass(frozen=True)
class CollectiveSig:
    """One collective in a compiled program's ordered signature."""

    op: str            # base kind ("all-reduce", "reduce-scatter", ...)
    groups: str        # canonical replica_groups text ("" when absent)
    channel_id: int    # -1 when the op carries no channel
    operand_bytes: int  # summed operand buffer bytes (wire payload proxy)


def _canonical_groups(line: str) -> str:
    m = _REPLICA_GROUPS_RE.search(line)
    if m:
        return m.group(1).replace(" ", "")
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        return f"[{m.group('dims')}]<=[{m.group('perm')}]"
    return ""


def collective_signature(
    hlo: "str | Mapping[str, HloInstr]",
) -> tuple[CollectiveSig, ...]:
    """The ordered collective signature of one compiled program.

    Order is post-optimization text order (the scheduler's order — what
    every device executes); ``-done`` halves of async pairs are dropped so
    each collective counts once, at its issue point."""
    instrs = parse_hlo_instructions(hlo) if isinstance(hlo, str) else hlo
    sigs: list[CollectiveSig] = []
    for instr in instrs.values():
        base = base_collective_op(instr.op)
        if base is None or instr.op.split(".", 1)[0].endswith("-done"):
            continue
        ch = _CHANNEL_ID_RE.search(instr.line)
        operand_bytes = sum(
            instrs[o].bytes for o in instr.operands if o in instrs
        ) or instr.bytes
        sigs.append(CollectiveSig(
            op=base,
            groups=_canonical_groups(instr.line),
            channel_id=int(ch.group(1)) if ch else -1,
            operand_bytes=operand_bytes,
        ))
    return tuple(sigs)


def parse_group_partition(groups: str) -> tuple[tuple[int, ...], ...] | None:
    """Explicit replica_groups text → partition of device ids, or None
    for empty/iota/world groups (world groups partition trivially)."""
    if not groups or "<=" in groups:
        return None
    out = []
    for grp in re.findall(r"\{([0-9,\s]*)\}", groups):
        ids = tuple(int(x) for x in grp.split(",") if x.strip())
        if ids:
            out.append(ids)
    return tuple(out) or None


def canonical_partition_text(partition: tuple[tuple[int, ...], ...]) -> str:
    """Order-independent rendering: groups sorted by first member, ids
    sorted within each group — two collectives whose groups enumerate the
    same partition in different order are the SAME factorization."""
    groups = sorted(tuple(sorted(g)) for g in partition)
    return ",".join("{" + ",".join(str(i) for i in g) + "}" for g in groups)


def partitions_compatible(
    p: tuple[tuple[int, ...], ...], q: tuple[tuple[int, ...], ...],
) -> bool:
    """Two worker-group factorizations of the same device set commute iff
    every pairwise intersection has ONE uniform size (mesh-axis-derived
    partitions always do: |p∩q| is 0 or the product of the shared axes).
    A hand-rolled grouping that straddles the other's groups unevenly —
    the expert-a2a-vs-compression-worker hazard — fails this."""
    sizes = {
        len(set(a) & set(b))
        for a in p for b in q
        if set(a) & set(b)
    }
    return len(sizes) <= 1


def signature_order_finding(
    program: str,
    first: tuple[CollectiveSig, ...],
    second: tuple[CollectiveSig, ...],
) -> Finding | None:
    """Two independent compiles of the same program must schedule the same
    collective sequence — rank k's executable is built on rank k from the
    same inputs, so ANY compile-time nondeterminism here is a pod-scale
    mismatched-collective hang waiting for a cache miss."""
    if first == second:
        return None
    diverge = next(
        (i for i, (a, b) in enumerate(zip(first, second)) if a != b),
        min(len(first), len(second)),
    )
    return Finding(
        severity="error",
        pass_name="ir",
        code="nondeterministic-collective-order",
        message=(
            f"{program}: two compiles of the same program disagree on the "
            f"collective sequence (lengths {len(first)} vs {len(second)}, "
            f"first divergence at position {diverge}) — per-rank "
            "compilation would execute mismatched collectives and hang "
            "the pod",
        ),
        context={"program": program, "position": diverge},
    )


def census_findings(
    signatures: Mapping[str, tuple[CollectiveSig, ...]],
    pairs: Iterable[tuple[str, str]] = (),
) -> list[Finding]:
    """The cross-program collective-matching census.

    - per program: an info ``collective-signature`` row (count + op
      histogram + distinct factorizations) — the operator-readable census.
    - within each program: every pair of distinct explicit factorizations
      must be compatible (``partitions_compatible``) — error
      ``collective-group-incompatible``.
    - across each requested pair of programs: the union of their
      factorizations must stay pairwise compatible — error
      ``collective-group-mismatch`` (paired programs run back-to-back
      over the same devices; incompatible worker groupings mean the two
      programs disagree about which ranks move together).
    """
    findings: list[Finding] = []
    facts: dict[str, dict[str, tuple[tuple[int, ...], ...]]] = {}
    for name, sigs in signatures.items():
        ops: dict[str, int] = {}
        for s in sigs:
            ops[s.op] = ops.get(s.op, 0) + 1
        fact: dict[str, tuple[tuple[int, ...], ...]] = {}
        for s in sigs:
            partition = parse_group_partition(s.groups)
            if partition is not None:
                fact[canonical_partition_text(partition)] = partition
        facts[name] = fact
        findings.append(Finding(
            severity="info",
            pass_name="ir",
            code="collective-signature",
            message=(
                f"{name}: {len(sigs)} collective(s) "
                f"[{', '.join(f'{k}x{v}' for k, v in sorted(ops.items()))}]"
                f", {len(fact)} distinct replica-group factorization(s)"
            ),
            context={
                "program": name,
                "collectives": len(sigs),
                "ops": ops,
                "factorizations": sorted(fact),
            },
        ))
        keys = sorted(fact)
        for i, ga in enumerate(keys):
            for gb in keys[i + 1:]:
                if not partitions_compatible(fact[ga], fact[gb]):
                    findings.append(Finding(
                        severity="error",
                        pass_name="ir",
                        code="collective-group-incompatible",
                        message=(
                            f"{name}: replica-group factorizations "
                            f"{ga} and {gb} straddle each other unevenly "
                            "— two collectives in ONE program disagree "
                            "about which ranks move together (the "
                            "expert-all-to-all vs compression-worker "
                            "hazard)"
                        ),
                        context={"program": name, "groups": [ga, gb]},
                    ))
    for a, b in pairs:
        if a not in facts or b not in facts:
            continue
        for ga, pa in sorted(facts[a].items()):
            for gb, pb in sorted(facts[b].items()):
                if not partitions_compatible(pa, pb):
                    findings.append(Finding(
                        severity="error",
                        pass_name="ir",
                        code="collective-group-mismatch",
                        message=(
                            f"{a} and {b}: worker-group factorizations "
                            f"disagree ({ga} vs {gb}) — paired programs "
                            "run over the same devices and must slice "
                            "them compatibly, or the two programs' "
                            "collectives imply different pod groupings"
                        ),
                        context={"programs": [a, b], "groups": [ga, gb]},
                    ))
    return findings
