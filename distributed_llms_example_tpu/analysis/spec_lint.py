"""Pass 1 — lint ShardingRules against the mesh and an abstract param tree.

Runs entirely from ShapeDtypeStructs: no weights, no devices, CPU-safe.
Catches the failure classes a typo'd rule produces at scale:

- an axis name not in the mesh (``P("tensro", ...)``) — jax surfaces this
  as an opaque KeyError at device_put time, after minutes of setup;
- the same axis used twice in one spec (undivisible by construction);
- a rule regex that matches no parameter path — the params it meant to
  shard silently fall through to the replicated default;
- a parameter above ``replicated_bytes_threshold`` that ends up fully
  replicated on a mesh that HAS model-sharding axes to offer — the
  "typo'd spec replicates a 7B weight until HBM blows" case;
- spec'd dims the mesh cannot divide (``divisible_spec`` replicates them
  at runtime with one log line; the lint says so up front).
"""

from __future__ import annotations

import math
from typing import Any, Mapping

from distributed_llms_example_tpu.analysis.findings import Finding
from distributed_llms_example_tpu.core.config import AXES

# Replicating anything past this on a model-sharded mesh is flagged as an
# error: 16 MiB is far above every legitimate replicated leaf (norm scales,
# biases, small position tables) and far below any transformer matmul
# weight at 7B scale (a llama-2-7b attention kernel is 64 MiB in fp32).
DEFAULT_REPLICATED_BYTES_THRESHOLD = 16 * 1024**2

# Axes whose purpose is splitting the MODEL (params/optimizer state);
# ``data`` replicates params by design, so a pure-DP mesh never triggers
# the oversized-replicated check.
MODEL_SHARDING_AXES = ("fsdp", "tensor", "expert", "stage")


def _spec_axes(spec) -> list[str]:
    """Flat axis names referenced by a PartitionSpec."""
    out: list[str] = []
    for entry in spec:
        if entry is None:
            continue
        out.extend(entry if isinstance(entry, tuple) else (entry,))
    return out


def _leaf_bytes(leaf: Any) -> int:
    shape = tuple(getattr(leaf, "shape", ()))
    dtype = getattr(leaf, "dtype", None)
    itemsize = getattr(dtype, "itemsize", 4) if dtype is not None else 4
    return int(math.prod(shape)) * itemsize


def lint_accumulator_mirror(params: Any, rules: Any = None) -> list[Finding]:
    """The grad-accumulation layout contract: the in-step fp32 gradient
    accumulators must be sharded EXACTLY like the parameters, leaf for
    leaf (``train/step.py accumulator_shardings`` — the weight-update-
    sharding recipe of arXiv:2004.13336).  This pass feeds the live
    function a tree of the params' resolved PartitionSpecs and errors on
    any leaf it fails to mirror — so an edit that replicates the
    accumulators (a param-sized fp32 copy per device) or re-shards them
    against the carry (a GSPMD reshard per microbatch) fails the lint
    before it ever compiles.  Device-free: specs only, no mesh."""
    import jax.tree_util as jtu

    from distributed_llms_example_tpu.parallel.sharding import _path_str
    from distributed_llms_example_tpu.train.step import accumulator_shardings

    if rules is None:
        from distributed_llms_example_tpu.parallel.sharding import default_rules

        rules = default_rules()

    paths: list[str] = []
    specs: list[Any] = []
    jtu.tree_map_with_path(
        lambda path, x: (
            paths.append(_path_str(path)),
            specs.append(rules.spec_for(_path_str(path), len(getattr(x, "shape", ())))),
        )
        and None,
        params,
    )
    param_spec_tree = jtu.tree_unflatten(jtu.tree_structure(params), specs)
    mirrored = accumulator_shardings(param_spec_tree)
    mirrored_leaves = jtu.tree_leaves(mirrored)
    findings: list[Finding] = []
    if len(mirrored_leaves) != len(specs):
        return [
            Finding(
                severity="error",
                pass_name="spec",
                code="accumulator-tree-mismatch",
                message=(
                    f"accumulator_shardings returned {len(mirrored_leaves)} "
                    f"leaves for a {len(specs)}-leaf param tree — the fp32 "
                    "accumulator tree no longer mirrors the params"
                ),
            )
        ]
    for path, want, got in zip(paths, specs, mirrored_leaves):
        if got != want:
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code="accumulator-spec-mismatch",
                    message=(
                        f"{path}: gradient accumulator spec {got} differs "
                        f"from the param spec {want} — the in-step fp32 "
                        "accumulators must mirror the param shardings "
                        "exactly (anything else replicates a param-sized "
                        "fp32 tree per device, or forces GSPMD to reshard "
                        "every microbatch's gradients against the carry)"
                    ),
                    context={"param": path, "param_spec": str(want), "accum_spec": str(got)},
                )
            )
    return findings


def lint_error_feedback_mirror(params: Any, rules: Any = None) -> list[Finding]:
    """The grad-compression layout contract (``--grad-compression int8``,
    ``ops/quant_collectives.py``): every error-feedback leaf is the
    param's spec with the worker dim prefixed over the replica axes —
    ``P(GRAD_WORKER_AXES, *param_spec)`` — i.e. the inner dims mirror the
    params EXACTLY, leaf for leaf, like the grad-accum carry.  This pass
    feeds the live ``error_feedback_specs`` function the params' resolved
    specs and errors on any leaf whose inner spec drifts from its param's
    (a drifted EF replicates a param-sized fp32 residual per device, or
    forces GSPMD to reshard the residual against the tiled gradients
    every step) or whose worker prefix is not the replica axes (the
    residual would shard over a model axis and stop being per-worker).
    Device-free: specs only, no mesh."""
    import jax.tree_util as jtu

    from distributed_llms_example_tpu.ops.quant_collectives import (
        GRAD_WORKER_AXES,
        error_feedback_specs,
    )
    from distributed_llms_example_tpu.parallel.sharding import _path_str

    if rules is None:
        from distributed_llms_example_tpu.parallel.sharding import default_rules

        rules = default_rules()

    paths: list[str] = []
    specs: list[Any] = []
    jtu.tree_map_with_path(
        lambda path, x: (
            paths.append(_path_str(path)),
            specs.append(rules.spec_for(_path_str(path), len(getattr(x, "shape", ())))),
        )
        and None,
        params,
    )
    param_spec_tree = jtu.tree_unflatten(jtu.tree_structure(params), specs)
    ef_leaves = jtu.tree_leaves(error_feedback_specs(param_spec_tree))
    findings: list[Finding] = []
    if len(ef_leaves) != len(specs):
        return [
            Finding(
                severity="error",
                pass_name="spec",
                code="error-feedback-tree-mismatch",
                message=(
                    f"error_feedback_specs returned {len(ef_leaves)} leaves "
                    f"for a {len(specs)}-leaf param tree — the EF tree no "
                    "longer mirrors the params"
                ),
            )
        ]
    want_prefix = (
        GRAD_WORKER_AXES[0] if len(GRAD_WORKER_AXES) == 1 else GRAD_WORKER_AXES
    )
    for path, pspec, ef in zip(paths, specs, ef_leaves):
        prefix = ef[0] if len(ef) else None
        inner = tuple(ef[1:])
        if prefix != want_prefix or inner != tuple(pspec):
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code="error-feedback-spec-mismatch",
                    message=(
                        f"{path}: error-feedback spec {ef} does not mirror "
                        f"the param spec {pspec} under the "
                        f"{GRAD_WORKER_AXES} worker prefix — the EF tree "
                        "must be the param layout with the worker dim over "
                        "the replica axes (anything else replicates the "
                        "fp32 residual per device or re-shards it against "
                        "the tiled gradients every step)"
                    ),
                    context={
                        "param": path,
                        "param_spec": str(pspec),
                        "ef_spec": str(ef),
                    },
                )
            )
    return findings


def lint_optimizer_moment_mirror(params: Any, rules: Any = None) -> list[Finding]:
    """The fused-optimizer layout contract (``ops/fused_optim.py``): the
    AdamW moments' resolved specs must equal the param specs, leaf for
    leaf.  The moments live in the optax chain state at paths ENDING
    with the param path (``opt_state/1/0/mu/<param path>``), and
    ``state_shardings`` resolves them through the same unanchored
    path-regex rules — so mirroring normally holds by construction.
    This pass errors when it does NOT (an anchored rule, a rule matching
    'mu'/'nu' path segments): the fused apply shard_maps (param, mu, nu,
    grad) with ONE spec per leaf, and a diverging moment spec would
    force GSPMD to reshard the moments against the kernel's layout
    every step.
    Device-free: specs only, no mesh."""
    import jax.tree_util as jtu

    from distributed_llms_example_tpu.parallel.sharding import _path_str

    if rules is None:
        from distributed_llms_example_tpu.parallel.sharding import default_rules

        rules = default_rules()

    findings: list[Finding] = []
    leaves: list[tuple[str, int]] = []
    jtu.tree_map_with_path(
        lambda path, x: leaves.append(
            (_path_str(path), len(getattr(x, "shape", ())))
        ),
        params,
    )
    for path, ndim in leaves:
        want = rules.spec_for(path, ndim)
        for moment in ("mu", "nu"):
            moment_path = f"opt_state/1/0/{moment}/{path}"
            got = rules.spec_for(moment_path, ndim)
            if got != want:
                findings.append(
                    Finding(
                        severity="error",
                        pass_name="spec",
                        code="optimizer-moment-spec-mismatch",
                        message=(
                            f"{moment_path}: adam {moment} resolves to spec "
                            f"{got} but its param resolves to {want} — the "
                            "fused optimizer apply shard_maps (param, mu, "
                            "nu, grad) with ONE spec per leaf; a rule that "
                            "distinguishes the moment path breaks the "
                            "mirror (and costs a GSPMD reshard per step on "
                            "the xla path too)"
                        ),
                        context={
                            "param": path,
                            "param_spec": str(want),
                            "moment_spec": str(got),
                        },
                    )
                )
    return findings


def lint_cache_sharding(
    cache: Any,
    mesh_axes: Mapping[str, int],
    *,
    rules: Any = None,
    replicated_bytes_threshold: int = DEFAULT_REPLICATED_BYTES_THRESHOLD,
) -> list[Finding]:
    """Pass 1 for the SERVING state: the per-layer KV cache is the second
    long-lived sharded tree (params being the first), so its rule set
    (``parallel/sharding.py CACHE_RULES``) gets the same validation —
    unknown axes, duplicate axes, dead rules, ragged dims, and any
    cached_key/cached_value leaf that would end up fully replicated on a
    mesh with batch/tensor capacity (a replicated cache multiplies decode
    HBM by the mesh size, exactly the unsharded-cache failure this
    subsystem exists to close).  ``cache`` is an abstract tree
    (ShapeDtypeStruct leaves) — e.g. ``evaluation.generation
    abstract_cache``."""
    if rules is None:
        from distributed_llms_example_tpu.parallel.sharding import cache_rules

        rules = cache_rules()
    findings = lint_sharding_rules(
        rules, mesh_axes, cache,
        replicated_bytes_threshold=replicated_bytes_threshold,
    )
    # the oversized-replicated check above only fires on rule FALLTHROUGH;
    # for the cache the contract is stronger — every K/V buffer must hit a
    # sharding rule (a cache leaf no rule matches decodes replicated).
    # The int8 KV cache's 3-D ``*_scale`` leaves are held to the same bar:
    # an unmatched scale leaf replicates batch×heads×len f32 per device
    # AND desyncs from the s8 buffers it dequantizes (a GSPMD reshard on
    # every decode step).
    import jax.tree_util as jtu

    from distributed_llms_example_tpu.parallel.sharding import _path_str

    leaves: list[tuple[str, Any]] = []
    jtu.tree_map_with_path(lambda p, x: leaves.append((_path_str(p), x)), cache)
    for path, leaf in leaves:
        nd = len(getattr(leaf, "shape", ()))
        if nd != 4 and not (nd == 3 and path.endswith("_scale")):
            continue
        if rules.match_path(path) is None:
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code="unmatched-cache-leaf",
                    message=(
                        f"cache leaf {path} matches no cache sharding rule — "
                        "it would decode fully replicated (per-device HBM × "
                        "mesh size for the serving state)"
                    ),
                    context={"leaf": path},
                )
            )
    return findings


# Axes a topology change may NOT move when either side uses them (>1):
# ``stage`` because the stacked-block storage layout is a function of the
# stage count (a resized axis silently permutes layers — composition row
# reshard-pipelined), ``expert`` because the MoE program structure
# (expert placement, the a2a groups, capacity math) is built around the
# expert count — restoring an expert>1 checkpoint onto an expert=1 mesh
# used to surface as an opaque restore exception deep in the walk-back.
RESHARD_PINNED_AXES = ("stage", "expert")


def lint_reshard_layout(
    saved_layout: Mapping[str, Any],
    mesh_axes: Mapping[str, int],
    params: Any,
    *,
    rules: Any = None,
) -> list[Finding]:
    """The resharding-restore proof pass (ISSUE 14): cross-check a
    checkpoint's recorded topology — the ``mesh_layout`` payload leaf /
    recovery-sidecar dict, ``{"axes": {axis: size}, "processes": N,
    "ef_workers": W}`` — against an ARBITRARY target mesh.

    Errors are the unmappable factorizations (the restore must fail fast
    and named, not deep in orbax): an axis name the live build does not
    know, or a moved ``stage``/``expert`` axis (see
    ``RESHARD_PINNED_AXES``).  ``data``/``fsdp``/``tensor``/``sequence``
    re-factorizations are exactly what the resharding restore exists
    for — for those the pass instead proves the TARGET layout is
    well-typed: every param leaf's spec resolves on the target mesh
    (ragged dims → warning: they silently replicate), and the
    accumulator / error-feedback mirrors re-derive leaf-for-leaf from
    the target param specs (the arXiv:2004.13336 discipline that makes
    the reshard well-typed in the first place).  The EF worker-count
    transition is reported as info (re-tile) or warning (zero-fill).
    Device-free: specs + shapes only."""
    import jax.tree_util as jtu

    from distributed_llms_example_tpu.parallel.sharding import (
        _clip_spec,
        _path_str,
        divisible_spec,
    )

    if rules is None:
        from distributed_llms_example_tpu.parallel.sharding import default_rules

        rules = default_rules()

    findings: list[Finding] = []
    saved_axes = dict(saved_layout.get("axes", {}) or {})
    for a, size in sorted(saved_axes.items()):
        if a not in AXES:
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code="unknown-saved-axis",
                    message=(
                        f"checkpoint layout names mesh axis {a!r} "
                        f"(size {size}), which this build does not know "
                        f"(axes: {', '.join(AXES)}) — the payload was "
                        "written by an incompatible mesh schema"
                    ),
                    context={"axis": a, "size": int(size)},
                )
            )
    for a in RESHARD_PINNED_AXES:
        old = int(saved_axes.get(a, 1) or 1)
        new = int(mesh_axes.get(a, 1) or 1)
        if old != new and (old > 1 or new > 1):
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code=f"reshard-{a}-mismatch",
                    message=(
                        f"checkpoint was saved with {a}={old} but the "
                        f"target mesh has {a}={new} — the {a} "
                        "factorization is part of the program structure "
                        + (
                            "(stacked-block storage layout is a function "
                            "of the stage count; a resized axis silently "
                            "permutes layers)"
                            if a == "stage"
                            else "(expert placement, all-to-all groups and "
                            "capacity math are built around the expert "
                            "count)"
                        )
                        + "; resume on a slice with the same "
                        f"{a} factorization"
                    ),
                    context={"axis": a, "saved": old, "target": new},
                )
            )

    # target-layout well-typedness: every leaf resolvable, ragged dims
    # named (they replicate at runtime — legal, but the operator should
    # know the reshard costs per-device memory)
    mesh_view = type("_MeshView", (), {"shape": dict(mesh_axes)})()
    leaves: list[tuple[str, Any]] = []
    jtu.tree_map_with_path(
        lambda path, x: leaves.append((_path_str(path), x)), params
    )
    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        spec = rules.spec_for(path, len(shape))
        if any(a not in AXES for a in _spec_axes(spec)):
            continue  # a broken rule set is lint_sharding_rules' job
        effective = divisible_spec(spec, shape, mesh_view)
        if effective != _clip_spec(spec, len(shape)):
            findings.append(
                Finding(
                    severity="warning",
                    pass_name="spec",
                    code="reshard-leaf-replicated",
                    message=(
                        f"{path}: shape {shape} resolves to spec {spec} on "
                        f"the target mesh {dict(mesh_axes)} but the ragged "
                        "dims will be replicated — the reshard lands, at a "
                        "per-device memory cost the saving mesh did not pay"
                    ),
                    context={"param": path, "spec": str(spec), "shape": list(shape)},
                )
            )

    # the mirrors that make the reshard well-typed: accumulator and (when
    # the payload carries an EF tree) error-feedback specs re-derived
    # leaf-for-leaf from the TARGET param specs
    findings.extend(lint_accumulator_mirror(params, rules))
    ef_workers = int(saved_layout.get("ef_workers", 0) or 0)
    if ef_workers > 0:
        findings.extend(lint_error_feedback_mirror(params, rules))
        from distributed_llms_example_tpu.ops.quant_collectives import (
            worker_count,
        )

        new_workers = worker_count(dict(mesh_axes))
        if new_workers != ef_workers:
            retile = new_workers > 1 and ef_workers % new_workers == 0
            findings.append(
                Finding(
                    severity="info" if retile else "warning",
                    pass_name="spec",
                    code="reshard-ef-retile" if retile else "reshard-ef-zero-fill",
                    message=(
                        f"error-feedback tree moves from {ef_workers} to "
                        f"{new_workers} worker group(s): "
                        + (
                            "merged groups' residuals sum (total deferred "
                            "error preserved)"
                            if retile
                            else "no residual regrouping preserves the "
                            "per-worker error — it zero-fills (one "
                            "residual's worth of deferred error dropped)"
                        )
                    ),
                    context={"saved_workers": ef_workers, "target_workers": new_workers},
                )
            )
    return findings


def lint_sharding_rules(
    rules: Any,
    mesh_axes: Mapping[str, int],
    params: Any,
    *,
    replicated_bytes_threshold: int = DEFAULT_REPLICATED_BYTES_THRESHOLD,
) -> list[Finding]:
    """Lint ``rules`` (a ShardingRules) against axis sizes and an abstract
    param tree (ShapeDtypeStruct leaves are fine)."""
    from distributed_llms_example_tpu.core.config import unknown_axis_error
    from distributed_llms_example_tpu.parallel.sharding import (
        _clip_spec,
        _path_str,
        divisible_spec,
        rule_match_counts,
    )
    import jax.tree_util as jtu

    findings: list[Finding] = []
    rule_seq = rules.match_rules()

    # --- per-rule checks -------------------------------------------------
    for pattern, spec in rule_seq:
        axes = _spec_axes(spec)
        for a in axes:
            if a not in AXES:
                findings.append(
                    Finding(
                        severity="error",
                        pass_name="spec",
                        code="unknown-mesh-axis",
                        message=f"rule {pattern!r}: {unknown_axis_error(a)}",
                        context={"rule": pattern, "axis": a},
                    )
                )
        dupes = sorted({a for a in axes if axes.count(a) > 1})
        if dupes:
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code="duplicate-spec-axis",
                    message=(
                        f"rule {pattern!r} names mesh axis(es) {dupes} more "
                        "than once in one PartitionSpec — an array dim cannot "
                        "be split twice over the same axis"
                    ),
                    context={"rule": pattern, "axes": dupes},
                )
            )

    # The stock DEFAULT_RULES are a deliberate multi-family union (llama
    # MoE rows are dead on t5, position-table rows dead on llama): dead
    # entries there are design, not typos — info, so `--strict` stays
    # green on every clean default config.  A CUSTOM rule set's dead rule
    # is the typo this check exists for — warning.
    from distributed_llms_example_tpu.parallel.sharding import DEFAULT_RULES

    dead_severity = "info" if rule_seq is DEFAULT_RULES else "warning"
    for (pattern, _), n in zip(rule_seq, rule_match_counts(rules, params)):
        if n == 0:
            findings.append(
                Finding(
                    severity=dead_severity,
                    pass_name="spec",
                    code="dead-rule",
                    message=(
                        f"rule {pattern!r} matched zero parameter paths "
                        "(typo, or shadowed by an earlier rule); anything it "
                        "targeted falls through to the replicated default"
                    ),
                    context={"rule": pattern},
                )
            )

    # --- per-parameter checks -------------------------------------------
    # Capacity = the model-sharding ways this RULE SET can actually use:
    # fsdp/tensor/expert always (the default rules' axes), stage only when
    # a rule names it — on a pure-stage mesh the non-stacked params are
    # replicated by design (the pipeline shards the stacked blocks), not a
    # lint error.
    relevant = {"fsdp", "tensor", "expert"}
    for _, spec in rule_seq:
        relevant.update(a for a in _spec_axes(spec) if a in MODEL_SHARDING_AXES)
    model_capacity = math.prod(max(1, mesh_axes.get(a, 1)) for a in sorted(relevant))
    leaves: list[tuple[str, Any]] = []
    jtu.tree_map_with_path(
        lambda path, x: leaves.append((_path_str(path), x)), params
    )

    # divisible_spec wants a mesh-like object with ``.shape``; give it one
    # so the lint stays device-free
    mesh_view = type("_MeshView", (), {"shape": dict(mesh_axes)})()

    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        ndim = len(shape)
        spec = rules.spec_for(path, ndim)
        if any(a not in AXES for a in _spec_axes(spec)):
            continue  # already reported per-rule; divisibility is moot
        effective = divisible_spec(spec, shape, mesh_view)
        if effective != _clip_spec(spec, ndim):
            findings.append(
                Finding(
                    severity="warning",
                    pass_name="spec",
                    code="ragged-dim-replicated",
                    message=(
                        f"{path}: shape {shape} is not divisible by spec "
                        f"{spec} on mesh {dict(mesh_axes)}; the ragged dims "
                        "will be replicated at runtime (per-device memory "
                        "grows by the dropped factor)"
                    ),
                    context={"param": path, "spec": str(spec), "shape": list(shape)},
                )
            )
        sharded_ways = math.prod(
            max(1, mesh_axes.get(a, 1)) for a in _spec_axes(effective)
        )
        nbytes = _leaf_bytes(leaf)
        if (
            sharded_ways == 1
            and model_capacity > 1
            and nbytes > replicated_bytes_threshold
            # only the DEFAULT fallthrough is an error: a matched rule that
            # ends up replicated is either operator intent (an explicit
            # P()) or a ragged fallback the warning above already names
            and rules.match_path(path) is None
        ):
            findings.append(
                Finding(
                    severity="error",
                    pass_name="spec",
                    code="oversized-replicated-param",
                    message=(
                        f"{path} ({nbytes / 1024**2:.1f} MiB) fell through "
                        "to the replicated default (no rule matched) "
                        f"although the mesh offers {model_capacity}-way "
                        "model sharding "
                        f"({', '.join(a for a in sorted(relevant) if mesh_axes.get(a, 1) > 1)}) "
                        "— every device pays the full copy"
                    ),
                    context={"param": path, "bytes": nbytes},
                )
            )
    return findings
