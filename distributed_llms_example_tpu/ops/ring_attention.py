"""Ring attention: sequence/context parallelism over the ``sequence`` axis.

The reference has no long-context support at all — sequence length is a
fixed 1024/128 pad/truncate (reference train-accelerator.py:114-127) and
its parallelism is data-only (SURVEY.md §5 "Long-context/sequence
parallelism: absent").  This module goes past parity: it makes the
``sequence`` mesh axis a real execution path, so a sequence too long for
one chip's HBM can be sharded across chips and attention still computes
exact (non-approximate) softmax over the full length.

Design (TPU-first, not a port of any CUDA kernel):

- Q stays put; K/V (and any K-aligned padding bias) rotate around the ring
  of ``sequence``-axis neighbors via ``jax.lax.ppermute`` — ICI
  neighbor-to-neighbor traffic, the cheapest collective on a TPU torus.
- Each device folds one (q_block × kv_block) tile per step into a running
  online-softmax state (max ``m``, denominator ``l``, accumulator ``acc``
  — the same streaming-softmax algebra as the Pallas flash kernel in
  ``flash_attention.py``, here expressed in jnp so XLA fuses it and
  autodiff provides the backward pass).
- The next rotation is issued *before* the current tile's compute, so
  XLA's async scheduler overlaps collective-permute with the matmuls.
- With ``causal=True``, tiles strictly above the diagonal are skipped with
  a ``lax.cond`` (no MXU work, the rotation still proceeds), and the
  per-step state update is wrapped in ``jax.checkpoint`` so the backward
  pass recomputes score tiles instead of storing all of them: peak memory
  per device stays O(S_local · d + S_local · S_local) regardless of ring
  size.

Conventions match ``ops.attention``: q/k/v are (batch, heads, seq,
head_dim) — *local shards* inside ``shard_map`` for ``ring_attention``,
global arrays for ``ring_attention_sharded``.  ``bias`` must be K-only:
shape (batch|1, 1, 1, kv_len) additive (a ``mask_to_bias`` padding mask);
it is sharded and rotated along its last axis with K/V.  Learned biases
with a query dimension (T5's relative-position table) are not supported —
T5 keeps its own attention path.  Like the flash kernel, a K-only bias is
treated as a *mask*: it rides the ring as data, and its gradient is zero
by construction of the callers (padding masks are constants).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_llms_example_tpu.ops.attention import NEG_INF
from distributed_llms_example_tpu.parallel.activation import compat_shard_map, pvary_to


def _block_update(carry, q, k, v, bias_blk, q_pos, k_pos, *, scale: float, causal: bool,
                  compute_dtype=None):
    """Fold one (q_blk, kv_blk) attention tile into the running softmax state.

    ``q_pos``/``k_pos`` are *global* positions of the local rows / the
    currently-held (rotated) K block, so the causal mask is exact across
    shard boundaries.  fp32 throughout; the P·V matmul runs in the value
    dtype (bf16 on TPU) on the MXU, like the flash kernel.
    """
    m, l, acc = carry
    # q/k/v may ride the ring (and the causal lax.cond) in fp32
    # (plumb_fp32 below); the matmuls run in the compute dtype so the MXU
    # path is unchanged
    cd = compute_dtype or q.dtype
    q, k, v = q.astype(cd), k.astype(cd), v.astype(cd)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    if causal:
        s = jnp.where(q_pos[None, None, :, None] >= k_pos[None, None, None, :], s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_next = jnp.maximum(m, m_cur)
    alpha = jnp.exp(m - m_next)  # m starts at -inf, all masks are finite → no NaN
    p = jnp.exp(s - m_next)
    l_next = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    acc_next = acc * alpha + pv
    return m_next, l_next, acc_next


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    axis_name: str = "sequence",
    axis_size: int,
    causal: bool = False,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
    plumb_fp32: bool = False,
) -> jnp.ndarray:
    """Exact attention over a sequence sharded across ``axis_name``.

    Must run inside ``shard_map`` with the seq dim of q/k/v sharded over
    ``axis_name`` (``axis_size`` shards, equal blocks).  ``causal=True``
    requires equal global q/kv lengths (top-left alignment, as in
    ``flash_attention``).  ``bias`` is a K-only local block (batch|1, 1, 1,
    kv_blk).  Masking uses a finite NEG_INF, so a row whose keys are ALL
    masked yields a near-uniform average of V, not zeros — such rows are
    padding queries and the caller must loss-mask them (the train step's
    label mask does).

    ``plumb_fp32``: rotate K/V/bias around the ring in fp32 even when the
    compute dtype is bf16.  Needed inside PARTIAL-manual regions (the
    stage×sequence pipeline): the XLA SPMD partitioner miscompiles bf16
    copy chains there ("Invalid binary instruction opcode copy" — the same
    bug the pipeline plumbing works around, parallel/pipeline.py), and the
    transpose of a bf16 ``ppermute`` hits it in the backward pass.  The
    matmuls still run in the compute dtype (``_block_update`` casts back),
    so only ring-hop bandwidth is affected.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, h, q_blk, d = q.shape
    kv_blk = k.shape[2]
    n = axis_size
    idx = jax.lax.axis_index(axis_name)
    q_pos = idx * q_blk + jnp.arange(q_blk)
    m = jnp.full((b, h, q_blk, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, q_blk, 1), jnp.float32)
    acc = jnp.zeros((b, h, q_blk, d), jnp.float32)
    # fresh zeros carry no varying-manual-axes provenance; inside a
    # check_vma region (the stage×sequence pipeline) the running state must
    # match q's vma or the causal lax.cond's branches disagree on types
    # (pre-vma jax has no typeof/pcast — there pvary_to is the identity)
    want = (
        tuple(getattr(jax.typeof(q), "vma", frozenset()))
        if hasattr(jax, "typeof") else ()
    )
    m, l, acc = pvary_to((m, l, acc), want)

    compute_dtype = q.dtype
    update = jax.checkpoint(
        functools.partial(_block_update, scale=scale, causal=causal, compute_dtype=compute_dtype)
    )
    # each step sends the held K/V block to the left neighbor; after t steps
    # device i holds the block that started on device (i + t) mod n
    perm = [(i, (i - 1) % n) for i in range(n)]
    if plumb_fp32 and compute_dtype == jnp.bfloat16:
        q = q.astype(jnp.float32)
        k = k.astype(jnp.float32)
        v = v.astype(jnp.float32)
        bias = None if bias is None else bias.astype(jnp.float32)
    kv: Any = (k, v, bias)
    for t in range(n):
        # issue next rotation before this tile's compute → XLA overlaps the
        # collective-permute with the matmuls
        nxt = jax.tree.map(lambda x: jax.lax.ppermute(x, axis_name, perm), kv) if t < n - 1 else None
        cur_k, cur_v, cur_bias = kv
        src = jax.lax.rem(idx + t, n)
        k_pos = src * kv_blk + jnp.arange(kv_blk)
        if causal:
            # equal blocks ⇒ the tile is all-masked iff src > idx; skip its MXU work
            m, l, acc = jax.lax.cond(
                src <= idx,
                lambda ops: update(ops[:3], *ops[3:]),
                lambda ops: ops[:3],
                (m, l, acc, q, cur_k, cur_v, cur_bias, q_pos, k_pos),
            )
        else:
            m, l, acc = update((m, l, acc), q, cur_k, cur_v, cur_bias, q_pos, k_pos)
        if nxt is not None:
            kv = nxt
    # l >= 1 always: every device applies at least one update (causal skip
    # never drops the diagonal tile) and the running max makes the max
    # element contribute exp(0) = 1, so no division guard is needed
    out = acc / l
    return out.astype(dtype or compute_dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    mesh: Mesh,
    causal: bool = False,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
    batch_axes: tuple[str, ...] = ("data", "fsdp", "expert"),
    head_axis: str = "tensor",
    seq_axis: str = "sequence",
) -> jnp.ndarray:
    """Global-array front door: shard (batch over data×fsdp, heads over
    tensor, seq over sequence) and run the ring per-shard.

    Requires: seq dims divisible by the ``sequence`` axis size, batch by
    the batch shards, heads by ``tensor`` — callers gate on
    ``select_attention_impl`` (ops/mha.py), which falls back to XLA
    attention when any of these fail.
    """
    n = mesh.shape.get(seq_axis, 1)
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    head = head_axis if head_axis in mesh.shape else None
    qspec = P(batch_axes or None, head, seq_axis, None)
    args: list = [q, k, v]
    in_specs: list = [qspec, qspec, qspec]
    if bias is not None:
        if bias.shape[1] != 1 or bias.shape[2] != 1:
            raise ValueError(
                f"ring attention needs a K-only bias (b|1, 1, 1, K); got {bias.shape}"
            )
        in_specs.append(P((batch_axes or None) if bias.shape[0] != 1 else None, None, None, seq_axis))
        args.append(bias)

    def run(q, k, v, *rest):
        return ring_attention(
            q, k, v, rest[0] if rest else None,
            axis_name=seq_axis, axis_size=n, causal=causal, scale=scale, dtype=dtype,
        )

    return compat_shard_map(
        run, mesh=mesh, in_specs=tuple(in_specs), out_specs=qspec, check_vma=False
    )(*args)
