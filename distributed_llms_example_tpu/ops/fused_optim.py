"""Fused Pallas clip+AdamW(+weight-decay+health) optimizer apply.

Why this exists: BENCH_7B_r05 pins 99.3 ms/step of non-layer overhead on
the 7B recipe, and a slice of it is the optimizer tail — the optax chain
(`clip_by_global_norm` → `scale_by_adam` → `add_decayed_weights` →
`scale_by_learning_rate` → `apply_updates`) lowers to MANY small HLO ops
per parameter leaf, each reading and writing param-sized fp32 buffers:
mu/nu EMA updates, bias-corrected division, sqrt, weight decay, lr scale,
and the final add each make their own pass unless XLA happens to fuse
them.  This module collapses the whole per-leaf update into ONE Pallas
kernel pass: each tile reads (param, mu, nu, grad) once, applies
clip-scale → AdamW → weight decay → lr in registers, and writes (param,
mu, nu) back IN PLACE (``input_output_aliases`` — no fp32 param copy),
emitting the health partial sums (param/update sum-of-squares, non-finite
grad count) from the same pass so ``--health`` costs no extra reduction
pass either.

Bit-equivalence contract: the kernel replicates the optax 0.2.x op
sequence EXACTLY, elementwise —

    gc  = select(gnorm < max_norm, g, (g / gnorm) * max_norm)
    mu' = (1-b1)*gc + b1*mu            nu' = (1-b2)*gc^2 + b2*nu
    u   = (mu'/bc1) / (sqrt(nu'/bc2) + eps)
    u   = u + wd*p        (decay-masked leaves only)
    u   = (-lr) * u       p' = p + u

with the scalars (global grad-norm, clip trigger, bias corrections,
-lr) computed OUTSIDE the kernel by the very same jnp expressions optax
uses.  Elementwise IEEE ops are deterministic, so the fused apply equals
the optax chain's output up to XLA's per-compilation FLOAT CONTRACTION —
the backend may fuse a multiply-add into an FMA in one program and not
the other, measured at ≤1 element per few thousand and a few ulp after
cancellation (pinned by tests/test_fused_optim.py; the opt-state pytree
structure and integer counts are exact, and the per-leaf health SUMS may
differ in reduction order — they are metrics, not state).  The global
grad-norm itself is the standard two-stage reduction: per-shard partial
sum-of-squares, then the cross-shard psum GSPMD inserts — the
weight-update-sharding recipe of arXiv:2004.13336, same as the optax
path.

Sharding: the apply is purely elementwise per leaf, so each leaf runs
per-shard under ``compat_shard_map`` with the leaf's OWN param
PartitionSpec (params, mu, nu and the grad accumulators share it by the
PR 5 mirror contract — ``analysis/spec_lint.py`` lints both mirrors).
Health partial sums psum over exactly the leaf's sharded axes.  Leaves
the kernel cannot tile (element count not a multiple of 8·128, non-f32
dtypes) take :func:`adamw_leaf_reference` — the same formulas in plain
jnp under the same contract, partitioned by GSPMD like any
elementwise op.

Impl selection mirrors ``ops/fused_dropout.py``: ``--optim-impl auto``
resolves to ``fused`` on TPU backends and ``xla`` (the optax chain)
elsewhere; tests force ``fused`` to exercise the interpret-mode kernel
on CPU.  The opt-state layout is UNTOUCHED — ``train/optim.py`` parses
and rebuilds the standard optax pytree, so checkpoints round-trip
freely between impls (test-pinned).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # TPU vector lane count
SUBLANES = 8  # fp32 sublane alignment

# VMEM tile budget: 7 live buffers (4 in / 3 out) per tile; 128K fp32
# elements each keeps the working set ~3.5 MB, far under the 16 MB stack.
_MAX_TILE_ELEMS = 128 * 1024

# scalar-vector layout (SMEM input): the traced per-step scalars the
# kernel consumes.  Indices are shared with the reference path.
_S_GNORM, _S_TRIGGER, _S_BC1, _S_BC2, _S_NEG_LR = 0, 1, 2, 3, 4
SCALARS = 8  # padded so the SMEM vector stays one sublane

# per-leaf stats-vector layout (SMEM output): health partial sums
# produced in the same kernel pass.
STAT_P_SUMSQ, STAT_U_SUMSQ, STAT_NONFINITE = 0, 1, 2
STATS = 4

# ---------------------------------------------------------------- impl knob

_VALID_IMPLS = ("auto", "fused", "xla")
_DEFAULT_IMPL = "auto"


def set_default_impl(impl: str) -> None:
    """Process-wide default for the optimizer apply when the caller does
    not pin one — the trainer sets it from ``--optim-impl`` at startup,
    bench flips it for the fused-vs-xla A/B."""
    global _DEFAULT_IMPL
    if impl not in _VALID_IMPLS:
        raise ValueError(f"optim impl {impl!r}: must be one of {_VALID_IMPLS}")
    _DEFAULT_IMPL = impl


def default_impl() -> str:
    return _DEFAULT_IMPL


def resolve_impl(impl: str | None = None, backend: str | None = None) -> str:
    """``auto`` → ``fused`` on TPU, ``xla`` elsewhere (the interpreted
    kernel is pure overhead in a real CPU run; tests pin ``fused``
    explicitly to exercise it)."""
    impl = impl or _DEFAULT_IMPL
    if impl not in _VALID_IMPLS:
        raise ValueError(f"optim impl {impl!r}: must be one of {_VALID_IMPLS}")
    if impl != "auto":
        return impl
    backend = backend or jax.default_backend()
    return "fused" if backend == "tpu" else "xla"


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------------- tiling


def _pick_cols(total: int) -> int:
    """Widest 128-multiple divisor of ``total`` ≤ 2048 whose row count
    stays 8-aligned — the apply is elementwise, so ANY (rows, cols)
    factorization of the flattened leaf is valid."""
    for cols in range(2048, 0, -LANES):
        if total % cols == 0 and (total // cols) % SUBLANES == 0:
            return cols
    return 0


def _pick_block_rows(rows: int, cols: int) -> int:
    cap = max(SUBLANES, (_MAX_TILE_ELEMS // max(cols, 1)) // SUBLANES * SUBLANES)
    start = min(rows, cap) // SUBLANES * SUBLANES
    for b in range(start, SUBLANES - 1, -SUBLANES):
        if rows % b == 0:
            return b
    return 0


def fused_adamw_supported(n_elems: int, dtype=jnp.float32) -> bool:
    """True when the kernel can serve a leaf (or leaf-shard) of this
    size: fp32, flattenable into 8-aligned rows of 128-aligned lanes.
    Unsupported leaves take the jnp reference path (same op
    sequence, same contract)."""
    if jnp.dtype(dtype) != jnp.float32:
        return False
    n = int(n_elems)
    if n <= 0 or n % (SUBLANES * LANES):
        return False
    cols = _pick_cols(n)
    return cols > 0 and _pick_block_rows(n // cols, cols) > 0


# ------------------------------------------------------------------- kernel


def _adamw_kernel(
    scal_ref, p_ref, mu_ref, nu_ref, g_ref, po_ref, muo_ref, nuo_ref,
    stats_ref, *, b1: float, b2: float, eps: float, max_norm: float,
    wd: float, clip: bool,
):
    """One row-tile of the fused apply.  All elementwise ops follow the
    optax op sequence exactly (module docstring) so the tile's output
    bits match the optax chain's; the stats accumulate across the
    sequential grid into the SMEM vector."""
    i = pl.program_id(0)
    g = g_ref[...]
    if clip:
        gnorm = scal_ref[_S_GNORM]
        trigger = scal_ref[_S_TRIGGER]
        # optax clip_by_global_norm: select(trigger, t, (t/g_norm)*max_norm)
        g = jnp.where(trigger != 0.0, g, (g / gnorm) * max_norm)
    p = p_ref[...]
    mu = (1 - b1) * g + b1 * mu_ref[...]
    nu = (1 - b2) * (g * g) + b2 * nu_ref[...]
    mu_hat = mu / scal_ref[_S_BC1]
    nu_hat = nu / scal_ref[_S_BC2]
    u = mu_hat / (jnp.sqrt(nu_hat) + eps)
    if wd:
        u = u + wd * p
    u = scal_ref[_S_NEG_LR] * u
    po_ref[...] = p + u
    muo_ref[...] = mu
    nuo_ref[...] = nu
    # health partial sums, same pass: param/update sum-of-squares and the
    # non-finite count of the (pre-clip) normalized gradient
    p_ss = jnp.sum(p * p)
    u_ss = jnp.sum(u * u)
    nf = jnp.sum((~jnp.isfinite(g_ref[...])).astype(jnp.float32))

    @pl.when(i == 0)
    def _():
        stats_ref[STAT_P_SUMSQ] = 0.0
        stats_ref[STAT_U_SUMSQ] = 0.0
        stats_ref[STAT_NONFINITE] = 0.0
        stats_ref[STATS - 1] = 0.0

    stats_ref[STAT_P_SUMSQ] = stats_ref[STAT_P_SUMSQ] + p_ss
    stats_ref[STAT_U_SUMSQ] = stats_ref[STAT_U_SUMSQ] + u_ss
    stats_ref[STAT_NONFINITE] = stats_ref[STAT_NONFINITE] + nf


def fused_adamw_leaf(
    p: jnp.ndarray, mu: jnp.ndarray, nu: jnp.ndarray, g: jnp.ndarray,
    scal: jnp.ndarray, *, b1: float, b2: float, eps: float,
    max_norm: float, wd: float, interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The fused per-leaf apply: (p', mu', nu', stats[4]) in one Pallas
    pass, param/mu/nu buffers aliased in place.  ``g`` is the
    token-NORMALIZED fp32 gradient (the ``optimizer_apply_block``
    contract); ``scal`` the ``SCALARS``-vector of traced step scalars.
    Gate on :func:`fused_adamw_supported` — this raises on untileable
    shapes."""
    if interpret is None:
        interpret = _default_interpret()
    shape = p.shape
    total = int(math.prod(shape))
    cols = _pick_cols(total)
    if not cols:
        raise ValueError(
            f"leaf of {total} elements is not fused-adamw tileable; gate on "
            "fused_adamw_supported"
        )
    rows = total // cols
    block_rows = _pick_block_rows(rows, cols)
    spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    args = [
        scal,
        p.reshape(rows, cols),
        mu.reshape(rows, cols),
        nu.reshape(rows, cols),
        g.reshape(rows, cols),
    ]
    out = pl.pallas_call(
        functools.partial(
            _adamw_kernel, b1=b1, b2=b2, eps=eps, max_norm=max_norm,
            wd=wd, clip=max_norm > 0,
        ),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), spec, spec, spec, spec],
        out_specs=[spec, spec, spec, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), p.dtype),
            jax.ShapeDtypeStruct((rows, cols), mu.dtype),
            jax.ShapeDtypeStruct((rows, cols), nu.dtype),
            jax.ShapeDtypeStruct((STATS,), jnp.float32),
        ],
        # the in-place contract: param/mu/nu write back over their own
        # buffers — no fp32 param copy in the compiled apply (the IR
        # census extension in analysis/ir_lint.py checks the program)
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(*args)
    p2, mu2, nu2, stats = out
    return p2.reshape(shape), mu2.reshape(shape), nu2.reshape(shape), stats


def adamw_leaf_reference(
    p: jnp.ndarray, mu: jnp.ndarray, nu: jnp.ndarray, g: jnp.ndarray,
    scal: jnp.ndarray, *, b1: float, b2: float, eps: float,
    max_norm: float, wd: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The identical update in plain jnp — the fallback for leaves the
    kernel cannot tile AND the oracle the kernel is tested against.
    Same op sequence, so (compiled) outputs match the kernel and the
    optax chain up to XLA float contraction (module docstring)."""
    g_raw = g  # the PRE-clip gradient: a NaN anywhere makes the global
    # norm NaN and the clip branch then NaN-floods the whole leaf — the
    # non-finite COUNT must see the raw stream (like the kernel's
    # g_ref read and health_metrics), or one bad element reports as
    # leaf-size and the tripwire loses the only signal it exists for
    if max_norm > 0:
        gnorm = scal[_S_GNORM]
        trigger = scal[_S_TRIGGER]
        g = jnp.where(trigger != 0.0, g, (g / gnorm) * max_norm)
    mu2 = (1 - b1) * g + b1 * mu
    nu2 = (1 - b2) * (g * g) + b2 * nu
    u = (mu2 / scal[_S_BC1]) / (jnp.sqrt(nu2 / scal[_S_BC2]) + eps)
    if wd:
        u = u + wd * p
    u = scal[_S_NEG_LR] * u
    stats = jnp.stack([
        jnp.sum(p.astype(jnp.float32) ** 2),
        jnp.sum(u.astype(jnp.float32) ** 2),
        jnp.sum(~jnp.isfinite(g_raw)).astype(jnp.float32),
        jnp.zeros((), jnp.float32),
    ])
    return p + u, mu2, nu2, stats


# ----------------------------------------------------------- tree dispatch


def _spec_axes(spec) -> tuple[str, ...]:
    axes: list[str] = []
    for entry in spec or ():
        if entry is None:
            continue
        axes.extend(entry if isinstance(entry, tuple) else (entry,))
    return tuple(axes)


def _spec_divides(shape: tuple, spec, mesh) -> bool:
    """Every spec'd dim must divide evenly over its axes: shard_map has
    no padded shards, so a ragged leaf must stay on the (GSPMD-padded)
    reference path even when its TOTAL element count happens to tile."""
    for i, entry in enumerate(spec or ()):
        if entry is None or i >= len(shape):
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= max(1, mesh.shape.get(a, 1))
        if shape[i] % n:
            return False
    return True


def _shard_elems(shape: tuple, spec, mesh) -> int:
    n = int(math.prod(shape))
    for a in _spec_axes(spec):
        n //= max(1, mesh.shape.get(a, 1))
    return n


def _sharded_leaf(
    p, mu, nu, g, scal, spec, mesh, *, hyper: dict, interpret: bool | None
):
    """Per-shard kernel run under ``compat_shard_map`` with the leaf's
    own param spec (params/mu/nu/grads share it by the mirror
    contracts); the health partial sums psum over exactly the leaf's
    sharded axes — the second stage of the two-stage reduction."""
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.parallel.activation import compat_shard_map

    axes = _spec_axes(spec)

    def run(scal, p, mu, nu, g):
        p2, mu2, nu2, stats = fused_adamw_leaf(
            p, mu, nu, g, scal, interpret=interpret, **hyper
        )
        if axes:
            stats = jax.lax.psum(stats, axes)
        return p2, mu2, nu2, stats

    return compat_shard_map(
        run, mesh=mesh,
        in_specs=(P(), spec, spec, spec, spec),
        out_specs=(spec, spec, spec, P()),
        check_vma=False,
    )(scal, p, mu, nu, g)


def adamw_tree_apply(
    params, mu, nu, grads, scal, *, b1: float, b2: float, eps: float,
    max_norm: float, weight_decay: float, decay_tree,
    mesh=None, param_specs=None, interpret: bool | None = None,
):
    """Map the fused apply over a whole (params, mu, nu, grads) tree.

    Per leaf: the Pallas kernel when the leaf (or its per-device shard,
    under a >1-device mesh with known ``param_specs``) tiles, the jnp
    reference otherwise — both matching the optax chain up to XLA float
    contraction.  Returns
    ``(new_params, new_mu, new_nu, stats_tree)`` with ``stats_tree``
    holding one ``(STATS,)`` fp32 vector per leaf (health partial sums,
    already cross-shard reduced)."""
    hyper = dict(b1=b1, b2=b2, eps=eps, max_norm=max_norm)
    multi = mesh is not None and int(mesh.devices.size) > 1

    def leaf(p, m, v, g, decay, spec):
        h = dict(hyper, wd=weight_decay if decay else 0.0)
        if not multi:
            if fused_adamw_supported(p.size, p.dtype) and p.dtype == m.dtype == v.dtype:
                return fused_adamw_leaf(p, m, v, g, scal, interpret=interpret, **h)
            return adamw_leaf_reference(p, m, v, g, scal, **h)
        if (
            spec is not None
            and p.dtype == m.dtype == v.dtype
            and _spec_divides(p.shape, spec, mesh)
            and fused_adamw_supported(_shard_elems(p.shape, spec, mesh), p.dtype)
        ):
            return _sharded_leaf(
                p, m, v, g, scal, spec, mesh, hyper=h, interpret=interpret
            )
        # GSPMD partitions the elementwise reference natively
        return adamw_leaf_reference(p, m, v, g, scal, **h)

    # manual flatten: PartitionSpec / bool auxiliary leaves must not be
    # re-interpreted as pytree structure by a multi-tree map
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_mu = treedef.flatten_up_to(mu)
    flat_nu = treedef.flatten_up_to(nu)
    flat_g = treedef.flatten_up_to(grads)
    flat_decay = treedef.flatten_up_to(decay_tree)
    flat_spec = (
        treedef.flatten_up_to(param_specs)
        if param_specs is not None
        else [None] * len(flat_p)
    )
    outs = [
        leaf(p, m, v, g, d, s)
        for p, m, v, g, d, s in zip(
            flat_p, flat_mu, flat_nu, flat_g, flat_decay, flat_spec
        )
    ]
    new_p = treedef.unflatten([o[0] for o in outs])
    new_mu = treedef.unflatten([o[1] for o in outs])
    new_nu = treedef.unflatten([o[2] for o in outs])
    stats = treedef.unflatten([o[3] for o in outs])
    return new_p, new_mu, new_nu, stats
