"""Pallas TPU flash attention (blockwise online-softmax), fwd + bwd.

The reference consumes attention as opaque CUDA/cuDNN kernels inside every
``model(**batch)`` call (reference train-accelerator.py:220); on TPU the
analogous hot op is this kernel: the (S, S) score matrix is never
materialized in HBM — Q/K/V tiles stream HBM→VMEM, QK^T and PV run on the
MXU per (block_q, block_k) tile, and the softmax is computed online with
running max/denominator carried in VMEM scratch across the kv grid axis.

Layout/conventions
  - q, k, v: (batch, heads, seq, head_dim); output matches q.
  - ``bias`` is additive, fp32-convertible, with every dim either 1 or the
    full size — e.g. a (B, 1, 1, K) padding mask from
    ``ops.attention.mask_to_bias``.  Size-1 dims are handled in the
    BlockSpec index maps, so the bias is never broadcast in HBM.
  - ``learned_bias`` is a second additive bias of shape exactly
    (1, H, Q, K) — T5's relative-position bias — that DOES receive a
    gradient: a third backward kernel accumulates dbias = p·(dp − δ)
    tile-by-tile with batch as the innermost (sequential) grid axis, so
    the (B, H, Q, K) un-reduced gradient is never materialized in HBM.
  - ``causal=True`` applies the triangular mask inside the kernel (and
    skips fully-masked kv tiles); don't also encode causality in ``bias``.
  - The backward pass treats ``bias`` as a constant (zero gradient) —
    padding/causal masks only; learned additive biases go through
    ``learned_bias``.
  - Softmax statistics (running max ``m``, denominator ``l``) live in
    (block_q, 128) fp32 scratch — TPU vector layout wants a full 128-lane
    last dim — and the logsumexp residual is saved as (B, H, S, 128) with
    the value replicated across lanes (same layout the backward kernels
    read it in).

Grid semantics: the kv axis is the innermost ("arbitrary") grid dimension,
so scratch accumulators persist across kv steps for a fixed (b, h, q-tile);
batch/heads/q-tiles are "parallel".

On CPU (tests, the 8-device virtual mesh) the kernel runs in Pallas
interpret mode; numerics are checked against ``dot_product_attention``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# pallas renamed TPUCompilerParams -> CompilerParams across jax versions
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

from distributed_llms_example_tpu.parallel.activation import compat_shard_map
from distributed_llms_example_tpu.ops.fused_dropout import tile_keep

LANES = 128  # TPU vector lane count: last-dim unit for scratch/statistics
MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)

# --------------------------------------------------------- probs dropout
#
# Attention-probs dropout rides INSIDE the kernels: the keep-mask for a
# (block_q, block_k) tile is drawn in-kernel from (seed, b, h, tile
# offsets) via ops.fused_dropout.tile_keep (TPU hardware PRNG compiled,
# counter hash in interpret mode), so the (B, H, S, S) mask never exists
# in HBM and the backward kernels recompute the identical mask from the
# same seed instead of saving it.  Math: with p-tilde the unnormalized
# softmax numerator and l its row sum, the forward accumulates
# pv from m·p-tilde/keep while l stays un-dropped — o = acc/l is then
# exactly dropout(softmax(s)) @ v.  Backward: with dp = do·vT,
# ds = p · (m·dp/keep − delta) and dv sums (m·p/keep)T·do, where
# delta = rowsum(do∘o) already equals Σ_j pd_j dp_j.
#
# Dropout seeding is per-(b, h, q-tile, k-tile), so forward and all three
# backward kernels agree as long as they tile identically — they share
# block_q/block_k by construction.


def _tile_dropout_keep(seed_ref, b, h, qi, ki, shape, *, rate: float,
                       block_q: int, block_k: int, hw_rng: bool):
    return tile_keep(
        seed_ref[0], b, h, qi * block_q, ki * block_k, shape, rate, hw_rng
    )


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _bias_spec(bias_shape, block_q: int, block_k: int):
    """BlockSpec for an additive bias whose dims are each 1 or full-size."""
    b1, h1, q1, k1 = (d == 1 for d in bias_shape)
    block = (1, 1, 1 if q1 else block_q, bias_shape[3] if k1 else block_k)

    def index_map(b, h, qi, ki):
        return (0 if b1 else b, 0 if h1 else h, 0 if q1 else qi, 0 if k1 else ki)

    return pl.BlockSpec(block, index_map)


def _causal_mask(s, qi, ki, block_q: int, block_k: int):
    # -inf, not a large finite value: a finite mask score would dominate
    # m_next for rows whose every VALID key is -inf-bias-masked, making the
    # forward average v over causally-forbidden positions.  The online
    # softmax handles -inf via safe_m (fwd) and the lse sentinel (bwd).
    # NOTE the exact-zero/zero-grad guarantee for fully-masked rows holds
    # only for true -inf biases; a finite large-negative padding bias
    # (ops/attention.py NEG_INF = -1e9, chosen because the XLA softmax path
    # NaNs on all--inf rows) leaves an all-padded row as a garbage-but-
    # finite uniform average — identical to the XLA path's behavior, and
    # unreachable from the data pipeline (every example carries ≥1 real
    # token, so no all-masked rows exist in training).
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, -jnp.inf)


# ---------------------------------------------------------------- forward


def _fwd_kernel(
    *refs, scale: float, causal: bool, block_q: int, block_k: int, nk: int,
    has_bias: bool, has_lbias: bool, dropout_rate: float = 0.0,
    hw_rng: bool = False,
):
    it = iter(refs)
    seed_ref = next(it) if dropout_rate > 0.0 else None
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    lbias_ref = next(it) if has_lbias else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = it
    # grid ids at kernel TOP LEVEL: the interpret-mode lowering only
    # rewrites program_id in the outer kernel jaxpr, not inside pl.when
    bi, hi = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    # with causal masking, tiles strictly above the diagonal contribute nothing
    diag_ok = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        q = q_ref[0, 0]  # (block_q, d)
        k = k_ref[0, 0]  # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        if lbias_ref is not None:
            s += lbias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)

        m_prev = m_scr[:, :1]  # (block_q, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        # a row can still be all -inf here (every key masked by a -inf
        # bias): -inf - -inf = NaN would poison alpha/p, so substitute a
        # finite max — exp(-inf - 0) = 0 then zeroes those entries, l
        # stays 0, and _finish's sentinel takes over
        safe_m = jnp.where(m_next == -jnp.inf, 0.0, m_next)
        alpha = jnp.exp(m_prev - safe_m)
        p = jnp.exp(s - safe_m)  # (block_q, block_k)
        l_next = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = jax.lax.broadcast_in_dim(m_next[:, 0], m_scr.shape, (0,))
        l_scr[:] = jax.lax.broadcast_in_dim(l_next[:, 0], l_scr.shape, (0,))
        if seed_ref is not None:
            # drop AFTER l accumulates: l normalizes the un-dropped
            # softmax, the dropped numerator rides only the value product
            keep = _tile_dropout_keep(
                seed_ref, bi, hi, qi, ki,
                p.shape, rate=dropout_rate, block_q=block_q,
                block_k=block_k, hw_rng=hw_rng,
            )
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zeros, not NaN
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:] + jnp.log(jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:]))
        lse_ref[0, 0] = jnp.where(l_scr[:] == 0.0, MASK_VALUE, lse)


def _seed_arg(dropout_seed):
    """(args, specs) prefix carrying the dropout seed into a kernel."""
    if dropout_seed is None:
        return [], []
    seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    return [seed], [pl.BlockSpec(memory_space=pltpu.SMEM)]


def _fwd(q, k, v, bias, lbias, *, scale, causal, block_q, block_k, interpret,
         dropout_rate=0.0, dropout_seed=None, hw_rng=False):
    batch, heads, q_len, d = q.shape
    kv_len = k.shape[2]
    nq, nk = q_len // block_q, kv_len // block_k
    grid = (batch, heads, nq, nk)

    def q_map(b, h, qi, ki):
        return (b, h, qi, 0)

    def kv_map(b, h, qi, ki):
        return (b, h, ki, 0)

    seed_args, in_specs = _seed_arg(dropout_seed if dropout_rate > 0.0 else None)
    in_specs += [
        pl.BlockSpec((1, 1, block_q, d), q_map),
        pl.BlockSpec((1, 1, block_k, d), kv_map),
        pl.BlockSpec((1, 1, block_k, d), kv_map),
    ]
    if bias is not None:
        in_specs.append(_bias_spec(bias.shape, block_q, block_k))
    if lbias is not None:
        in_specs.append(_bias_spec(lbias.shape, block_q, block_k))
    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((batch, heads, q_len, LANES), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, 1, block_q, d), q_map),
        pl.BlockSpec((1, 1, block_q, LANES), q_map),
    ]
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk,
        has_bias=bias is not None, has_lbias=lbias is not None,
        dropout_rate=dropout_rate, hw_rng=hw_rng,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*seed_args, *[x for x in (q, k, v, bias, lbias) if x is not None])
    return o, lse


# --------------------------------------------------------------- backward


def _bwd_dq_kernel(
    *refs, scale: float, causal: bool, block_q: int, block_k: int, nk: int,
    has_bias: bool, has_lbias: bool, dropout_rate: float = 0.0,
    hw_rng: bool = False,
):
    it = iter(refs)
    seed_ref = next(it) if dropout_rate > 0.0 else None
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    lbias_ref = next(it) if has_lbias else None
    do_ref, lse_ref, delta_ref, dq_ref, dq_scr = it
    bi, hi = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    diag_ok = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        q, kk, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        if lbias_ref is not None:
            s += lbias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        lse = lse_ref[0, 0][:, :1]
        p = jnp.exp(s - lse)  # (block_q, block_k)
        # fully-masked rows save lse = MASK_VALUE (sentinel, fwd kernel):
        # exp(s - sentinel) is garbage there (overflows to inf when any s
        # is finite), and inf·0 = NaN would poison the gradient — zero
        # those rows explicitly
        p = jnp.where(lse <= MASK_VALUE / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if seed_ref is not None:
            # recompute the forward's keep-mask from the seed: the dropped
            # entries' dp never reaches ds (d(dropout)/d(p) = m/keep)
            keep = _tile_dropout_keep(
                seed_ref, bi, hi, qi, ki,
                p.shape, rate=dropout_rate, block_q=block_q,
                block_k=block_k, hw_rng=hw_rng,
            )
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    *refs, scale: float, causal: bool, block_q: int, block_k: int, nq: int,
    has_bias: bool, has_lbias: bool, dropout_rate: float = 0.0,
    hw_rng: bool = False,
):
    it = iter(refs)
    seed_ref = next(it) if dropout_rate > 0.0 else None
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    lbias_ref = next(it) if has_lbias else None
    do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr = it
    bi, hi = pl.program_id(0), pl.program_id(1)
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    diag_ok = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        q, kk, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        if lbias_ref is not None:
            s += lbias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        lse = lse_ref[0, 0][:, :1]
        p = jnp.exp(s - lse)
        # zero fully-masked rows (lse == MASK_VALUE sentinel) — see dq kernel
        p = jnp.where(lse <= MASK_VALUE / 2, 0.0, p)
        keep = None
        if seed_ref is not None:
            keep = _tile_dropout_keep(
                seed_ref, bi, hi, qi, ki,
                p.shape, rate=dropout_rate, block_q=block_q,
                block_k=block_k, hw_rng=hw_rng,
            )
        # dv sums the DROPPED probs (only kept entries fed the forward pv)
        pd = p if keep is None else jnp.where(
            keep, p * (1.0 / (1.0 - dropout_rate)), 0.0
        )
        dv_scr[:] += jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if keep is not None:
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta_ref[0, 0][:, :1]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dlbias_kernel(
    *refs, scale: float, causal: bool, block_q: int, block_k: int, nb: int,
    has_bias: bool, dropout_rate: float = 0.0, hw_rng: bool = False,
):
    """Gradient of the LEARNED (1, H, Q, K) bias: dbias = Σ_batch p·(dp−δ).

    Grid is (heads, q-tiles, k-tiles, batch) with batch innermost and
    "arbitrary", so the (block_q, block_k) scratch accumulates the batch
    reduction across grid steps and the un-reduced (B, H, Q, K) gradient
    never exists in HBM.  Recomputes s/p per tile from the residuals (same
    trade the dq/dkv kernels make)."""
    it = iter(refs)
    seed_ref = next(it) if dropout_rate > 0.0 else None
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    lbias_ref, do_ref, lse_ref, delta_ref, dlb_ref, dlb_scr = it
    hi = pl.program_id(0)
    qi, ki, bi = pl.program_id(1), pl.program_id(2), pl.program_id(3)

    @pl.when(bi == 0)
    def _init():
        dlb_scr[:] = jnp.zeros(dlb_scr.shape, jnp.float32)

    diag_ok = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(diag_ok)
    def _compute():
        q, kk, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        s += lbias_ref[0, 0].astype(jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        # masked entries in a live row have s = -inf → p is exactly 0;
        # FULLY-masked rows save lse = MASK_VALUE (sentinel), so exp(s -
        # lse) is garbage there — zero those rows explicitly
        lse = lse_ref[0, 0][:, :1]
        p = jnp.exp(s - lse)
        p = jnp.where(lse <= MASK_VALUE / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if seed_ref is not None:
            # grid here is (heads, q, k, batch): tags stay (b, h)
            keep = _tile_dropout_keep(
                seed_ref, bi, hi, qi, ki,
                p.shape, rate=dropout_rate, block_q=block_q,
                block_k=block_k, hw_rng=hw_rng,
            )
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        # ∂s/∂lbias = 1 (no scale factor — scale multiplies only q·k)
        dlb_scr[:] += p * (dp - delta_ref[0, 0][:, :1])

    @pl.when(bi == nb - 1)
    def _finish():
        dlb_ref[0, 0] = dlb_scr[:].astype(dlb_ref.dtype)


def _bwd_dlbias(q, k, v, bias, lbias, lse, delta, do, *, scale, causal,
                block_q, block_k, interpret,
                dropout_rate=0.0, dropout_seed=None, hw_rng=False):
    batch, heads, q_len, d = q.shape
    kv_len = k.shape[2]
    nq, nk = q_len // block_q, kv_len // block_k
    grid = (heads, nq, nk, batch)

    def q_map(h, qi, ki, b):
        return (b, h, qi, 0)

    def kv_map(h, qi, ki, b):
        return (b, h, ki, 0)

    def lb_map(h, qi, ki, b):
        return (0, h, qi, ki)

    bias_spec = None
    if bias is not None:
        inner = _bias_spec(bias.shape, block_q, block_k)

        def reordered(h, qi, ki, b):
            return inner.index_map(b, h, qi, ki)

        bias_spec = pl.BlockSpec(inner.block_shape, reordered)
    seed_args, in_specs = _seed_arg(dropout_seed if dropout_rate > 0.0 else None)
    in_specs += [
        spec
        for spec in (
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map),
            bias_spec,
            pl.BlockSpec((1, 1, block_q, block_k), lb_map),
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_q, LANES), q_map),
            pl.BlockSpec((1, 1, block_q, LANES), q_map),
        )
        if spec is not None
    ]
    args = seed_args + [
        x for x in (q, k, v, bias, lbias, do, lse, delta) if x is not None
    ]
    return pl.pallas_call(
        functools.partial(
            _bwd_dlbias_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, nb=batch, has_bias=bias is not None,
            dropout_rate=dropout_rate, hw_rng=hw_rng,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, block_k), lb_map),
        out_shape=jax.ShapeDtypeStruct(lbias.shape, lbias.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, block_k), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)


def _bwd(q, k, v, bias, lbias, o, lse, do, *, scale, causal, block_q, block_k,
         interpret, dropout_rate=0.0, dropout_seed=None, hw_rng=False):
    batch, heads, q_len, d = q.shape
    kv_len = k.shape[2]
    nq, nk = q_len // block_q, kv_len // block_k

    # delta_i = rowsum(dO ∘ O): tiny elementwise reduce, leave it to XLA
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jax.lax.broadcast_in_dim(
        delta, (batch, heads, q_len, LANES), (0, 1, 2)
    )

    def q_map(b, h, qi, ki):
        return (b, h, qi, 0)

    def kv_map_q(b, h, qi, ki):
        return (b, h, ki, 0)

    bias_spec = _bias_spec(bias.shape, block_q, block_k) if bias is not None else None
    lbias_spec = _bias_spec(lbias.shape, block_q, block_k) if lbias is not None else None
    seed_args, seed_specs = _seed_arg(dropout_seed if dropout_rate > 0.0 else None)
    common_in = seed_specs + [
        spec
        for spec in (
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_k, d), kv_map_q),
            pl.BlockSpec((1, 1, block_k, d), kv_map_q),
            bias_spec,
            lbias_spec,
            pl.BlockSpec((1, 1, block_q, d), q_map),
            pl.BlockSpec((1, 1, block_q, LANES), q_map),
            pl.BlockSpec((1, 1, block_q, LANES), q_map),
        )
        if spec is not None
    ]
    args = seed_args + [
        x for x in (q, k, v, bias, lbias, do, lse, delta) if x is not None
    ]

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, nk=nk,
            has_bias=bias is not None, has_lbias=lbias is not None,
            dropout_rate=dropout_rate, hw_rng=hw_rng,
        ),
        grid=(batch, heads, nq, nk),
        in_specs=common_in,
        out_specs=pl.BlockSpec((1, 1, block_q, d), q_map),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    # dk/dv: kv tiles are the outer (parallel) axis, q tiles the inner
    def q_map_kv(b, h, ki, qi):
        return (b, h, qi, 0)

    def kv_map_kv(b, h, ki, qi):
        return (b, h, ki, 0)

    def _swap_spec(x):
        if x is None:
            return None
        inner = _bias_spec(x.shape, block_q, block_k)

        def swapped(b, h, ki, qi):
            return inner.index_map(b, h, qi, ki)

        return pl.BlockSpec(inner.block_shape, swapped)

    dkv_in = seed_specs + [
        spec
        for spec in (
            pl.BlockSpec((1, 1, block_q, d), q_map_kv),
            pl.BlockSpec((1, 1, block_k, d), kv_map_kv),
            pl.BlockSpec((1, 1, block_k, d), kv_map_kv),
            _swap_spec(bias),
            _swap_spec(lbias),
            pl.BlockSpec((1, 1, block_q, d), q_map_kv),
            pl.BlockSpec((1, 1, block_q, LANES), q_map_kv),
            pl.BlockSpec((1, 1, block_q, LANES), q_map_kv),
        )
        if spec is not None
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, nq=nq,
            has_bias=bias is not None, has_lbias=lbias is not None,
            dropout_rate=dropout_rate, hw_rng=hw_rng,
        ),
        grid=(batch, heads, nk, nq),
        in_specs=dkv_in,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), kv_map_kv),
            pl.BlockSpec((1, 1, block_k, d), kv_map_kv),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    dlbias = None
    if lbias is not None:
        dlbias = _bwd_dlbias(
            q, k, v, bias, lbias, lse, delta, do,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret, dropout_rate=dropout_rate,
            dropout_seed=dropout_seed, hw_rng=hw_rng,
        )
    return dq, dk, dv, dlbias


# ------------------------------------------------------------- public API


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11, 12)
)
def _flash(q, k, v, bias, lbias, dropout_seed,
           scale, causal, block_q, block_k, interpret, dropout_rate, hw_rng):
    o, _ = _fwd(
        q, k, v, bias, lbias, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed, hw_rng=hw_rng,
    )
    return o


def _flash_fwd(q, k, v, bias, lbias, dropout_seed,
               scale, causal, block_q, block_k, interpret, dropout_rate, hw_rng):
    o, lse = _fwd(
        q, k, v, bias, lbias, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed, hw_rng=hw_rng,
    )
    # the kernel replicates lse across all 128 lanes — keep one lane as the
    # residual so HBM between fwd and bwd holds (B,H,S,1), not (B,H,S,128).
    # The dropout mask is NOT a residual: the backward kernels redraw it
    # from the seed — zero extra bytes for probs dropout.
    return o, (q, k, v, bias, lbias, dropout_seed, o, lse[..., :1])


def _flash_bwd(scale, causal, block_q, block_k, interpret, dropout_rate,
               hw_rng, res, do):
    q, k, v, bias, lbias, dropout_seed, o, lse_lane = res
    lse = jax.lax.broadcast_in_dim(
        lse_lane[..., 0], (*lse_lane.shape[:-1], LANES), (0, 1, 2)
    )
    dq, dk, dv, dlbias = _bwd(
        q, k, v, bias, lbias, o, lse, do, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        dropout_rate=dropout_rate, dropout_seed=dropout_seed, hw_rng=hw_rng,
    )
    dbias = None if bias is None else jnp.zeros_like(bias)  # bias is a mask
    return dq, dk, dv, dbias, dlbias, None  # seed: int, no cotangent


_flash.defvjp(_flash_fwd, _flash_bwd)


MAX_BLOCK = 512  # measured on v5e: 512-tiles run the fwd+bwd ~2.5x faster
#                  than 128-tiles at (16, 16, 1024, 64) — bigger tiles
#                  amortize grid overhead and keep the MXU busier, and a
#                  512x512 fp32 score tile + operands is still ~1.5 MB VMEM

MAX_BLOCK_NONCAUSAL = 1024  # v5e sweep at (16, 16, 1024, 64) fwd+bwd:
#                  non-causal 1024x1024 = 70.0 ms vs 512x512 = 74.6 ms
#                  (~6% — fewer grid steps, same VMEM class: 4 MB score
#                  tile).  CAUSAL at head_dim 64 stays at 512: the
#                  tile-skip guard works per-block, so 1024-tiles waste
#                  half of each diagonal block on masked work (74.5 ms vs
#                  71.0 at 512).  The learned-bias path caps block_q at
#                  512 but block_k at 1024 (71.1 ms vs 73.9 at 512x512):
#                  its backward carries the (1, H, Q, K) bias tile +
#                  dlbias accumulator on top of the plain path's scratch,
#                  and 1024x1024 overflows the 16 MB VMEM stack (measured
#                  18.07 MB on v5e).

MAX_BLOCK_CAUSAL_WIDE = 1024  # v5e sweep at the 7B regime (4/8, 32,
#                  1024, 128) fwd+bwd: causal 1024x1024 = 3.48/4.97 ms vs
#                  512x512 = 4.16/6.58 ms (batch 4/8) — at head_dim 128
#                  the wider tiles' extra MXU occupancy beats the diagonal
#                  blocks' masked-work waste that dominates at d=64, so
#                  the causal cap is head_dim-dependent.


def _block_caps(causal: bool, has_learned_bias: bool,
                head_dim: int = 64) -> tuple[int, int]:
    """(cap_q, cap_k) for the given attention flavor — see the constants'
    comments for the v5e measurements behind each choice.  The learned-
    bias cap applies even when causal: its backward's bias tile + dlbias
    accumulator overflow VMEM at 1024×1024 regardless of masking (and
    tiles only grow with head_dim)."""
    if has_learned_bias:
        return MAX_BLOCK, MAX_BLOCK_NONCAUSAL
    if causal:
        cap = MAX_BLOCK_CAUSAL_WIDE if head_dim >= 128 else MAX_BLOCK
        return cap, cap
    return MAX_BLOCK_NONCAUSAL, MAX_BLOCK_NONCAUSAL


def auto_block(seq_len: int, cap: int = MAX_BLOCK) -> int:
    """Default tile size when the caller doesn't pin one (0 = not tileable,
    callers fall back to XLA attention).

    Largest 16-aligned block in [128, cap] dividing ``seq_len`` — 16 is the
    bf16 sublane tiling (8 would satisfy fp32 only), and below 128 the
    kv×q grid overhead beats the XLA path the kernel replaces.  Sequences
    shorter than 128 use one seq-sized tile when 16-aligned."""
    if seq_len < 128:
        return seq_len if seq_len >= 16 and seq_len % 16 == 0 else 0
    start = min(cap, seq_len) // 16 * 16
    for b in range(start, 127, -16):
        if seq_len % b == 0:
            return b
    return 0


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    learned_bias: jnp.ndarray | None = None,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    dtype: jnp.dtype | None = None,
    dropout_rate: float = 0.0,
    dropout_seed: jax.Array | None = None,
    hw_rng: bool | None = None,
) -> jnp.ndarray:
    """Blockwise-softmax attention; drop-in for ``dot_product_attention``.

    ``block_q``/``block_k`` default to ``auto_block``: the largest
    16-aligned tile dividing each sequence length, capped per attention
    flavor (512 causal, 512/1024 learned-bias, 1024 otherwise — see
    ``_block_caps``; one seq-sized tile for short sequences).  Each seq
    len must divide by its (auto-clamped) block size — the framework's
    bucketed batching guarantees this for training shapes; call
    ``flash_supported`` first for arbitrary shapes.

    Contract notes (both enforced or documented because this is a public
    drop-in API, not just an internal kernel):

    - ``bias`` is treated as a CONSTANT mask: its gradient is zero.  Do not
      route a *learned* additive bias through it — that bias would silently
      stop training.  Learned biases go through ``learned_bias``.
    - ``learned_bias`` must be exactly (1, heads, q_len, kv_len) — T5's
      relative-position bias shape.  It is differentiable: the backward
      pass runs a third kernel that accumulates its gradient over the
      batch grid axis without materializing (B, H, Q, K) in HBM.
    - ``causal=True`` requires ``q_len == kv_len``.  The mask is top-left
      aligned (q_pos >= k_pos with no kv offset), which is only meaningful
      for square self-attention; decode-style bottom-right alignment with
      cached keys is the KV-cache path's job, not this kernel's.
    - ``dropout_rate`` > 0 applies attention-PROBS dropout inside the
      kernel: the keep-mask is drawn in-kernel from ``dropout_seed`` (an
      int32 scalar, e.g. ``ops.fused_dropout.seed_from_key``) — the
      (B, H, Q, K) mask never materializes in HBM and the backward
      recomputes it from the same seed instead of saving it.  ``hw_rng``
      picks the TPU hardware PRNG (default on compiled TPU) vs the
      portable counter hash (interpret mode / tests).
    """
    if causal and q.shape[2] != k.shape[2]:
        raise ValueError(
            f"causal=True requires square self-attention, got q_len={q.shape[2]} "
            f"!= kv_len={k.shape[2]} (the mask is top-left aligned; a causal "
            "prefix over cached keys needs the KV-cache path instead)"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    cap_q, cap_k = _block_caps(causal, learned_bias is not None, q.shape[-1])
    block_q = auto_block(q.shape[2], cap_q) if block_q is None else min(block_q, q.shape[2])
    block_k = auto_block(k.shape[2], cap_k) if block_k is None else min(block_k, k.shape[2])
    if (
        not block_q
        or not block_k
        or q.shape[2] % block_q
        or k.shape[2] % block_k
        or block_q % 8
        or block_k % 8
    ):
        raise ValueError(
            f"seq lens {q.shape[2]}/{k.shape[2]} not divisible into 8-aligned "
            f"blocks {block_q}/{block_k}"
        )
    if bias is not None:
        for i, (bd, full) in enumerate(
            zip(bias.shape, (q.shape[0], q.shape[1], q.shape[2], k.shape[2]))
        ):
            if bd not in (1, full):
                raise ValueError(f"bias dim {i} is {bd}, must be 1 or {full}")
    if learned_bias is not None:
        want = (1, q.shape[1], q.shape[2], k.shape[2])
        if tuple(learned_bias.shape) != want:
            raise ValueError(
                f"learned_bias shape {tuple(learned_bias.shape)} must be exactly "
                f"{want} (batch dim 1 is what the dbias kernel reduces over)"
            )
    if interpret is None:
        interpret = _default_interpret()
    if hw_rng is None:
        hw_rng = not interpret
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        if not dropout_rate < 1.0:
            raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires a dropout_seed scalar")
        dropout_seed = jnp.asarray(dropout_seed, jnp.int32).reshape(())
    else:
        dropout_seed = None
    out = _flash(q, k, v, bias, learned_bias, dropout_seed,
                 float(scale), bool(causal), int(block_q), int(block_k),
                 bool(interpret), dropout_rate, bool(hw_rng))
    return out if dtype is None else out.astype(dtype)


def flash_supported(q_len: int, kv_len: int, head_dim: int,
                    block_q: int | None = None, block_k: int | None = None,
                    *, causal: bool = False,
                    has_learned_bias: bool = False) -> bool:
    """True when shapes are flash-eligible (divisible seqs, sane head_dim).
    ``None`` blocks mirror ``flash_attention``'s ``auto_block`` defaults,
    including its per-path block caps (``_block_caps``) — pass ``causal``/
    ``has_learned_bias`` as the eventual kernel call will, or a length only
    tileable above 512 (e.g. 592 = 16*37) would be reported eligible for a
    path whose cap rejects it."""
    cap_q, cap_k = _block_caps(causal, has_learned_bias, head_dim)
    bq = auto_block(q_len, cap_q) if block_q is None else min(block_q, q_len)
    bk = auto_block(kv_len, cap_k) if block_k is None else min(block_k, kv_len)
    return (
        bq > 0
        and bk > 0
        and q_len % bq == 0
        and kv_len % bk == 0
        and bq % 8 == 0  # TPU sublane alignment
        and bk % 8 == 0
        and head_dim % 8 == 0
    )


# ------------------------------------------------------- decode variant
#
# The four kernels above are the TRAINING shapes: square (or prefill-
# rectangular) attention where q tiles stream against kv tiles and a
# backward pass exists.  Serving's hot op is different: ONE query row per
# sequence (the token being decoded) against a full-length cached K/V
# buffer of which only the first ``offset+1`` slots are live.  The decode
# kernel reuses the same online-softmax block machinery with three
# changes: the whole (tiny) q block rides every grid step, validity is a
# per-ROW length mask (k_pos <= offset[b] + q_row, bottom-right aligned —
# exactly the alignment the training kernel's top-left causal mask cannot
# express), and kv tiles entirely beyond the longest live row are SKIPPED
# via a dynamic pl.when, so a step early in the decode reads ~offset/L of
# the cache instead of all of it.  Inference only: no vjp.
#
# int8 KV (--kv-cache-dtype int8): the cache buffers arrive as s8 with
# per-head per-position f32 scales (quantize_kv below — THE owning
# quantize/dequantize implementation, guarded by repo_lint rule 10).
# The kernel dequantizes each (block_k, d) tile in VMEM right after the
# DMA, so HBM traffic and cache footprint are both s8 while the MXU math
# stays f32 — the XLA fallback path dequantizes through the identical
# dequantize_kv expression, which is what keeps the two paths
# token-comparable.


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-head per-position int8 quantization of a K/V tensor.

    ``x``: (..., len, head_dim) — one scale per (..., position): the
    head_dim row written at one cache slot shares one scale, so a cache
    write (one row per slot per step) quantizes independently of every
    other position and nothing ever needs requantizing.  Deterministic
    round-to-nearest (decode parity wants bit-stable values, not the
    unbiased stochastic rounding gradients need).  Returns ``(q, scale)``
    with ``q`` int8 shaped like ``x`` and ``scale`` f32 with the head_dim
    axis dropped."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of ``quantize_kv`` — the ONE dequantize expression both the
    Pallas decode kernel (per tile, in VMEM) and the XLA fallback path
    (whole buffer) evaluate, so their reconstructed K/V are identical."""
    return q.astype(jnp.float32) * scale[..., None]


def _decode_kernel(
    *refs, scale: float, block_k: int, nk: int, has_bias: bool,
    has_scales: bool = False,
):
    it = iter(refs)
    off_ref = next(it)  # SMEM (batch,) int32: absolute position of q row 0
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    ks_ref = next(it) if has_scales else None
    vs_ref = next(it) if has_scales else None
    bias_ref = next(it) if has_bias else None
    o_ref, m_scr, l_scr, acc_scr = it
    bi = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    offset = off_ref[bi]
    q_len = q_ref.shape[2]
    # every live position of this row's tile is <= offset + q_len - 1:
    # tiles past that contribute nothing — skip their DMA'd compute
    live = ki * block_k <= offset + q_len - 1

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]  # (q_len, d)
        k = k_ref[0, 0]  # (block_k, d) — s8 under int8 KV
        if ks_ref is not None:
            # dequantize the tile in VMEM: HBM moved 1 byte/elem, the MXU
            # sees f32 — same expression as dequantize_kv
            k = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        # bottom-right aligned length mask: q row r sits at absolute
        # position offset + r and may attend cache slots <= its own
        q_pos = offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, -jnp.inf)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        safe_m = jnp.where(m_next == -jnp.inf, 0.0, m_next)
        alpha = jnp.exp(m_prev - safe_m)
        p = jnp.exp(s - safe_m)
        l_scr[:] = jax.lax.broadcast_in_dim(
            (alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True))[:, 0],
            l_scr.shape, (0,),
        )
        m_scr[:] = jax.lax.broadcast_in_dim(m_next[:, 0], m_scr.shape, (0,))
        v = v_ref[0, 0]
        if vs_ref is not None:
            v = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def flash_decode(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    offsets: jnp.ndarray,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    dtype: jnp.dtype | None = None,
) -> jnp.ndarray:
    """Decode-step attention: a short q block against a cached K/V buffer.

    ``q``: (B, H, Q, d) with Q the decode step width (1 for token-by-token
    decode; beam batches flatten beams into B).  ``k``/``v``: (B, H, L, d)
    full-length cache buffers.  ``offsets``: (B,) int32 — the absolute
    cache position of each row's FIRST query; row r of the q block attends
    cache slots <= offsets[b] + r, so not-yet-written slots never
    contribute regardless of their (stale, reused) contents.  ``bias`` is
    a constant additive mask, every dim 1 or full — the padding mask /
    T5's decode-step relative-position bias.  ``k_scale``/``v_scale``
    ((B, H, L) f32, both or neither): the int8 KV cache's per-head
    per-position scales — ``k``/``v`` are then s8 and each kv tile is
    dequantized in VMEM after the DMA, so decode HBM traffic drops ~4×
    vs f32 buffers.  Inference only (no vjp); numerically identical to
    masked ``dot_product_attention`` on the same (dequantized) inputs
    (the parity tests pin greedy and beam decode against it).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    batch, heads, q_len, d = q.shape
    kv_len = k.shape[2]
    block_k = auto_block(kv_len) if block_k is None else min(block_k, kv_len)
    if not block_k or kv_len % block_k or block_k % 8:
        raise ValueError(
            f"kv_len {kv_len} not divisible into 8-aligned blocks ({block_k})"
        )
    if bias is not None:
        for i, (bd, full) in enumerate(
            zip(bias.shape, (batch, heads, q_len, kv_len))
        ):
            if bd not in (1, full):
                raise ValueError(f"bias dim {i} is {bd}, must be 1 or {full}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    has_scales = k_scale is not None
    if has_scales:
        want = (batch, heads, kv_len)
        for name, s in (("k_scale", k_scale), ("v_scale", v_scale)):
            if tuple(s.shape) != want:
                raise ValueError(f"{name} shape {tuple(s.shape)} != {want}")
    if interpret is None:
        interpret = _default_interpret()
    offsets = jnp.asarray(offsets, jnp.int32).reshape(batch)
    nk = kv_len // block_k
    grid = (batch, heads, nk)

    def q_map(b, h, ki):
        return (b, h, 0, 0)

    def kv_map(b, h, ki):
        return (b, h, ki, 0)

    def scale_map(b, h, ki):
        return (b, h, ki)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # offsets, whole array
        pl.BlockSpec((1, 1, q_len, d), q_map),
        pl.BlockSpec((1, 1, block_k, d), kv_map),
        pl.BlockSpec((1, 1, block_k, d), kv_map),
    ]
    if has_scales:
        in_specs += [
            pl.BlockSpec((1, 1, block_k), scale_map),
            pl.BlockSpec((1, 1, block_k), scale_map),
        ]
    if bias is not None:
        inner = _bias_spec(bias.shape, q_len, block_k)

        def bias_map(b, h, ki):
            return inner.index_map(b, h, 0, ki)

        in_specs.append(pl.BlockSpec(inner.block_shape, bias_map))
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=float(scale), block_k=block_k, nk=nk,
            has_bias=bias is not None, has_scales=has_scales,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, q_len, d), q_map),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((q_len, LANES), jnp.float32),
            pltpu.VMEM((q_len, LANES), jnp.float32),
            pltpu.VMEM((q_len, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(offsets, *[x for x in (q, k, v, k_scale, v_scale, bias) if x is not None])
    return out if dtype is None else out.astype(dtype)


# The decode kernel's q-block ceiling: plain decode steps are 1 row, and
# speculative verify (serving/spec.py) rides the SAME entry with a q block
# of spec_tokens + 1 rows — the per-row length masks already express the
# staggered offsets, so k drafts verify for about the price of one step.
# core.config.SPEC_MAX_DRAFT_TOKENS = this - 1 (the bonus row).
MAX_DECODE_Q_ROWS = 8


def flash_decode_supported(
    q_len: int, kv_len: int, head_dim: int, block_k: int | None = None
) -> bool:
    """True when a cached decode step is kernel-eligible: the cache length
    tiles into 8-aligned blocks, the head dim is lane-aligned, and the q
    block is small enough to live in scratch (plain decode steps are 1
    row, speculative verify up to ``MAX_DECODE_Q_ROWS`` — the cap keeps
    prefill-sized calls out)."""
    bk = auto_block(kv_len) if block_k is None else min(block_k, kv_len)
    return (
        0 < q_len <= MAX_DECODE_Q_ROWS
        and bk > 0
        and kv_len % bk == 0
        and bk % 8 == 0
        and head_dim % 8 == 0
    )


# ------------------------------------------------- paged decode variant
#
# The paged-cache twin of flash_decode (serving/cache_pool.py owns the
# pool/allocator; this kernel is the device half): K/V live in a SHARED
# block pool of (num_blocks, H, block_size, d) and each slot maps its
# logical kv tiles onto pool blocks through a per-slot block table.  The
# block size IS the kv tile size, so the kernel's tile loop indexes pool
# blocks directly — the block table rides scalar prefetch and the
# BlockSpec index maps read it, meaning the DMA fetches exactly the
# slot's blocks and a flat (slots, H, L, d) view never exists anywhere.
# A sentinel entry (>= num_blocks: an unallocated logical tile) clamps to
# a valid block for the DMA and is masked to -inf in-kernel, so whatever
# the clamped block holds contributes exactly nothing.


def _decode_paged_kernel(
    *refs, scale: float, block_k: int, nk: int, num_blocks: int,
    has_bias: bool, has_scales: bool,
):
    it = iter(refs)
    bt_ref, off_ref = next(it), next(it)  # scalar-prefetch: (B, nk), (B,)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    ks_ref = next(it) if has_scales else None
    vs_ref = next(it) if has_scales else None
    bias_ref = next(it) if has_bias else None
    o_ref, m_scr, l_scr, acc_scr = it
    bi = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    offset = off_ref[bi]
    q_len = q_ref.shape[2]
    # dead-tile skip as in _decode_kernel, plus: a sentinel block-table
    # entry is an unallocated tile — nothing of it may contribute
    allocated = bt_ref[bi, ki] < num_blocks
    live = jnp.logical_and(ki * block_k <= offset + q_len - 1, allocated)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]  # one pool block's head slice: (block_k, d)
        if ks_ref is not None:
            k = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale
        if bias_ref is not None:
            s += bias_ref[0, 0].astype(jnp.float32)
        q_pos = offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, -jnp.inf)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        safe_m = jnp.where(m_next == -jnp.inf, 0.0, m_next)
        alpha = jnp.exp(m_prev - safe_m)
        p = jnp.exp(s - safe_m)
        l_scr[:] = jax.lax.broadcast_in_dim(
            (alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True))[:, 0],
            l_scr.shape, (0,),
        )
        m_scr[:] = jax.lax.broadcast_in_dim(m_next[:, 0], m_scr.shape, (0,))
        v = v_ref[0, 0]
        if vs_ref is not None:
            v = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def flash_decode_paged(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    block_tables: jnp.ndarray,
    offsets: jnp.ndarray,
    k_scale_pool: jnp.ndarray | None = None,
    v_scale_pool: jnp.ndarray | None = None,
    scale: float | None = None,
    interpret: bool | None = None,
    dtype: jnp.dtype | None = None,
) -> jnp.ndarray:
    """Decode attention straight off a shared block pool.

    ``q``: (B, H, Q≤8, d).  ``k_pool``/``v_pool``: (num_blocks, H,
    block_size, d) — the pool; ``block_tables``: (B, n_tiles) int32
    mapping each row's logical tile to its pool block (entries >=
    num_blocks are unallocated tiles and contribute nothing);
    ``offsets``: (B,) as in ``flash_decode``.  The logical cache length
    is ``n_tiles × block_size`` and ``bias`` (1-or-full dims) is indexed
    in LOGICAL tile order.  ``k_scale_pool``/``v_scale_pool``
    ((num_blocks, H, block_size) f32) compose the int8 KV cache with
    paging.  Numerically identical to ``flash_decode`` over the
    flattened view of the same blocks."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    batch, heads, q_len, d = q.shape
    num_blocks, pool_heads, block_k, pool_d = k_pool.shape
    if pool_heads != heads or pool_d != d:
        raise ValueError(
            f"pool shape {k_pool.shape} does not match q heads/dim "
            f"({heads}, {d})"
        )
    n_tiles = block_tables.shape[1]
    kv_len = n_tiles * block_k
    if block_k % 8:
        raise ValueError(f"block_size {block_k} must be 8-aligned")
    if bias is not None:
        for i, (bd, full) in enumerate(
            zip(bias.shape, (batch, heads, q_len, kv_len))
        ):
            if bd not in (1, full):
                raise ValueError(f"bias dim {i} is {bd}, must be 1 or {full}")
    if (k_scale_pool is None) != (v_scale_pool is None):
        raise ValueError("k_scale_pool and v_scale_pool go together")
    has_scales = k_scale_pool is not None
    if interpret is None:
        interpret = _default_interpret()
    block_tables = jnp.asarray(block_tables, jnp.int32).reshape(batch, n_tiles)
    offsets = jnp.asarray(offsets, jnp.int32).reshape(batch)
    grid = (batch, heads, n_tiles)
    clamp = num_blocks - 1

    def q_map(b, h, ki, bt_ref, off_ref):
        return (b, h, 0, 0)

    def pool_map(b, h, ki, bt_ref, off_ref):
        # sentinel tiles clamp to a real block for the DMA; the kernel
        # masks them to -inf so the clamped contents never contribute
        return (jnp.minimum(bt_ref[b, ki], clamp), h, 0, 0)

    def pool_scale_map(b, h, ki, bt_ref, off_ref):
        return (jnp.minimum(bt_ref[b, ki], clamp), h, 0)

    in_specs = [
        pl.BlockSpec((1, 1, q_len, d), q_map),
        pl.BlockSpec((1, 1, block_k, d), pool_map),
        pl.BlockSpec((1, 1, block_k, d), pool_map),
    ]
    if has_scales:
        in_specs += [
            pl.BlockSpec((1, 1, block_k), pool_scale_map),
            pl.BlockSpec((1, 1, block_k), pool_scale_map),
        ]
    if bias is not None:
        inner = _bias_spec(bias.shape, q_len, block_k)

        def bias_map(b, h, ki, bt_ref, off_ref):
            return inner.index_map(b, h, 0, ki)

        in_specs.append(pl.BlockSpec(inner.block_shape, bias_map))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, q_len, d), lambda b, h, ki, bt_ref, off_ref: (b, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((q_len, LANES), jnp.float32),
            pltpu.VMEM((q_len, LANES), jnp.float32),
            pltpu.VMEM((q_len, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_paged_kernel, scale=float(scale), block_k=block_k,
            nk=n_tiles, num_blocks=num_blocks,
            has_bias=bias is not None, has_scales=has_scales,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        block_tables, offsets,
        *[
            x
            for x in (q, k_pool, v_pool, k_scale_pool, v_scale_pool, bias)
            if x is not None
        ],
    )
    return out if dtype is None else out.astype(dtype)


def flash_decode_run(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None,
    *,
    offsets: jnp.ndarray,
    mesh,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Run the decode kernel — directly on one device, per-shard under
    ``shard_map`` on a mesh (batch over data×fsdp×expert, heads over
    ``tensor``, mirroring ``ops.mha.flash_run``).  ``offsets`` shard with
    the batch rows; the int8 KV scales (``k_scale``/``v_scale``) shard
    exactly like the buffers they dequantize (batch × heads); the kernel
    body needs no collectives (decode never mixes rows or heads).  A bias
    carrying a HEAD dim must be full-size (it shards with the heads);
    batch dim 1-or-full as usual."""
    import math as _math

    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.parallel.activation import BATCH_AXES

    if mesh is None or _math.prod(mesh.devices.shape) == 1:
        return flash_decode(
            q, k, v, bias, offsets=offsets, k_scale=k_scale, v_scale=v_scale,
            scale=scale, dtype=dtype, interpret=interpret,
        )
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.shape)
    head_axis = "tensor" if "tensor" in mesh.shape else None
    qkv_spec = P(batch_axes or None, head_axis, None, None)
    scale_spec = P(batch_axes or None, head_axis, None)
    off_spec = P(batch_axes or None)
    has_scales = k_scale is not None

    def run(q, k, v, off, *rest):
        rest = list(rest)
        ks = vs = None
        if has_scales:
            ks, vs = rest.pop(0), rest.pop(0)
        return flash_decode(
            q, k, v, rest[0] if rest else None, offsets=off,
            k_scale=ks, v_scale=vs, scale=scale,
            dtype=dtype, interpret=interpret,
        )

    args = (q, k, v, jnp.asarray(offsets, jnp.int32).reshape(q.shape[0]))
    in_specs = (qkv_spec, qkv_spec, qkv_spec, off_spec)
    if has_scales:
        args = (*args, k_scale, v_scale)
        in_specs = (*in_specs, scale_spec, scale_spec)
    if bias is not None:
        bias_spec = P(
            (batch_axes or None) if bias.shape[0] != 1 else None,
            head_axis if bias.shape[1] != 1 else None,
            None,
            None,
        )
        args = (*args, bias)
        in_specs = (*in_specs, bias_spec)
    return compat_shard_map(
        run, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec, check_vma=False
    )(*args)


# --------------------------------------------- multi-device learned bias


def make_flash_lbias_sharded(
    mesh,
    *,
    batch_axes: tuple[str, ...],
    head_axis: str | None,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    has_bias: bool,
    out_dtype,
    dropout_rate: float = 0.0,
    hw_rng: bool = False,
):
    """Multi-device flash attention WITH a differentiable (1, H, Q, K)
    learned bias: per-shard Pallas kernels under ``shard_map`` (batch over
    ``batch_axes``, heads over ``head_axis``) and a HAND-WRITTEN vjp whose
    backward psums the per-batch-shard dbias partials inside the manual
    region.  The generic ``flash_run`` path can't do this: its shard_map
    runs ``check_vma=False``, under which autodiff would silently drop the
    cross-shard reduction a replicated input's cotangent needs — here the
    reduction is explicit, so T5's relative-position bias trains correctly
    on any mesh, not just a single chip.

    Returns ``f(q, k, v[, bias], lbias[, seed]) -> o``.  ``bias`` (present
    iff ``has_bias``) is a constant (b|1, 1, 1, K) mask; ``lbias`` is
    heads-sharded over ``head_axis`` and replicated across the batch
    shards.  ``seed`` (present iff ``dropout_rate > 0``) is the replicated
    int32 probs-dropout seed — each shard folds its axis indices in, so
    batch/head shards draw independent masks, and the per-shard backward
    redraws the identical mask from the same folded seed.
    """
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.ops.fused_dropout import _shard_seed

    has_dropout = dropout_rate > 0.0
    fold_axes = batch_axes + ((head_axis,) if head_axis else ())

    qkv_spec = P(batch_axes or None, head_axis, None, None)
    lb_spec = P(None, head_axis, None, None)
    lse_spec = P(batch_axes or None, head_axis, None, None)

    def mask_spec(b):
        return P(
            (batch_axes or None) if b.shape[0] != 1 else None,
            None, None, None,
        )

    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              interpret=interpret)

    def split(args):
        """(q, k, v[, bias], lbias[, seed]) → (q, k, v, bias|None, lbias,
        seed|None)."""
        args, seed = (args[:-1], args[-1]) if has_dropout else (args, None)
        if has_bias:
            q, k, v, bias, lbias = args
        else:
            (q, k, v, lbias), bias = args, None
        return q, k, v, bias, lbias, seed

    def drop_kw(seed):
        if seed is None:
            return {}
        return dict(
            dropout_rate=dropout_rate, hw_rng=hw_rng,
            dropout_seed=_shard_seed(seed, fold_axes) if fold_axes else seed,
        )

    def fwd_in_specs(bias):
        return tuple(
            s for s in (
                qkv_spec, qkv_spec, qkv_spec,
                mask_spec(bias) if has_bias else None, lb_spec,
                P() if has_dropout else None,
            ) if s is not None
        )

    def fwd_shard(*sargs):
        sq, sk, sv, sbias, slb, sseed = split(sargs)
        o, lse = _fwd(sq, sk, sv, sbias, slb, **kw, **drop_kw(sseed))
        return o, lse[..., :1]

    def run_fwd(args, bias):
        return compat_shard_map(
            fwd_shard, mesh=mesh, in_specs=fwd_in_specs(bias),
            out_specs=(qkv_spec, lse_spec), check_vma=False,
        )(*args)

    @jax.custom_vjp
    def f(*args):
        _, _, _, bias, _, _ = split(args)
        return run_fwd(args, bias)[0]

    def f_fwd(*args):
        q, k, v, bias, lbias, seed = split(args)
        o, lse1 = run_fwd(args, bias)
        return o, (q, k, v, bias, lbias, seed, o, lse1)

    def f_bwd(res, do):
        q, k, v, bias, lbias, seed, o, lse1 = res

        def bwd_shard(*sargs):
            sargs, sseed = (sargs[:-1], sargs[-1]) if has_dropout else (sargs, None)
            if has_bias:
                sq, sk, sv, sbias, slb, so, slse1, sdo = sargs
            else:
                (sq, sk, sv, slb, so, slse1, sdo), sbias = sargs, None
            lse = jax.lax.broadcast_in_dim(
                slse1[..., 0], (*slse1.shape[:-1], LANES), (0, 1, 2)
            )
            dq, dk, dv, dlb = _bwd(
                sq, sk, sv, sbias, slb, so, lse, sdo, **kw, **drop_kw(sseed)
            )
            # each batch shard computed dbias for ITS rows only: the
            # explicit cross-shard reduction autodiff can't insert here
            if batch_axes:
                dlb = jax.lax.psum(dlb, batch_axes)
            return dq, dk, dv, dlb

        base = fwd_in_specs(bias)
        if has_dropout:
            base = base[:-1]  # seed spec moves to the end (matches args)
        in_specs = (*base, qkv_spec, lse_spec, qkv_spec) + (
            (P(),) if has_dropout else ()
        )
        args = tuple(
            x for x in (q, k, v, bias, lbias, o, lse1, do) if x is not None
        ) + ((seed,) if has_dropout else ())
        dq, dk, dv, dlb = compat_shard_map(
            bwd_shard, mesh=mesh, in_specs=in_specs,
            out_specs=(qkv_spec, qkv_spec, qkv_spec, lb_spec), check_vma=False,
        )(*args)
        out = (dq, dk, dv)
        if has_bias:
            out = (*out, jnp.zeros_like(bias))
        out = (*out, dlb)
        if has_dropout:
            out = (*out, None)  # seed: int, no cotangent
        return out

    f.defvjp(f_fwd, f_bwd)
    return lambda *args: f(*args).astype(out_dtype)


def flash_attention_lbias_sharded(
    q, k, v, bias, learned_bias, *, mesh,
    batch_axes: tuple[str, ...], head_axis: str | None,
    causal: bool = False, scale: float | None = None,
    block_q: int | None = None, block_k: int | None = None,
    interpret: bool | None = None, dtype=None,
    dropout_rate: float = 0.0, dropout_seed=None, hw_rng: bool | None = None,
):
    """Front door for the multi-device learned-bias path (see
    ``make_flash_lbias_sharded``).  Same shape/validation contract as
    ``flash_attention``; block sizes are the per-shard auto defaults
    (q and the learned bias's Q dim are full-length per shard — only batch
    and heads split).  The mask additionally must not carry a HEAD dim
    (the per-shard BlockSpec would index the wrong heads on non-first
    tensor shards); a full query dim — a (B, 1, Q, K) mask — is fine, since
    Q/K are unsharded here."""
    if causal and q.shape[2] != k.shape[2]:
        raise ValueError(
            f"causal=True requires square self-attention, got q_len={q.shape[2]} "
            f"!= kv_len={k.shape[2]}"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    cap_q, cap_k = _block_caps(bool(causal), True, q.shape[-1])
    block_q = auto_block(q.shape[2], cap_q) if block_q is None else min(block_q, q.shape[2])
    block_k = auto_block(k.shape[2], cap_k) if block_k is None else min(block_k, k.shape[2])
    if (
        not block_q or not block_k
        or q.shape[2] % block_q or k.shape[2] % block_k
        or block_q % 8 or block_k % 8
    ):
        raise ValueError(
            f"seq lens {q.shape[2]}/{k.shape[2]} not divisible into 8-aligned "
            f"blocks {block_q}/{block_k}"
        )
    if bias is not None:
        for i, (bd, full) in enumerate(
            zip(bias.shape, (q.shape[0], 1, q.shape[2], k.shape[2]))
        ):
            if bd not in (1, full):
                raise ValueError(
                    f"bias dim {i} is {bd}, must be 1 or {full} (the head dim "
                    "must be 1 on the sharded learned-bias path)"
                )
    want = (1, q.shape[1], q.shape[2], k.shape[2])
    if tuple(learned_bias.shape) != want:
        raise ValueError(f"learned_bias shape {tuple(learned_bias.shape)} != {want}")
    if interpret is None:
        interpret = _default_interpret()
    if hw_rng is None:
        hw_rng = not interpret
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        if not dropout_rate < 1.0:
            raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires a dropout_seed scalar")
    f = make_flash_lbias_sharded(
        mesh, batch_axes=batch_axes, head_axis=head_axis, causal=bool(causal),
        scale=float(scale), block_q=int(block_q), block_k=int(block_k),
        interpret=bool(interpret), has_bias=bias is not None,
        out_dtype=dtype or q.dtype,
        dropout_rate=dropout_rate, hw_rng=bool(hw_rng),
    )
    args = (q, k, v, bias, learned_bias) if bias is not None else (q, k, v, learned_bias)
    if dropout_rate > 0.0:
        args = (*args, jnp.asarray(dropout_seed, jnp.int32).reshape(()))
    return f(*args)
