"""Mixture-of-experts MLP with expert parallelism (Mixtral-style).

The reference has no MoE (SURVEY.md §2: expert parallel "out of scope");
this module goes past parity so the LLaMA family extends to
Mixtral-class sparse models.  TPU-first design choices:

- **Dense dispatch in fixed-size groups** (GShard/Switch formulation):
  tokens are routed within groups of ``group_size``, and routing is
  expressed as two einsums against a (group, tokens, experts, capacity)
  one-hot dispatch tensor — the whole layer is static-shaped matmuls the
  MXU executes and XLA can partition; no ragged gather/scatter, no
  data-dependent shapes, and activation memory linear in sequence length
  (per-group dispatch is O(group_size²·K/E), ~167 MB fp32 at the 4096
  default with E=8/K=2).  Tokens over an expert's per-group capacity are
  dropped (their output is 0; the block's residual connection carries
  them through), the standard capacity-factor trade.
- **Expert parallelism via GSPMD**: the stacked expert weights
  (E, d_in, d_out) shard their leading dim over the ``tensor`` mesh axis
  (see ``parallel/sharding.py`` EXPERT rules), and the expert-major
  activations (G, E, capacity, d) are constrained to the same axis — the
  partitioner then lowers the dispatch/combine einsums to the expert
  all-to-all over ICI, with zero hand-written collectives.
- **Router in fp32** — softmax over experts is precision-sensitive, the
  same policy as attention softmax (core/precision.py).
- The load-balancing auxiliary loss (E · Σ_e fraction_e · prob_e with
  all top-k assignments in the fraction — HF Mixtral's
  ``load_balancing_loss_func``, = top_k at uniform routing) is ``sow``-n
  into the ``losses`` collection; the train step adds it when
  ``config.moe_aux_weight > 0`` and generation (which never mutates
  ``losses``) silently discards it.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.parallel.activation import constrain


def _expert_spec():
    """(groups, experts, capacity, d_model) — experts over ``expert``."""
    from jax.sharding import PartitionSpec as P

    return P(None, "expert")


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU experts; drop-in for a dense gated MLP.

    Shapes: E experts, each a SwiGLU of (d_model → ff → d_model) with
    stacked weights (E, ...).  ``capacity_factor`` scales each expert's
    token budget: capacity = ceil(top_k · N / E · factor).
    """

    num_experts: int
    intermediate_size: int
    top_k: int = 2
    capacity_factor: float = 1.25
    # routing group size (GShard): tokens are routed within fixed-size
    # groups, so the (group, E, capacity) dispatch tensors stay
    # O(group_size²) per group and total activation memory is LINEAR in
    # sequence length — without grouping the dense dispatch is quadratic
    # and cannot fit 32k-context mixtral-8x7b on a 16 GB chip
    group_size: int = 4096
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, no_drop: bool = False) -> jnp.ndarray:
        """``no_drop=True`` (cached decode/prefill) sizes capacity so NO
        token can overflow (capacity = group size).  ``capacity_factor <= 0``
        makes the layer no-drop on EVERY path, including teacher-forced
        scoring and fine-tuning — HF Mixtral routes densely with no
        capacity limit, so converted checkpoints load with that setting
        (registry) to reproduce HF logits exactly everywhere, at the price
        of a larger dispatch tensor."""
        b, s, d = x.shape
        E, K = self.num_experts, self.top_k
        n = b * s
        g = min(self.group_size, n)
        G = -(-n // g)  # ceil
        n_pad = G * g - n
        tokens = x.reshape(n, d)
        if n_pad:
            tokens = jnp.pad(tokens, ((0, n_pad), (0, 0)))
        tokens = tokens.reshape(G, g, d)
        # pad tokens are excluded from routing (they claim no capacity)
        valid = (jnp.arange(G * g) < n).astype(jnp.float32).reshape(G, g)
        no_drop = no_drop or self.capacity_factor <= 0
        capacity = g if no_drop else max(1, math.ceil(K * g / E * self.capacity_factor))

        router = nn.Dense(E, use_bias=False, dtype=jnp.float32, name="router")
        logits = router(tokens.astype(jnp.float32))  # (G, g, E), fp32
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k selection; Mixtral renormalizes the chosen gates to sum 1
        gate_vals, expert_idx = jax.lax.top_k(probs, K)  # (G, g, K)
        if K > 1:
            gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

        # position-in-expert via in-group cumsum, k-th choices queue behind
        # (k-1)-th; tokens past an expert's capacity are dropped
        dispatch = jnp.zeros((G, g, E, capacity), jnp.float32)
        combine = jnp.zeros((G, g, E, capacity), jnp.float32)
        counts = jnp.zeros((G, E), jnp.float32)
        for k in range(K):
            mask_k = jax.nn.one_hot(expert_idx[..., k], E, dtype=jnp.float32)
            mask_k = mask_k * valid[..., None]  # (G, g, E)
            pos_k = jnp.cumsum(mask_k, axis=1) - mask_k + counts[:, None, :]
            counts = counts + jnp.sum(mask_k, axis=1)
            mask_k = mask_k * (pos_k < capacity)
            slot = jax.nn.one_hot(
                jnp.sum(pos_k * mask_k, axis=-1).astype(jnp.int32), capacity, dtype=jnp.float32
            )  # (G, g, cap)
            disp_k = mask_k[..., None] * slot[..., None, :]  # (G, g, E, cap)
            dispatch = dispatch + disp_k
            combine = combine + gate_vals[..., k, None, None] * disp_k

        # Load-balance loss over REAL tokens: E * Σ_e fraction_e ·
        # mean-prob_e, where the fraction counts ALL top-k assignments
        # (pre-capacity) — exactly HF Mixtral's load_balancing_loss_func,
        # so a converted checkpoint's router_aux_loss_coef is directly
        # comparable.  Value is top_k at uniform routing (1.0 for top-1,
        # the Switch special case).
        n_real = jnp.maximum(jnp.sum(valid), 1.0)
        # ``counts`` already accumulated Σ_k Σ_tokens of the PRE-capacity
        # (valid-masked) assignment one-hots in the dispatch loop — reuse
        # it instead of materializing a (G, g, K, E) one-hot again
        frac = jnp.sum(counts, axis=0) / n_real  # sums to top_k
        mean_prob = jnp.sum(probs * valid[..., None], axis=(0, 1)) / n_real
        aux = E * jnp.sum(frac * mean_prob)
        self.sow(
            "losses", "moe_aux", aux,
            reduce_fn=lambda a, b: a + b,
            init_fn=lambda: jnp.zeros((), jnp.float32),
        )

        # dispatch → per-expert per-group batches, batched SwiGLU on the
        # MXU (experts broadcast over groups), combine
        expert_in = jnp.einsum("Gnec,Gnd->Gecd", dispatch.astype(self.dtype), tokens)
        expert_in = constrain(expert_in, _expert_spec())
        w_gate = self.param(
            "gate_proj", nn.initializers.lecun_normal(), (E, d, self.intermediate_size)
        ).astype(self.dtype)
        w_up = self.param(
            "up_proj", nn.initializers.lecun_normal(), (E, d, self.intermediate_size)
        ).astype(self.dtype)
        w_down = self.param(
            "down_proj", nn.initializers.lecun_normal(), (E, self.intermediate_size, d)
        ).astype(self.dtype)
        h = nn.silu(jnp.einsum("Gecd,edf->Gecf", expert_in, w_gate))
        h = h * jnp.einsum("Gecd,edf->Gecf", expert_in, w_up)
        expert_out = jnp.einsum("Gecf,efd->Gecd", h, w_down)
        expert_out = constrain(expert_out, _expert_spec())
        out = jnp.einsum("Gnec,Gecd->Gnd", combine.astype(self.dtype), expert_out)
        out = out.reshape(G * g, d)
        if n_pad:
            out = out[:n]
        return out.reshape(b, s, d)
