"""Normalization layers.

The compute dtype discipline matters on TPU: statistics are accumulated in
float32 even when activations are bf16, then the result is cast back —
matching what XLA's fused layernorm does and avoiding bf16 variance
underflow.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class RMSNorm(nn.Module):
    """T5/LLaMA-style RMS normalization: no mean subtraction, no bias."""

    epsilon: float = 1e-6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        return (y * scale).astype(self.dtype)


class LayerNorm(nn.Module):
    """Standard layernorm (BART-style: with bias), fp32 statistics."""

    epsilon: float = 1e-5
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + self.epsilon))
        return (y * scale + bias).astype(self.dtype)
