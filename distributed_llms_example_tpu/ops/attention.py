"""Attention core shared by all model families.

Replaces what the reference consumes as opaque CUDA/cuDNN kernels inside
``model(**batch)`` (reference train-accelerator.py:220) with an explicit,
TPU-shaped computation: one batched einsum onto the MXU for QK^T, fp32
softmax, one einsum for the value contraction.  XLA fuses mask/bias/softmax
into the surrounding matmuls; a Pallas flash-attention kernel
(``ops/flash_attention.py``) is used for long sequences where materializing
the (S, S) score matrix would be HBM-bound.

Conventions: q/k/v are (batch, heads, q_len/kv_len, head_dim); ``bias`` is
additive, broadcastable to (batch, heads, q_len, kv_len) and already
encodes masking as large negative values.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e9  # large-negative mask value; safe in both fp32 and bf16


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
) -> jnp.ndarray:
    """Plain softmax attention.

    ``scale=None`` means 1/sqrt(head_dim); pass ``scale=1.0`` for T5, which
    folds the scale into initialization and does NOT scale scores.
    Softmax runs in float32 regardless of compute dtype.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    dtype = dtype or q.dtype
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(dtype), v)


def make_causal_bias(q_len: int, kv_len: int, offset: int = 0) -> jnp.ndarray:
    """(1, 1, q_len, kv_len) additive causal mask; ``offset`` is the absolute
    position of query 0 (for incremental decoding with a KV cache)."""
    q_pos = jnp.arange(q_len)[:, None] + offset
    kv_pos = jnp.arange(kv_len)[None, :]
    mask = q_pos >= kv_pos
    return jnp.where(mask, 0.0, NEG_INF)[None, None, :, :]


def mask_to_bias(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """(batch, kv_len) {0,1} padding mask → (batch, 1, 1, kv_len) additive bias."""
    return jnp.where(attention_mask[:, None, None, :] > 0, 0.0, NEG_INF)
