"""Attention core shared by all model families.

Replaces what the reference consumes as opaque CUDA/cuDNN kernels inside
``model(**batch)`` (reference train-accelerator.py:220) with an explicit,
TPU-shaped computation: one batched einsum onto the MXU for QK^T, fp32
softmax, one einsum for the value contraction.  XLA fuses mask/bias/softmax
into the surrounding matmuls; a Pallas flash-attention kernel
(``ops/flash_attention.py``) is used for long sequences where materializing
the (S, S) score matrix would be HBM-bound.

Conventions: q/k/v are (batch, heads, q_len/kv_len, head_dim); ``bias`` is
additive, broadcastable to (batch, heads, q_len, kv_len) and already
encodes masking as large negative values.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e9  # large-negative mask value; safe in both fp32 and bf16


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jnp.ndarray:
    """Plain softmax attention.

    ``scale=None`` means 1/sqrt(head_dim); pass ``scale=1.0`` for T5, which
    folds the scale into initialization and does NOT scale scores.
    Softmax runs in float32 regardless of compute dtype.

    ``dropout_rate`` > 0 (with a ``dropout_rng`` key) applies inverted
    dropout to the attention probs — the XLA reference semantics for the
    flash kernel's in-kernel probs dropout.  This path DOES materialize
    the (B, H, Q, K) mask (that is exactly the cost the fused kernel
    removes); it exists for parity and for shapes the kernel rejects.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    dtype = dtype or q.dtype
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    if dropout_rate > 0.0 and dropout_rng is not None:
        import jax

        keep_prob = 1.0 - dropout_rate
        keep = jax.random.bernoulli(dropout_rng, keep_prob, probs.shape)
        probs = jnp.where(keep, probs / keep_prob, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(dtype), v)


def grouped_dot_product_attention(
    q5: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
) -> jnp.ndarray:
    """``dot_product_attention`` with a beam/group dim folded next to heads.

    ``q5``: (B, G, H, Q, d) attends SHARED ``k``/``v``: (B, H, K, d) —
    the einsum contracts without materializing the (B·G, H, K, d) repeat,
    so K/V stream from HBM once per row instead of once per beam copy
    (the dominant decode-step traffic for seq2seq generation, where every
    beam of a row shares the encoder's cross K/V).  Same math per element
    as ``dot_product_attention`` on repeated K/V: fp32 scores/softmax,
    identical scale/bias conventions; ``bias`` is (B|1, 1|H, Q, K) —
    per-row, like K/V, never per-beam (beams of a row share the mask)."""
    if scale is None:
        scale = q5.shape[-1] ** -0.5
    dtype = dtype or q5.dtype
    scores = jnp.einsum("bghqd,bhkd->bghqk", q5, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)[:, None]
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    return jnp.einsum("bghqk,bhkd->bghqd", probs.astype(dtype), v)


def beam_grouped_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    *,
    scale: float | None = None,
    dtype: jnp.dtype | None = None,
    learned_bias: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Beam-decode front end for ``grouped_dot_product_attention``: the ONE
    home for the fold/slice/unfold convention both attention modules use.

    ``q``: (B·G, H, Q, d) flattened beam batch; ``k``/``v``: (B, H, K, d)
    shared per row.  A per-beam ``bias`` (leading dim B·G) is stride-
    sliced to one row per group (beams of a row share their mask);
    ``learned_bias`` (1, H, Q, K) adds on top.  Returns (B·G, H, Q, d)."""
    B = k.shape[0]
    G = q.shape[0] // B
    H, Q, d = q.shape[1], q.shape[2], q.shape[3]
    bb = None
    if bias is not None:
        bb = bias if bias.shape[0] in (1, B) else bias[::G]
    if learned_bias is not None:
        bb = learned_bias if bb is None else bb + learned_bias
    out = grouped_dot_product_attention(
        q.reshape(B, G, H, Q, d), k, v, bb, scale=scale, dtype=dtype
    )
    return out.reshape(B * G, H, Q, d)


def make_causal_bias(q_len: int, kv_len: int, offset: int = 0) -> jnp.ndarray:
    """(1, 1, q_len, kv_len) additive causal mask; ``offset`` is the absolute
    position of query 0 (for incremental decoding with a KV cache)."""
    q_pos = jnp.arange(q_len)[:, None] + offset
    kv_pos = jnp.arange(kv_len)[None, :]
    mask = q_pos >= kv_pos
    return jnp.where(mask, 0.0, NEG_INF)[None, None, :, :]


def mask_to_bias(attention_mask: jnp.ndarray) -> jnp.ndarray:
    """(batch, kv_len) {0,1} padding mask → (batch, 1, 1, kv_len) additive bias."""
    return jnp.where(attention_mask[:, None, None, :] > 0, 0.0, NEG_INF)
