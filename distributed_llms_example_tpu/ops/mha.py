"""Shared multi-head attention module (BART/LLaMA families).

One module covers: scaled dot-product attention with optional biases in the
projections, causal masking, fixed-shape KV caching for autoregressive
decode, rotary position embeddings (LLaMA), and grouped-query attention
(fewer KV heads than Q heads).  T5 keeps its own attention (unscaled
scores + relative position bias are peculiar to it).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributed_llms_example_tpu.parallel.activation import compat_shard_map

from distributed_llms_example_tpu.ops.attention import (
    NEG_INF,
    beam_grouped_attention,
    dot_product_attention,
    make_causal_bias,
)
from distributed_llms_example_tpu.ops.flash_attention import (
    flash_attention,
    flash_decode_run,
    flash_decode_supported,
    flash_supported,
)
from distributed_llms_example_tpu.ops.ring_attention import ring_attention, ring_attention_sharded
from distributed_llms_example_tpu.parallel.activation import (
    BATCH_AXES,
    current_manual_seq,
    current_mesh,
)
from distributed_llms_example_tpu.utils.jsonlog import log_json

_IMPL_LOGGED: set[tuple] = set()


def _log_impl_once(impl: str, reason: str) -> None:
    """One-time JSON line saying which attention path a module selected —
    so "flash is wired in" claims are verifiable from any run log."""
    key = (impl, reason)
    if key not in _IMPL_LOGGED:
        _IMPL_LOGGED.add(key)
        log_json({"event": "attention_impl", "impl": impl, "reason": reason})


def _mesh_batch_shards(mesh: Mesh) -> int:
    return math.prod(mesh.shape.get(a, 1) for a in BATCH_AXES)


def _uneven_split_blocker(mesh: Mesh, *, heads: int, batch: int) -> str | None:
    """Both shard_map paths (flash per-shard, ring) need batch and heads to
    split evenly over (data×fsdp) and ``tensor``; None when they do."""
    tensor = mesh.shape.get("tensor", 1)
    shards = _mesh_batch_shards(mesh)
    if heads % tensor or batch % shards:
        return (
            f"uneven split: heads={heads} over tensor={tensor}, "
            f"batch={batch} over {shards} data/fsdp shards"
        )
    return None


def select_attention_impl(
    attention_impl: str,
    *,
    batch: int,
    heads: int,
    head_dim: int,
    q_len: int,
    kv_len: int,
    use_cache: bool,
    mesh: Mesh | None,
    backend: str,
    device_count: int,
    causal: bool = False,
    bias_kv_only: bool | None = None,
    has_learned_bias: bool = False,
) -> tuple[str, str]:
    """(impl, reason) — pure selection logic, unit-testable without TPUs.

    ``auto`` picks, in priority order: **ring attention** when the mesh has
    a ``sequence`` axis of size > 1 and the shapes split evenly over it
    (sequence/context parallelism — the Pallas/XLA single-shard paths
    would force GSPMD to all-gather the sequence); the **Pallas flash
    kernel** on TPU for non-trivial score matrices — under a multi-device
    mesh it additionally requires the batch and head counts to split
    evenly over the (data×fsdp) and ``tensor`` axes, because multi-device
    flash runs per-shard under ``shard_map`` (an opaque pallas call can't
    be partitioned by GSPMD itself); **XLA attention** otherwise.

    ``bias_kv_only``: None = no bias, True = (b|1, 1, 1, K) padding-style
    bias (the only form the ring can rotate), False = anything wider.
    """
    if attention_impl not in ("auto", "flash", "ring", "xla"):
        raise ValueError(
            f"attention_impl={attention_impl!r}: must be 'auto', 'flash', 'ring', or 'xla'"
        )
    if attention_impl == "xla":
        return "xla", "forced"
    if use_cache:
        return "xla", "kv-cache decode step"
    seq_shards = mesh.shape.get("sequence", 1) if mesh is not None else 1
    if attention_impl == "ring" or (attention_impl == "auto" and seq_shards > 1):
        why = _ring_blocker(
            seq_shards, batch=batch, heads=heads, q_len=q_len, kv_len=kv_len,
            causal=causal, bias_kv_only=bias_kv_only, mesh=mesh,
        )
        if why is None:
            return "ring", ("forced" if attention_impl == "ring" else "auto: sequence-parallel mesh")
        if attention_impl == "ring":
            if mesh is None:
                # not a config error: module init and other traces outside a
                # mesh context legitimately can't ring — fall back quietly
                # so a forced-ring training run can still initialize
                return "xla", f"ring requested but {why}"
            raise ValueError(f"attention_impl='ring' but {why}")
        # a sequence-sharded mesh where ring can't run: XLA attention is
        # correct (GSPMD gathers the sequence) but loses the SP memory win
        return "xla", f"sequence axis present but {why}"
    if not flash_supported(
        q_len, kv_len, head_dim, causal=causal, has_learned_bias=has_learned_bias
    ):
        # 'flash' means "wherever eligible": single-token decode steps and
        # other non-tileable shapes silently use the XLA path
        return "xla", f"shape not tileable (q={q_len}, kv={kv_len}, d={head_dim})"
    multi_device = device_count > 1
    if multi_device:
        if mesh is None:
            return "xla", "multi-device jit without a mesh context"
        why = _uneven_split_blocker(mesh, heads=heads, batch=batch)
        if why is not None:
            return "xla", why
    if attention_impl == "flash":
        return "flash", "forced"
    if backend != "tpu":
        return "xla", f"auto: backend={backend} (interpreted kernel is pure overhead)"
    if q_len * kv_len < 128 * 128:
        return "xla", "auto: score matrix too small to tile"
    return "flash", "auto: TPU" + (" (shard_map per-shard)" if multi_device else "")


def select_decode_impl(
    attention_impl: str,
    *,
    batch: int,
    heads: int,
    head_dim: int,
    q_len: int,
    kv_len: int,
    mesh: Mesh | None,
    backend: str,
    device_count: int,
) -> tuple[str, str]:
    """(impl, reason) for a CACHED decode step — the serving twin of
    ``select_attention_impl``, pure and unit-testable.

    ``auto`` picks the Pallas **decode kernel** (``flash_decode``: one
    short q block — a single decode row, or the speculative verify's
    k+1 rows — against the cached K/V buffer, per-row length mask,
    dead-tile skip) on TPU when the cache length tiles and — under a
    multi-device mesh — batch/heads split evenly over (data×fsdp) and
    ``tensor`` (the kernel runs per-shard under ``shard_map``, like
    training flash).  ``flash`` forces the kernel wherever eligible; XLA
    attention (per-row masked ``dot_product_attention``) otherwise.
    ``ring`` has no KV-cache path and falls back to XLA."""
    if attention_impl not in ("auto", "flash", "ring", "xla"):
        raise ValueError(
            f"attention_impl={attention_impl!r}: must be 'auto', 'flash', 'ring', or 'xla'"
        )
    if attention_impl == "xla":
        return "xla", "forced"
    if attention_impl == "ring":
        return "xla", "ring attention has no KV-cache decode path"
    if not flash_decode_supported(q_len, kv_len, head_dim):
        return "xla", (
            f"decode shape not tileable (q={q_len}, kv={kv_len}, d={head_dim})"
        )
    if device_count > 1:
        if mesh is None:
            return "xla", "multi-device jit without a mesh context"
        why = _uneven_split_blocker(mesh, heads=heads, batch=batch)
        if why is not None:
            return "xla", why
    if attention_impl == "flash":
        return "flash_decode", "forced"
    if backend != "tpu":
        return "xla", f"auto: backend={backend} (interpreted kernel is pure overhead)"
    if kv_len < 128:
        return "xla", "auto: cache too short to tile"
    return "flash_decode", "auto: TPU decode" + (
        " (shard_map per-shard)" if device_count > 1 else ""
    )


def decode_step_bias(offsets: jnp.ndarray, q_len: int, kv_len: int) -> jnp.ndarray:
    """(B, 1, q_len, kv_len) additive validity+causality mask for a cached
    decode step: q row r (absolute position ``offsets[b] + r``) attends
    cache slots <= its own position — the XLA reference semantics for the
    decode kernel's in-kernel length mask, per-row so continuous-batching
    slots at different offsets share one program."""
    k_pos = jnp.arange(kv_len)[None, None, None, :]
    q_pos = offsets[:, None, None, None] + jnp.arange(q_len)[None, None, :, None]
    return jnp.where(k_pos <= q_pos, 0.0, NEG_INF)


def _ring_blocker(
    seq_shards: int,
    *,
    batch: int,
    heads: int,
    q_len: int,
    kv_len: int,
    causal: bool,
    bias_kv_only: bool | None,
    mesh: Mesh | None,
) -> str | None:
    """None if ring attention can run, else a human-readable blocker."""
    if mesh is None:
        return "no mesh context"
    if seq_shards <= 1:
        return "mesh has no sequence axis > 1"
    if q_len % seq_shards or kv_len % seq_shards:
        return f"q_len={q_len}/kv_len={kv_len} not divisible by sequence={seq_shards}"
    if causal and q_len != kv_len:
        return f"causal ring needs square attention, got q={q_len} kv={kv_len}"
    if bias_kv_only is False:
        return "bias is not K-only (ring rotates only (b,1,1,K) biases)"
    return _uneven_split_blocker(mesh, heads=heads, batch=batch)


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float = 10000.0) -> tuple:
    """(..., head_dim) cos/sin tables for the given integer positions, in the
    HF half-rotation layout (freqs repeated, not interleaved)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., head_dim/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: (batch, heads, seq, head_dim); cos/sin: (seq, head_dim) or
    broadcastable."""
    half = x.shape[-1] // 2
    rotated = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
    return (x * cos + rotated * sin).astype(x.dtype)


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    model_dim: int
    num_kv_heads: int | None = None  # None → == num_heads
    use_bias: bool = True
    causal: bool = False
    use_rope: bool = False
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.float32
    # "auto": ring attention on sequence-parallel meshes, Pallas flash
    # attention on TPU for flash-eligible shapes, XLA attention otherwise;
    # "ring"/"flash"/"xla" force a path.  The causal mask is applied inside
    # this module (natively by the flash/ring kernels), so callers pass
    # only padding/cross-attention biases.
    attention_impl: str = "auto"
    # attention-PROBS dropout (HF ``attention_dropout``); active only with
    # ``deterministic=False`` and a "dropout" rng.  On the flash path the
    # keep-mask is drawn in-kernel from a folded seed — the (B, H, S, S)
    # mask never materializes in HBM (ops/flash_attention.py); the XLA
    # path applies the reference bernoulli mask to the probs.
    probs_dropout_rate: float = 0.0

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads if self.num_kv_heads is not None else self.num_heads

    def setup(self) -> None:
        inner_q = self.num_heads * self.head_dim
        inner_kv = self.kv_heads * self.head_dim
        mk = lambda feats, name: nn.Dense(feats, use_bias=self.use_bias, dtype=self.dtype, name=name)  # noqa: E731
        self.q_proj = mk(inner_q, "q_proj")
        self.k_proj = mk(inner_kv, "k_proj")
        self.v_proj = mk(inner_kv, "v_proj")
        self.o_proj = mk(self.model_dim, "o_proj")

    def _split(self, x: jnp.ndarray, heads: int) -> jnp.ndarray:
        b, s, _ = x.shape
        return x.reshape(b, s, heads, self.head_dim).transpose(0, 2, 1, 3)

    def project_kv(self, kv_hidden: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
        """K/V projections alone, as ``__call__`` would compute them —
        (B, kv_heads, S, head_dim) each.  Generation precomputes these ONCE
        per sequence for cross-attention (the encoder output is fixed for
        the whole decode) and feeds them back via ``cross_kv``; without
        this, every decode step re-projects the full encoder output
        through k/v_proj — 2·S·d_model² FLOPs per layer per token, ~100×
        the rest of the step for src 1024 summarization."""
        return (
            self._split(self.k_proj(kv_hidden), self.kv_heads),
            self._split(self.v_proj(kv_hidden), self.kv_heads),
        )

    @nn.compact
    def _cache_kv(self, key: jnp.ndarray, value: jnp.ndarray,
                  cache_positions: jnp.ndarray | None = None):
        """Append this step's k/v into the cache.

        ``cache_positions`` (B,) int32 switches to PER-ROW writes — each
        row lands at its own cache slot, the continuous-batching contract
        where every serving slot sits at a different decode offset
        (``mode="drop"`` makes an out-of-range position a no-op, which is
        how idle slots park).  q_len may exceed 1: row b's queries write
        the contiguous span ``cache_positions[b] + [0, q_len)`` — the
        warm-admission contract, where each slot ingests its uncached
        prompt tail at its own start offset.  Without ``cache_positions``
        the whole batch writes at the shared ``cache_index`` (the
        static-batch generation loops).

        Under ``kv_cache_context("int8")`` the buffers are s8 with
        per-head per-position f32 ``key_scale``/``value_scale`` leaves
        (``ops.flash_attention.quantize_kv`` — the owning quantize
        implementation): each write quantizes its own rows, so nothing
        ever requantizes.  Returns ``(k, v, k_scale, v_scale, idx)``;
        scales are None on the f32 path."""
        from distributed_llms_example_tpu.ops.flash_attention import quantize_kv
        from distributed_llms_example_tpu.parallel.activation import (
            current_kv_cache_dtype,
        )

        int8_kv = current_kv_cache_dtype() == "int8"
        store_dtype = jnp.int8 if int8_kv else key.dtype
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros, key.shape, store_dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros, value.shape, store_dtype)
        if int8_kv:
            k_scale = self.variable(
                "cache", "key_scale", jnp.zeros, key.shape[:3], jnp.float32
            )
            v_scale = self.variable(
                "cache", "value_scale", jnp.zeros, value.shape[:3], jnp.float32
            )
        cache_index = self.variable("cache", "cache_index", lambda: jnp.array(0, dtype=jnp.int32))
        idx = cache_index.value
        if is_initialized:
            if int8_kv:
                key, ks_new = quantize_kv(key)
                value, vs_new = quantize_kv(value)
            if cache_positions is not None:
                b = jnp.arange(key.shape[0])
                if key.shape[2] == 1:
                    k = cached_k.value.at[b, :, cache_positions].set(
                        key[:, :, 0, :], mode="drop"
                    )
                    v = cached_v.value.at[b, :, cache_positions].set(
                        value[:, :, 0, :], mode="drop"
                    )
                    cached_k.value, cached_v.value = k, v
                    if int8_kv:
                        k_scale.value = k_scale.value.at[b, :, cache_positions].set(
                            ks_new[:, :, 0], mode="drop"
                        )
                        v_scale.value = v_scale.value.at[b, :, cache_positions].set(
                            vs_new[:, :, 0], mode="drop"
                        )
                else:
                    # per-row multi-token span: row b writes positions
                    # cache_positions[b] + [0, T).  Advanced indexing with
                    # a mid-axis slice puts the (B, T) index result in
                    # front, so values transpose to (B, T, H[, D]).
                    pos = cache_positions[:, None] + jnp.arange(key.shape[2])[None, :]
                    k = cached_k.value.at[b[:, None], :, pos].set(
                        key.transpose(0, 2, 1, 3), mode="drop"
                    )
                    v = cached_v.value.at[b[:, None], :, pos].set(
                        value.transpose(0, 2, 1, 3), mode="drop"
                    )
                    cached_k.value, cached_v.value = k, v
                    if int8_kv:
                        k_scale.value = k_scale.value.at[b[:, None], :, pos].set(
                            ks_new.transpose(0, 2, 1), mode="drop"
                        )
                        v_scale.value = v_scale.value.at[b[:, None], :, pos].set(
                            vs_new.transpose(0, 2, 1), mode="drop"
                        )
                # the engine owns per-slot offsets; the shared counter is
                # meaningless here and stays put
            else:
                k = jax.lax.dynamic_update_slice(cached_k.value, key, (0, 0, idx, 0))
                v = jax.lax.dynamic_update_slice(cached_v.value, value, (0, 0, idx, 0))
                cached_k.value, cached_v.value = k, v
                if int8_kv:
                    k_scale.value = jax.lax.dynamic_update_slice(
                        k_scale.value, ks_new, (0, 0, idx)
                    )
                    v_scale.value = jax.lax.dynamic_update_slice(
                        v_scale.value, vs_new, (0, 0, idx)
                    )
                cache_index.value = idx + key.shape[2]
        else:
            k, v = cached_k.value, cached_v.value
        if int8_kv:
            return k, v, k_scale.value, v_scale.value, idx
        return k, v, None, None, idx

    def __call__(
        self,
        hidden: jnp.ndarray,
        kv_hidden: jnp.ndarray | None = None,
        bias: jnp.ndarray | None = None,
        use_cache: bool = False,
        positions: jnp.ndarray | None = None,
        cross_kv: tuple[jnp.ndarray, jnp.ndarray] | None = None,
        deterministic: bool = True,
        cache_positions: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        """``positions``: optional (batch, q_len) absolute positions for RoPE
        — needed when cache slots don't equal sequence positions (right-
        padded prompts).  Defaults to cache-index/arange positions.
        ``cross_kv``: precomputed ``project_kv`` output — skips the k/v
        projections entirely (cross-attention decode).  ``deterministic``
        gates ``probs_dropout_rate`` (training passes False + a "dropout"
        rng, like every other dropout).  ``cache_positions``: (batch,)
        per-row cache write offsets for continuous-batching decode (each
        serving slot at its own position; q_len rows > 1 write the
        contiguous span starting there — warm prefix admission and the
        speculative verify block both ride this, up to the decode
        kernel's ``MAX_DECODE_Q_ROWS``) — defaults to the shared
        ``cache_index`` counter."""
        q = self._split(self.q_proj(hidden), self.num_heads)
        if cross_kv is not None:
            k, v = cross_kv
            if k.shape[0] != hidden.shape[0]:
                if self.kv_heads != self.num_heads:
                    # GQA cross-attention cannot fold beams next to heads
                    # (head counts already differ): replicate K/V per beam
                    # instead — correct, just without the traffic saving
                    G = hidden.shape[0] // k.shape[0]
                    k = jnp.repeat(k, G, axis=0)
                    v = jnp.repeat(v, G, axis=0)
                else:
                    # beam decode: every beam of a row shares the row's
                    # cross K/V — fold the beam group next to heads so K/V
                    # stream once per row instead of once per beam copy
                    # (the dominant decode-step HBM traffic)
                    out = beam_grouped_attention(q, k, v, bias, dtype=self.dtype)
                    b_, h_, s_, d_ = out.shape
                    return self.o_proj(out.transpose(0, 2, 1, 3).reshape(b_, s_, h_ * d_))
        else:
            kv_src = hidden if kv_hidden is None else kv_hidden
            k = self._split(self.k_proj(kv_src), self.kv_heads)
            v = self._split(self.v_proj(kv_src), self.kv_heads)

        offset = 0
        decode_offsets = None  # (B,) absolute position of q row 0, cached decode
        k_scale = v_scale = None  # int8 KV cache scales (f32 path: None)
        if use_cache and self.causal:
            # RoPE must see absolute positions, so rotate before caching
            if self.use_rope:
                if positions is None:
                    if cache_positions is not None:
                        positions = cache_positions[:, None] + jnp.arange(q.shape[2])[None, :]
                    else:
                        # peek the index without mutating (mutation happens in _cache_kv)
                        idx = (
                            self.get_variable("cache", "cache_index")
                            if self.has_variable("cache", "cache_index")
                            else 0
                        )
                        positions = (jnp.arange(q.shape[2]) + idx)[None, :]
                cos, sin = rope_cos_sin(positions, self.head_dim, self.rope_theta)
                cos, sin = cos[:, None], sin[:, None]  # add heads axis
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
            k, v, k_scale, v_scale, offset = self._cache_kv(k, v, cache_positions)
            # validity + causality are the DECODE dispatch's job below:
            # per-row offsets feed either the decode kernel's in-kernel
            # length mask or decode_step_bias on the XLA path
            decode_offsets = (
                cache_positions
                if cache_positions is not None
                else jnp.full((q.shape[0],), offset, jnp.int32)
            )
        elif self.use_rope:
            if positions is None:
                pos = jnp.arange(q.shape[2])[None, :]
                manual = current_manual_seq()
                if manual is not None:
                    # inside a manual sequence region q holds a LOCAL shard;
                    # RoPE must see absolute positions
                    pos = pos + jax.lax.axis_index(manual[0]) * q.shape[2]
            else:
                pos = positions
            cos, sin = rope_cos_sin(pos, self.head_dim, self.rope_theta)
            cos, sin = cos[:, None], sin[:, None]  # add heads axis
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)

        if self.kv_heads != self.num_heads:
            rep = self.num_heads // self.kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
            if k_scale is not None:
                k_scale = jnp.repeat(k_scale, rep, axis=1)
                v_scale = jnp.repeat(v_scale, rep, axis=1)

        # causal masking for the non-cached path is applied here (the cached
        # path built step_bias above): natively by the flash kernel, or as an
        # additive bias for the XLA path.
        causal_here = self.causal and not use_cache
        manual = current_manual_seq()
        if manual is not None and use_cache:
            # no KV-cache path inside the manual region: cache slots would
            # be indexed with LOCAL shard positions — fail loudly rather
            # than decode silently wrong logits
            raise ValueError(
                "use_cache is not supported inside a manual sequence region "
                "(pipeline stage×sequence is training/teacher-forced only; "
                "unstack the pipelined params to decode)"
            )
        if manual is not None:
            if self.attention_impl in ("xla", "flash"):
                # the region is manual over the sequence axis: activations
                # hold local shards and only the ring body can run.  A
                # forced non-ring impl must fail loudly, not be silently
                # overridden (same contract as the trainer's forced-ring
                # startup validation).
                raise ValueError(
                    f"attention_impl={self.attention_impl!r} cannot run inside a "
                    "manual sequence region (pipeline stage×sequence executes "
                    "ring attention only); use 'auto' or 'ring'"
                )
            # Tracing inside a shard_map that is manual over the sequence
            # axis (the stage×sequence pipeline): q/k/v hold LOCAL sequence
            # shards and the normal dispatch (which opens its own shard_map
            # over global arrays) cannot run.  Use the in-region ring body
            # directly — collectives over the manual axis are exactly what
            # is legal here.
            if bias is not None and (bias.shape[1] != 1 or bias.shape[2] != 1):
                raise ValueError(
                    "manual sequence region needs a K-only bias (b|1, 1, 1, K); "
                    f"got {bias.shape}"
                )
            _log_impl_once("ring", "manual sequence region (pipeline stage×sequence)")
            out = ring_attention(
                q, k, v, bias,
                axis_name=manual[0], axis_size=manual[1],
                causal=causal_here, dtype=self.dtype,
                # partial-manual region: bf16 ppermute transposes hit the
                # partitioner's copy-chain bug — ride the ring in fp32
                plumb_fp32=True,
            )
            b, h, s, d = out.shape
            return self.o_proj(out.transpose(0, 2, 1, 3).reshape(b, s, h * d))
        mesh = current_mesh()
        if decode_offsets is not None:
            decode_dropout = (
                float(self.probs_dropout_rate) if not deterministic else 0.0
            )
            impl, reason = select_decode_impl(
                self.attention_impl,
                batch=q.shape[0],
                heads=self.num_heads,
                head_dim=self.head_dim,
                q_len=q.shape[2],
                kv_len=k.shape[2],
                mesh=mesh,
                backend=jax.default_backend(),
                device_count=jax.device_count(),
            )
            if decode_dropout > 0.0 and impl == "flash_decode":
                # the decode kernel has no in-kernel mask stream; a decode
                # pass that WANTS probs dropout (MC-dropout eval) keeps the
                # old XLA semantics instead of silently going deterministic
                impl, reason = "xla", "probs dropout requested on cached decode"
            _log_impl_once(impl, reason)
            if impl == "flash_decode":
                # bias here is the caller's constant padding mask only —
                # validity/causality ride the kernel's per-row length mask;
                # int8 KV scales dequantize per kv tile inside the kernel
                out = flash_decode_run(
                    q, k, v, bias, offsets=decode_offsets, mesh=mesh,
                    k_scale=k_scale, v_scale=v_scale,
                    dtype=self.dtype,
                )
            else:
                if k_scale is not None:
                    # the XLA fallback dequantizes through the IDENTICAL
                    # expression the kernel evaluates per tile
                    from distributed_llms_example_tpu.ops.flash_attention import (
                        dequantize_kv,
                    )

                    k = dequantize_kv(k, k_scale)
                    v = dequantize_kv(v, v_scale)
                step = decode_step_bias(decode_offsets, q.shape[2], k.shape[2])
                out = dot_product_attention(
                    q, k, v, step if bias is None else bias + step,
                    dtype=self.dtype,
                    dropout_rate=decode_dropout,
                    dropout_rng=(
                        self.make_rng("dropout") if decode_dropout > 0.0 else None
                    ),
                )
            b, h, s, d = out.shape
            return self.o_proj(out.transpose(0, 2, 1, 3).reshape(b, s, h * d))
        impl, reason = select_attention_impl(
            self.attention_impl,
            batch=q.shape[0],
            heads=self.num_heads,
            head_dim=self.head_dim,
            q_len=q.shape[2],
            kv_len=k.shape[2],
            use_cache=use_cache,
            mesh=mesh,
            backend=jax.default_backend(),
            device_count=jax.device_count(),
            causal=causal_here,
            bias_kv_only=None if bias is None else (bias.shape[1] == 1 and bias.shape[2] == 1),
        )
        _log_impl_once(impl, reason)
        probs_dropout = (
            float(self.probs_dropout_rate) if not deterministic else 0.0
        )
        if impl == "ring":
            if probs_dropout > 0.0:
                raise ValueError(
                    "probs_dropout_rate > 0 is not supported on the ring "
                    "attention path (the rotating kv blocks would need a "
                    "ring-aware mask stream); train with attention_impl "
                    "'flash'/'xla' or probs dropout off"
                )
            out = ring_attention_sharded(
                q, k, v, bias, mesh=mesh, causal=causal_here, dtype=self.dtype
            )
        elif impl == "flash":
            seed = None
            if probs_dropout > 0.0:
                from distributed_llms_example_tpu.ops.fused_dropout import (
                    seed_from_key,
                )

                seed = seed_from_key(self.make_rng("dropout"))
            out = self._flash_run(
                q, k, v, bias, causal_here, mesh,
                dropout_rate=probs_dropout, dropout_seed=seed,
            )
        else:
            if causal_here:
                step = make_causal_bias(q.shape[2], k.shape[2])
                bias = step if bias is None else bias + step
            out = dot_product_attention(
                q, k, v, bias, dtype=self.dtype,
                dropout_rate=probs_dropout,
                dropout_rng=(
                    self.make_rng("dropout") if probs_dropout > 0.0 else None
                ),
            )
        b, h, s, d = out.shape
        return self.o_proj(out.transpose(0, 2, 1, 3).reshape(b, s, h * d))

    def _flash_run(
        self,
        q: jnp.ndarray,
        k: jnp.ndarray,
        v: jnp.ndarray,
        bias: jnp.ndarray | None,
        causal: bool,
        mesh: Mesh | None,
        dropout_rate: float = 0.0,
        dropout_seed=None,
    ) -> jnp.ndarray:
        return flash_run(
            q, k, v, bias, causal=causal, mesh=mesh, dtype=self.dtype,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )


def flash_run(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None,
    *,
    causal: bool,
    mesh: Mesh | None,
    dtype: jnp.dtype,
    scale: float | None = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
) -> jnp.ndarray:
    """Run the Pallas kernel — directly on one device, per-shard under
    ``shard_map`` on a mesh (batch over data×fsdp×expert, heads over
    tensor; attention itself never mixes batches or heads, so the kernel
    body needs no collectives).  Constant-mask biases only: the shard_map
    runs with check_vma=False, under which a learned bias's gradient would
    silently miss its cross-shard psum — learned biases use
    ops/flash_attention.flash_attention_lbias_sharded, whose hand-written
    vjp performs that psum explicitly.

    ``dropout_rate`` > 0 (with an int32 ``dropout_seed``) turns on the
    in-kernel attention-probs dropout; each shard folds its axis indices
    into the seed so shards draw independent masks."""
    if mesh is None or math.prod(mesh.devices.shape) == 1:
        return flash_attention(
            q, k, v, bias, causal=causal, dtype=dtype, scale=scale,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.shape)
    head_axis = "tensor" if "tensor" in mesh.shape else None
    qkv_spec = P(batch_axes or None, head_axis, None, None)
    has_dropout = dropout_rate > 0.0 and dropout_seed is not None
    fold_axes = batch_axes + ((head_axis,) if head_axis else ())

    def run(q, k, v, *rest):
        rest = list(rest)
        seed = rest.pop() if has_dropout else None
        if seed is not None and fold_axes:
            from distributed_llms_example_tpu.ops.fused_dropout import _shard_seed

            seed = _shard_seed(seed, fold_axes)
        return flash_attention(
            q, k, v, rest[0] if rest else None, causal=causal, dtype=dtype,
            scale=scale, dropout_rate=dropout_rate, dropout_seed=seed,
        )

    args = (q, k, v)
    in_specs = (qkv_spec, qkv_spec, qkv_spec)
    if bias is not None:
        bias_spec = P(
            (batch_axes or None) if bias.shape[0] != 1 else None,
            head_axis if bias.shape[1] != 1 else None,
            None,
            None,
        )
        args = (*args, bias)
        in_specs = (*in_specs, bias_spec)
    if has_dropout:
        args = (*args, jnp.asarray(dropout_seed, jnp.int32).reshape(()))
        in_specs = (*in_specs, P())
    return compat_shard_map(
        run, mesh=mesh, in_specs=in_specs, out_specs=qkv_spec, check_vma=False
    )(*args)
