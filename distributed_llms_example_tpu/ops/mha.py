"""Shared multi-head attention module (BART/LLaMA families).

One module covers: scaled dot-product attention with optional biases in the
projections, causal masking, fixed-shape KV caching for autoregressive
decode, rotary position embeddings (LLaMA), and grouped-query attention
(fewer KV heads than Q heads).  T5 keeps its own attention (unscaled
scores + relative position bias are peculiar to it).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.ops.attention import (
    NEG_INF,
    dot_product_attention,
    make_causal_bias,
)
from distributed_llms_example_tpu.ops.flash_attention import (
    flash_attention,
    flash_supported,
)


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float = 10000.0) -> tuple:
    """(..., head_dim) cos/sin tables for the given integer positions, in the
    HF half-rotation layout (freqs repeated, not interleaved)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # (..., head_dim/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: (batch, heads, seq, head_dim); cos/sin: (seq, head_dim) or
    broadcastable."""
    half = x.shape[-1] // 2
    rotated = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
    return (x * cos + rotated * sin).astype(x.dtype)


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    model_dim: int
    num_kv_heads: int | None = None  # None → == num_heads
    use_bias: bool = True
    causal: bool = False
    use_rope: bool = False
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.float32
    # "auto": Pallas flash attention on TPU for flash-eligible shapes,
    # XLA attention otherwise; "flash"/"xla" force a path.  The causal
    # mask is applied inside this module (natively by the flash kernel),
    # so callers pass only padding/cross-attention biases.
    attention_impl: str = "auto"

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads if self.num_kv_heads is not None else self.num_heads

    def setup(self) -> None:
        inner_q = self.num_heads * self.head_dim
        inner_kv = self.kv_heads * self.head_dim
        mk = lambda feats, name: nn.Dense(feats, use_bias=self.use_bias, dtype=self.dtype, name=name)  # noqa: E731
        self.q_proj = mk(inner_q, "q_proj")
        self.k_proj = mk(inner_kv, "k_proj")
        self.v_proj = mk(inner_kv, "v_proj")
        self.o_proj = mk(self.model_dim, "o_proj")

    def _split(self, x: jnp.ndarray, heads: int) -> jnp.ndarray:
        b, s, _ = x.shape
        return x.reshape(b, s, heads, self.head_dim).transpose(0, 2, 1, 3)

    @nn.compact
    def _cache_kv(self, key: jnp.ndarray, value: jnp.ndarray):
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros, key.shape, key.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros, value.shape, value.dtype)
        cache_index = self.variable("cache", "cache_index", lambda: jnp.array(0, dtype=jnp.int32))
        idx = cache_index.value
        if is_initialized:
            k = jax.lax.dynamic_update_slice(cached_k.value, key, (0, 0, idx, 0))
            v = jax.lax.dynamic_update_slice(cached_v.value, value, (0, 0, idx, 0))
            cached_k.value, cached_v.value = k, v
            cache_index.value = idx + key.shape[2]
        else:
            k, v = cached_k.value, cached_v.value
        return k, v, idx

    def __call__(
        self,
        hidden: jnp.ndarray,
        kv_hidden: jnp.ndarray | None = None,
        bias: jnp.ndarray | None = None,
        use_cache: bool = False,
        positions: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        """``positions``: optional (batch, q_len) absolute positions for RoPE
        — needed when cache slots don't equal sequence positions (right-
        padded prompts).  Defaults to cache-index/arange positions."""
        kv_src = hidden if kv_hidden is None else kv_hidden
        q = self._split(self.q_proj(hidden), self.num_heads)
        k = self._split(self.k_proj(kv_src), self.kv_heads)
        v = self._split(self.v_proj(kv_src), self.kv_heads)

        offset = 0
        if use_cache and self.causal:
            # RoPE must see absolute positions, so rotate before caching
            if self.use_rope:
                if positions is None:
                    # peek the index without mutating (mutation happens in _cache_kv)
                    idx = (
                        self.get_variable("cache", "cache_index")
                        if self.has_variable("cache", "cache_index")
                        else 0
                    )
                    positions = (jnp.arange(q.shape[2]) + idx)[None, :]
                cos, sin = rope_cos_sin(positions, self.head_dim, self.rope_theta)
                cos, sin = cos[:, None], sin[:, None]  # add heads axis
                q = apply_rope(q, cos, sin)
                k = apply_rope(k, cos, sin)
            k, v, offset = self._cache_kv(k, v)
            kv_len, q_len = k.shape[2], q.shape[2]
            pos = jnp.arange(kv_len)[None, None, None, :]
            valid = pos <= (offset + q_len - 1)
            causal = pos <= (offset + jnp.arange(q_len)[None, None, :, None])
            step_bias = jnp.where(valid & causal, 0.0, NEG_INF)
            bias = step_bias if bias is None else bias + step_bias
        elif self.use_rope:
            pos = jnp.arange(q.shape[2])[None, :] if positions is None else positions
            cos, sin = rope_cos_sin(pos, self.head_dim, self.rope_theta)
            cos, sin = cos[:, None], sin[:, None]  # add heads axis
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)

        if self.kv_heads != self.num_heads:
            rep = self.num_heads // self.kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)

        # causal masking for the non-cached path is applied here (the cached
        # path built step_bias above): natively by the flash kernel, or as an
        # additive bias for the XLA path.
        causal_here = self.causal and not use_cache
        if self._use_flash(q.shape[2], k.shape[2], use_cache):
            out = flash_attention(q, k, v, bias, causal=causal_here, dtype=self.dtype)
        else:
            if causal_here:
                step = make_causal_bias(q.shape[2], k.shape[2])
                bias = step if bias is None else bias + step
            out = dot_product_attention(q, k, v, bias, dtype=self.dtype)
        b, h, s, d = out.shape
        return self.o_proj(out.transpose(0, 2, 1, 3).reshape(b, s, h * d))

    def _use_flash(self, q_len: int, kv_len: int, use_cache: bool) -> bool:
        if self.attention_impl not in ("auto", "flash", "xla"):
            raise ValueError(
                f"attention_impl={self.attention_impl!r}: must be 'auto', "
                "'flash', or 'xla'"
            )
        if use_cache or self.attention_impl == "xla":
            return False
        if not flash_supported(q_len, kv_len, self.head_dim):
            # 'flash' means "wherever eligible": single-token decode steps
            # (q_len=1 cross-attention during cached generation) and other
            # non-tileable shapes silently use the XLA path
            return False
        if self.attention_impl == "flash":
            return True
        # auto: compiled kernel on TPU for non-trivial score matrices.  On
        # CPU the interpreted kernel would be pure overhead.  Restricted to
        # single-device processes for now: under multi-device GSPMD jit an
        # opaque pallas call can't be partitioned, so multi-chip runs take
        # the XLA attention path unless a shard-local caller (shard_map)
        # forces attention_impl='flash'.
        return (
            jax.default_backend() == "tpu"
            and jax.device_count() == 1
            and q_len * kv_len >= 128 * 128
        )
