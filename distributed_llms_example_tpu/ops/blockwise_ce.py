"""Blockwise (vocab-chunked) cross-entropy for large-vocab LM heads.

The standard causal-LM loss materializes the full (tokens, vocab) logits
tensor — for the 7B recipe (batch 4 × seq 1024 × vocab 32000) that is a
0.5 GB fp32 array written and re-read several times (logsumexp, gather,
softmax in the backward), all pure HBM traffic.  This module fuses the
LM-head matmul, the softmax statistics, and the CE reduction into one
``lax.scan`` over vocab chunks: per chunk, a (tokens, block) tile is
produced by the MXU, reduced to per-row scalars, and dropped — the only
(tokens, vocab)-sized object that ever exists is conceptual.

The backward (second attack, after the r05 regression 99.3 → 111.5 ms):

- **Residuals are per-chunk scalars.**  The ``custom_vjp`` saves only
  the per-chunk logsumexp rows ``lse[(nc, N)]`` (and the function's own
  inputs, which autodiff keeps alive anyway) — zero logits bytes
  resident between forward and backward, so the op stays
  remat-transparent and composes with activation checkpointing.
- **One recompute feeding BOTH contractions.**  The backward scan
  recomputes each chunk's logits once and immediately contracts them
  into dh (``g @ w_cᵀ`` — the dlogits→dhidden contraction, fused per
  chunk) and dw (``hᵀ @ g``) — the minimum possible: the softmax term
  of the gradient needs the probabilities, and with no logits resident
  they must be recomputed exactly once (~one extra head matmul pass vs
  the materializing path; that pass IS the price of the 0.5 GB saving,
  measured honestly in the bench A/B).
- **A lean scan body.**  The r05 body built a (tokens, block) one-hot,
  clip/compare target indexing, and a running dw carry updated with
  ``dynamic_update_slice`` — a full (D, V) fp32 carry rewritten every
  chunk when XLA fails to alias it.  Now the body is exactly matmul →
  exp → scale → two contractions: dw chunks leave the scan as stacked
  OUTPUTS (written once each), and the one-hot / label-smoothing
  correction terms are applied OUTSIDE the loop as one gather
  (``w[:, targets]``), one scatter-add, and a rank-1 term.

Numerics: chunk logits are computed at fp32 accumulation
(``preferred_element_type``) from the bf16 hidden/kernel — slightly
MORE precise than the unfused path, whose logits round through bf16
before the fp32 CE.  Same token-SUM semantics as
``train.step.cross_entropy_sums`` (loss sum, unmasked-token count), so
grad accumulation and token weighting compose identically.

Sharding: intended for data/fsdp meshes (the BASELINE 7B config).  Under
tensor parallelism the LM-head kernel's vocab dim is sharded and the
per-chunk ``dynamic_slice`` would fight the partitioner — keep the
unfused path there (the Trainer only enables this via ``--fused-ce``).

The reference has no analog (fp32 torch, full logits); this is part of
the TPU-first perf work, like ops/flash_attention.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.data.batching import LABEL_PAD


def pick_block(vocab: int, target: int = 4096) -> int:
    """Largest divisor of ``vocab`` ≤ ``target`` — chunks must tile the
    vocab exactly so no masking/padding logic runs in the hot loop."""
    for b in range(min(target, vocab), 0, -1):
        if vocab % b == 0:
            return b
    return vocab


def _chunk(w: jnp.ndarray, i: jnp.ndarray, block: int) -> jnp.ndarray:
    return jax.lax.dynamic_slice_in_dim(w, i * block, block, axis=1)


def _logits(h: jnp.ndarray, w_c: jnp.ndarray) -> jnp.ndarray:
    # fp32 MXU accumulation straight out of the matmul — the unfused path
    # rounds logits through bf16 first
    return jnp.einsum("nd,dv->nv", h, w_c, preferred_element_type=jnp.float32)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def blockwise_cross_entropy_sums(
    hidden: jnp.ndarray,
    w: jnp.ndarray,
    labels: jnp.ndarray,
    label_smoothing: float = 0.0,
    block: int | None = None,
):
    """(loss_sum, token_count) of next-token CE without materializing logits.

    ``hidden``: (N, D) pre-head activations (caller flattens and applies
    the next-token shift); ``w``: (D, V) LM-head kernel; ``labels``: (N,)
    int ids with ``LABEL_PAD`` marking masked positions.  Gradients flow
    to ``hidden`` and ``w``; the count output has zero gradient.
    """
    lsum, tokens, _, _ = _forward(hidden, w, labels, label_smoothing, block)
    return lsum, tokens


def _forward(hidden, w, labels, label_smoothing, block):
    """Vocab-chunked forward: per chunk, (N, blk) logits reduce to the
    per-row chunk-local logsumexp ``lse_c``, the correct-class logit
    (one chunk holds each row's target), and — under label smoothing —
    the chunk's logit sum.  No cross-chunk carry: the global logsumexp
    is the (nc, N) → (N,) logsumexp of the per-chunk rows, exactly equal
    to the online-softmax recurrence but leaving per-chunk scalars the
    backward can be reconstructed from."""
    V = w.shape[1]
    blk = pick_block(V) if block is None else block
    if V % blk:
        raise ValueError(f"block {blk} does not divide vocab {V}")
    nc = V // blk
    mask = (labels != LABEL_PAD)
    targets = jnp.where(mask, labels, 0)
    smooth_on = label_smoothing > 0.0

    def body(_, i):
        lg = _logits(hidden, _chunk(w, i, blk))  # (N, blk) fp32
        m_c = jnp.max(lg, axis=-1)
        lse_c = m_c + jnp.log(jnp.sum(jnp.exp(lg - m_c[:, None]), axis=-1))
        c0 = i * blk
        in_chunk = (targets >= c0) & (targets < c0 + blk)
        idx = jnp.clip(targets - c0, 0, blk - 1)
        t = jnp.take_along_axis(lg, idx[:, None], axis=1)[:, 0]
        t_part = jnp.where(in_chunk, t, 0.0)  # each target lives in ONE chunk
        sum_part = jnp.sum(lg, axis=-1) if smooth_on else jnp.zeros(())
        return 0, (lse_c, t_part, sum_part)

    _, (lse, t_parts, sum_parts) = jax.lax.scan(body, 0, jnp.arange(nc))
    m = jnp.max(lse, axis=0)
    logz = m + jnp.log(jnp.sum(jnp.exp(lse - m[None, :]), axis=0))
    t_logit = jnp.sum(t_parts, axis=0)
    loss = logz - t_logit
    if smooth_on:
        # mean over vocab of -log_softmax = logz - mean(logits)
        smooth = logz - jnp.sum(sum_parts, axis=0) / V
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth
    maskf = mask.astype(jnp.float32)
    return jnp.sum(loss * maskf), jnp.sum(maskf), logz, lse


def _fwd(hidden, w, labels, label_smoothing, block):
    lsum, tokens, _logz, lse = _forward(hidden, w, labels, label_smoothing, block)
    # residuals: the inputs (alive under autodiff regardless) plus ONLY
    # the per-chunk lse rows — (nc, N) fp32 scalars, no logits bytes
    return (lsum, tokens), (hidden, w, labels, lse)


def _bwd(label_smoothing, block, res, ct):
    hidden, w, labels, lse = res
    d_lsum, _d_tokens = ct  # the count is a constant of the data: no grad
    V = w.shape[1]
    blk = pick_block(V) if block is None else block
    nc = V // blk
    mask = (labels != LABEL_PAD)
    targets = jnp.where(mask, labels, 0)
    # global logsumexp reassembled from the saved per-chunk rows
    m = jnp.max(lse, axis=0)
    logz = m + jnp.log(jnp.sum(jnp.exp(lse - m[None, :]), axis=0))
    scale = mask.astype(jnp.float32) * d_lsum  # (N,)

    # The softmax term: one scan whose recomputed chunk logits feed BOTH
    # contractions — dh += g @ w_cᵀ fused per chunk (the dlogits→dhidden
    # contraction never materializes g beyond one (N, blk) tile), dw
    # chunks leave as stacked scan OUTPUTS (each written exactly once; a
    # dw carry + dynamic_update_slice rewrote the full (D, V) fp32
    # buffer per chunk when XLA failed to alias it — the r05 regression's
    # biggest slice)
    def body(dh, i):
        w_c = _chunk(w, i, blk)
        lg = _logits(hidden, w_c)  # the one recompute, flash-style
        g = jnp.exp(lg - logz[:, None]) * scale[:, None]  # (N, blk) fp32
        dh = dh + jnp.einsum("nv,dv->nd", g, w_c, preferred_element_type=jnp.float32)
        dw_c = jnp.einsum("nd,nv->dv", hidden, g, preferred_element_type=jnp.float32)
        return dh, dw_c

    dh0 = jnp.zeros(hidden.shape, jnp.float32)
    dh, dw_chunks = jax.lax.scan(body, dh0, jnp.arange(nc))
    # (nc, D, blk) chunks are contiguous vocab slabs → (D, V)
    dw = jnp.moveaxis(dw_chunks, 0, 1).reshape(w.shape)

    # Correction terms OUTSIDE the hot loop (the r05 body rebuilt a
    # (N, blk) one-hot every chunk): the correct-class term is one
    # gather + one scatter-add, the label-smoothing term is rank-1.
    onehot_coef = (1.0 - label_smoothing) * scale  # (N,)
    w_y = jnp.take(w, targets, axis=1).astype(jnp.float32)  # (D, N)
    dh = dh - onehot_coef[:, None] * w_y.T
    h32 = hidden.astype(jnp.float32)
    dw = dw.at[:, targets].add(
        -(onehot_coef[:, None] * h32).T, mode="drop"
    )
    if label_smoothing > 0.0:
        sm = label_smoothing / V
        w_rowsum = jnp.sum(w, axis=1).astype(jnp.float32)  # (D,)
        dh = dh - (sm * scale)[:, None] * w_rowsum[None, :]
        dw = dw - sm * jnp.sum(scale[:, None] * h32, axis=0)[:, None]
    return dh.astype(hidden.dtype), dw.astype(w.dtype), None


blockwise_cross_entropy_sums.defvjp(_fwd, _bwd)
