"""Blockwise (vocab-chunked) cross-entropy for large-vocab LM heads.

The standard causal-LM loss materializes the full (tokens, vocab) logits
tensor — for the 7B recipe (batch 4 × seq 1024 × vocab 32000) that is a
0.5 GB fp32 array written and re-read several times (logsumexp, gather,
softmax in the backward), all pure HBM traffic.  This module fuses the
LM-head matmul, the online softmax statistics, and the CE reduction into
one ``lax.scan`` over vocab chunks: per chunk, a (tokens, block) tile is
produced by the MXU, consumed by the running logsumexp / true-logit
gather, and dropped — the only (tokens, vocab)-sized object that ever
exists is conceptual.  The hand-written vjp recomputes each chunk's
logits in the backward (flash-attention-style rematerialization) and
accumulates dh / dW chunk by chunk.

Numerics: chunk logits are computed at fp32 accumulation
(``preferred_element_type``) from the bf16 hidden/kernel — slightly
MORE precise than the unfused path, whose logits round through bf16
before the fp32 CE.  Same token-SUM semantics as
``train.step.cross_entropy_sums`` (loss sum, unmasked-token count), so
grad accumulation and token weighting compose identically.

Sharding: intended for data/fsdp meshes (the BASELINE 7B config).  Under
tensor parallelism the LM-head kernel's vocab dim is sharded and the
per-chunk ``dynamic_slice`` would fight the partitioner — keep the
unfused path there (the Trainer only enables this via ``--fused-ce``).

The reference has no analog (fp32 torch, full logits); this is part of
the TPU-first perf work, like ops/flash_attention.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from distributed_llms_example_tpu.data.batching import LABEL_PAD

_NEG = -1.0e30  # finite stand-in for -inf: exp(_NEG - m) underflows to 0


def pick_block(vocab: int, target: int = 4096) -> int:
    """Largest divisor of ``vocab`` ≤ ``target`` — chunks must tile the
    vocab exactly so no masking/padding logic runs in the hot loop."""
    for b in range(min(target, vocab), 0, -1):
        if vocab % b == 0:
            return b
    return vocab


def _chunk(w: jnp.ndarray, i: jnp.ndarray, block: int) -> jnp.ndarray:
    return jax.lax.dynamic_slice_in_dim(w, i * block, block, axis=1)


def _logits(h: jnp.ndarray, w_c: jnp.ndarray) -> jnp.ndarray:
    # fp32 MXU accumulation straight out of the matmul — the unfused path
    # rounds logits through bf16 first
    return jnp.einsum("nd,dv->nv", h, w_c, preferred_element_type=jnp.float32)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def blockwise_cross_entropy_sums(
    hidden: jnp.ndarray,
    w: jnp.ndarray,
    labels: jnp.ndarray,
    label_smoothing: float = 0.0,
    block: int | None = None,
):
    """(loss_sum, token_count) of next-token CE without materializing logits.

    ``hidden``: (N, D) pre-head activations (caller flattens and applies
    the next-token shift); ``w``: (D, V) LM-head kernel; ``labels``: (N,)
    int ids with ``LABEL_PAD`` marking masked positions.  Gradients flow
    to ``hidden`` and ``w``; the count output has zero gradient.
    """
    lsum, tokens, _ = _forward(hidden, w, labels, label_smoothing, block)
    return lsum, tokens


def _forward(hidden, w, labels, label_smoothing, block):
    V = w.shape[1]
    blk = pick_block(V) if block is None else block
    if V % blk:
        raise ValueError(f"block {blk} does not divide vocab {V}")
    nc = V // blk
    mask = (labels != LABEL_PAD)
    targets = jnp.where(mask, labels, 0)

    def body(carry, i):
        m, s, t_logit, sum_l = carry
        lg = _logits(hidden, _chunk(w, i, blk))  # (N, blk) fp32
        m_new = jnp.maximum(m, jnp.max(lg, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(lg - m_new[:, None]), axis=-1)
        c0 = i * blk
        in_chunk = (targets >= c0) & (targets < c0 + blk)
        idx = jnp.clip(targets - c0, 0, blk - 1)
        t = jnp.take_along_axis(lg, idx[:, None], axis=1)[:, 0]
        t_logit = jnp.where(in_chunk, t, t_logit)
        sum_l = sum_l + jnp.sum(lg, axis=-1)
        return (m_new, s, t_logit, sum_l), None

    N = hidden.shape[0]
    init = (
        jnp.full((N,), _NEG, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.full((N,), _NEG, jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    (m, s, t_logit, sum_l), _ = jax.lax.scan(body, init, jnp.arange(nc))
    logz = m + jnp.log(s)
    loss = logz - t_logit
    if label_smoothing > 0.0:
        # mean over vocab of -log_softmax = logz - mean(logits)
        smooth = logz - sum_l / V
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smooth
    maskf = mask.astype(jnp.float32)
    return jnp.sum(loss * maskf), jnp.sum(maskf), logz


def _fwd(hidden, w, labels, label_smoothing, block):
    lsum, tokens, logz = _forward(hidden, w, labels, label_smoothing, block)
    return (lsum, tokens), (hidden, w, labels, logz)


def _bwd(label_smoothing, block, res, ct):
    hidden, w, labels, logz = res
    d_lsum, _d_tokens = ct  # the count is a constant of the data: no grad
    V = w.shape[1]
    blk = pick_block(V) if block is None else block
    nc = V // blk
    mask = (labels != LABEL_PAD)
    targets = jnp.where(mask, labels, 0)
    scale = (mask.astype(jnp.float32) * d_lsum)[:, None]  # (N, 1)

    def body(carry, i):
        dh, dw = carry
        w_c = _chunk(w, i, blk)
        lg = _logits(hidden, w_c)  # recompute, flash-style
        p = jnp.exp(lg - logz[:, None])
        c0 = i * blk
        in_chunk = (targets >= c0) & (targets < c0 + blk)
        idx = jnp.clip(targets - c0, 0, blk - 1)
        onehot = (
            (jnp.arange(blk)[None, :] == idx[:, None]) & in_chunk[:, None]
        ).astype(jnp.float32)
        g = p - (1.0 - label_smoothing) * onehot - label_smoothing / V
        g = g * scale  # (N, blk) fp32
        dh = dh + jnp.einsum("nv,dv->nd", g, w_c, preferred_element_type=jnp.float32)
        dw_c = jnp.einsum("nd,nv->dv", hidden, g, preferred_element_type=jnp.float32)
        dw = jax.lax.dynamic_update_slice_in_dim(dw, dw_c, i * blk, axis=1)
        return (dh, dw), None

    dh0 = jnp.zeros(hidden.shape, jnp.float32)
    dw0 = jnp.zeros(w.shape, jnp.float32)
    (dh, dw), _ = jax.lax.scan(body, (dh0, dw0), jnp.arange(nc))
    return dh.astype(hidden.dtype), dw.astype(w.dtype), None


blockwise_cross_entropy_sums.defvjp(_fwd, _bwd)
