"""Fused Pallas TPU dropout — in-kernel RNG, seed-recompute backward.

Why this exists: the trainer fine-tunes with the model's real dropout, and
the bench A/B (BENCH_r05) shows dropout is the single largest measured gap
in the hot path — the dropout-free synthetic step runs ~24% faster than
the with-dropout step.  ``--prng-impl rbg`` proves most of that is mask
*generation* (threefry counter math); the rest is the mask tensor itself:
XLA materializes the random bits to HBM, reads them back in the backward
pass (the mask is a saved residual), and does not fuse the
generate→compare→select→add chain into one pass over the activation.

This module removes the whole tax:

- **In-kernel RNG**: random bits are generated INSIDE the Pallas kernel —
  ``pltpu.prng_seed`` / ``pltpu.prng_random_bits`` (the TPU hardware RNG)
  on real TPUs, seeded deterministically per (seed, tile); a murmur3-style
  counter hash of absolute element positions everywhere else (pure uint32
  VPU ops, identical in interpret and compiled mode, so the fused path is
  testable in the CPU tier-1 suite).  No mask tensor is ever produced by
  threefry or written to HBM.
- **Fused residual add**: the transformer call sites are all
  ``residual + dropout(h)`` — the add rides the same kernel, so the
  activation makes one HBM round-trip instead of three.
- **Seed-recompute backward**: the ``jax.custom_vjp`` saves ONLY the int32
  seed and recomputes the keep-mask in the backward kernel from the same
  (seed, tile) stream — zero residual bytes for dropout, which also makes
  the op remat-transparent (recomputing the forward draws the identical
  mask).

Determinism contract: masks are a pure function of (seed, absolute element
position) for the hash stream, and of (seed, tile index, tile shape) for
the hardware stream — equal seeds give equal masks across calls, forward
and backward always agree.  The bit stream differs from
``jax.random.bernoulli`` (and between the hash/hw streams): selecting the
fused impl trades bit-for-bit reproducibility with the XLA path for speed,
exactly like ``--prng-impl rbg`` already does (README "Dropout & RNG
performance").

Impl selection (``--dropout-impl``): ``auto`` (default) resolves to
``fused`` on TPU backends and ``xla`` elsewhere; the ``xla`` path is
bit-identical to ``flax.linen.Dropout``.  Model code routes every dropout
through the :class:`Dropout` module / :func:`dropout` functional below —
``scripts/repo_lint.py`` forbids raw ``nn.Dropout`` / ``bernoulli`` in
``models/`` and ``train/`` so call sites cannot silently bypass the fused
path.  Attention-probs dropout is folded into the flash-attention kernels
(``ops/flash_attention.py``) using :func:`tile_keep` from here, so the
(B, H, S, S) probs mask never materializes either.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # TPU vector lane count; last dim must divide into it

# VMEM budget per tile: block_rows * cols elements.  512K fp32 elements is
# ~2 MB — three buffers (x, residual, out) stay far under the 16 MB stack.
_MAX_TILE_ELEMS = 512 * 1024

# ---------------------------------------------------------------- impl knob

_VALID_IMPLS = ("auto", "fused", "xla")
_DEFAULT_IMPL = "auto"


def set_default_impl(impl: str) -> None:
    """Process-wide default for :class:`Dropout` / :func:`dropout` when the
    caller does not pin one — the trainer sets it from ``--dropout-impl``
    at startup, bench flips it for the fused-vs-xla A/B."""
    global _DEFAULT_IMPL
    if impl not in _VALID_IMPLS:
        raise ValueError(f"dropout impl {impl!r}: must be one of {_VALID_IMPLS}")
    _DEFAULT_IMPL = impl


def default_impl() -> str:
    return _DEFAULT_IMPL


def resolve_impl(impl: str | None = None, backend: str | None = None) -> str:
    """``auto`` → ``fused`` on TPU, ``xla`` elsewhere (the interpreted
    kernel is pure overhead in a real training run; tests pin
    ``impl="fused"`` explicitly to exercise it on CPU)."""
    impl = impl or _DEFAULT_IMPL
    if impl not in _VALID_IMPLS:
        raise ValueError(f"dropout impl {impl!r}: must be one of {_VALID_IMPLS}")
    if impl != "auto":
        return impl
    backend = backend or jax.default_backend()
    return "fused" if backend == "tpu" else "xla"


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------------ the RNG


def keep_threshold(rate: float) -> int:
    """uint32 threshold T such that ``(bits >> 8) < T`` keeps with
    probability ``1 - rate`` (24-bit uniform compare — integer-only keep
    decision, no float conversion of the bits)."""
    return int(round((1.0 - float(rate)) * (1 << 24)))


def _mix32(x):
    """murmur3 finalizer: full-avalanche 32-bit mix (uint32 in/out)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _hash_bits(seed, tag_a, tag_b, rows, cols):
    """Counter-based uint32 bit stream: a pure function of (seed, tag pair,
    absolute row, absolute col).  ``rows``/``cols`` are uint32 arrays of the
    tile's absolute element coordinates; scalars are int32-convertible.
    Block-size independent by construction, so forward/backward (and remat
    replays) agree no matter how each pass tiles the array."""
    s = _mix32(
        jnp.uint32(seed).astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
        + jnp.uint32(tag_a).astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
        + jnp.uint32(tag_b).astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    )
    x = (
        rows * jnp.uint32(0x27D4EB2F)
        + cols * jnp.uint32(0x165667B1)
        + s
    )
    return _mix32(x)


def tile_keep(seed, tag_a, tag_b, row0, col0, shape, rate: float,
              hw_rng: bool):
    """Keep-mask for one (rows, cols) tile whose top-left element sits at
    absolute (row0, col0) of the (tag_a, tag_b)-indexed plane.

    Called INSIDE Pallas kernels (here and in the flash-attention probs
    dropout).  ``hw_rng=True`` seeds the TPU hardware PRNG per tile —
    deterministic for equal (seed, tags, offsets, shape), compiled-TPU
    only; ``False`` uses the portable counter hash, which is additionally
    tile-independent (same bits for an element no matter the blocking).
    """
    if not hw_rng:
        # the counter-hash stream: the SAME function tests use as the
        # reference, so the in-kernel mask cannot drift from it
        return hash_keep_mask(
            seed, shape, rate, tag_a=tag_a, tag_b=tag_b, row0=row0, col0=col0
        )
    pltpu.prng_seed(seed, tag_a, tag_b, row0, col0)
    bits = pltpu.prng_random_bits(shape)
    if bits.dtype != jnp.uint32:
        bits = pltpu.bitcast(bits, jnp.uint32)
    return (bits >> 8) < jnp.uint32(keep_threshold(rate))


def hash_keep_mask(seed, shape, rate: float, *, tag_a=0, tag_b=0,
                   row0=0, col0=0) -> jnp.ndarray:
    """The hash stream's keep-mask as a plain jnp array — the REFERENCE the
    kernels reproduce tile-by-tile (tests reconstruct the exact in-kernel
    mask with this; it is also what the backward recomputes)."""
    r = jnp.uint32(row0) + jax.lax.broadcasted_iota(jnp.int32, shape, 0).astype(jnp.uint32)
    c = jnp.uint32(col0) + jax.lax.broadcasted_iota(jnp.int32, shape, 1).astype(jnp.uint32)
    bits = _hash_bits(seed, tag_a, tag_b, r, c)
    return (bits >> 8) < jnp.uint32(keep_threshold(rate))


def seed_from_key(key: jax.Array) -> jax.Array:
    """Fold a JAX PRNG key (typed — threefry/rbg — or legacy uint32 vector)
    into the ONE int32 scalar the kernels consume.  Cheap by design: the
    whole point is that per-element randomness comes from the in-kernel
    stream, so the host-side PRNG only ever produces this scalar."""
    data = key
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(key)
    data = data.astype(jnp.uint32).ravel()
    seed = jnp.uint32(0x9E3779B9)
    for i in range(int(data.shape[0])):  # static, 2-4 words
        seed = _mix32(seed ^ data[i])
    return seed.astype(jnp.int32)


# ------------------------------------------------------------------ kernels


def _dropout_kernel(*refs, rate: float, hw_rng: bool, block_rows: int,
                    has_res: bool):
    """out = residual + where(keep, x * 1/(1-rate), 0) over one row tile."""
    it = iter(refs)
    seed_ref = next(it)
    x_ref = next(it)
    res_ref = next(it) if has_res else None
    o_ref = next(it)
    i = pl.program_id(0)
    keep = tile_keep(
        seed_ref[0], 0, 0, i * block_rows, 0, x_ref.shape, rate, hw_rng
    )
    inv_keep = jnp.float32(1.0 / (1.0 - rate))
    y = jnp.where(keep, x_ref[...].astype(jnp.float32) * inv_keep, 0.0)
    if res_ref is not None:
        y = res_ref[...].astype(jnp.float32) + y
    o_ref[...] = y.astype(o_ref.dtype)


def _run_dropout(x2, res2, seed, *, rate: float, block_rows: int,
                 hw_rng: bool, interpret: bool):
    rows, cols = x2.shape
    grid = (rows // block_rows,)
    spec = pl.BlockSpec((block_rows, cols), lambda i: (i, 0))
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM), spec]
    args = [seed.reshape(1), x2]
    if res2 is not None:
        in_specs.append(spec)
        args.append(res2)
    return pl.pallas_call(
        functools.partial(
            _dropout_kernel, rate=rate, hw_rng=hw_rng,
            block_rows=block_rows, has_res=res2 is not None,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _fused(x2, seed, rate, block_rows, hw_rng, interpret):
    return _run_dropout(
        x2, None, seed, rate=rate, block_rows=block_rows,
        hw_rng=hw_rng, interpret=interpret,
    )


def _fused_fwd(x2, seed, rate, block_rows, hw_rng, interpret):
    y = _fused(x2, seed, rate, block_rows, hw_rng, interpret)
    return y, seed  # the ENTIRE residual: one int32 scalar


def _fused_bwd(rate, block_rows, hw_rng, interpret, seed, g):
    # recompute the keep-mask from the seed: dx = where(keep, g/(1-rate), 0)
    # is the same masked-scale as the forward (without residual), so the
    # forward kernel IS the backward kernel
    dx = _run_dropout(
        g, None, seed, rate=rate, block_rows=block_rows,
        hw_rng=hw_rng, interpret=interpret,
    )
    return dx, None


_fused.defvjp(_fused_fwd, _fused_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_res(x2, res2, seed, rate, block_rows, hw_rng, interpret):
    return _run_dropout(
        x2, res2, seed, rate=rate, block_rows=block_rows,
        hw_rng=hw_rng, interpret=interpret,
    )


def _fused_res_fwd(x2, res2, seed, rate, block_rows, hw_rng, interpret):
    y = _fused_res(x2, res2, seed, rate, block_rows, hw_rng, interpret)
    return y, seed


def _fused_res_bwd(rate, block_rows, hw_rng, interpret, seed, g):
    dx = _run_dropout(
        g, None, seed, rate=rate, block_rows=block_rows,
        hw_rng=hw_rng, interpret=interpret,
    )
    return dx, g, None  # d(residual) = g: the add saves nothing either


_fused_res.defvjp(_fused_res_fwd, _fused_res_bwd)


# ----------------------------------------------------------------- plumbing


def _pick_block_rows(rows: int, cols: int) -> int:
    """Largest 8-aligned divisor of ``rows`` whose tile fits the VMEM
    budget; 0 = shape not tileable (caller falls back to XLA)."""
    cap = max(8, (_MAX_TILE_ELEMS // max(cols, 1)) // 8 * 8)
    start = min(rows, cap) // 8 * 8
    for b in range(start, 7, -8):
        if rows % b == 0:
            return b
    return 0


def fused_dropout_supported(shape, *, rate: float | None = None) -> bool:
    """True when the fused kernel can run this activation shape: last dim a
    multiple of the 128-lane vector width, leading dims tiling into
    8-aligned row blocks.  The helper silently uses the XLA path otherwise
    (correctness first; training activation shapes all qualify)."""
    if rate is not None and not 0.0 < float(rate) < 1.0:
        return False
    if len(shape) < 2:
        return False
    cols = int(shape[-1])
    rows = int(math.prod(shape[:-1]))
    if cols % LANES or rows < 8:
        return False
    return _pick_block_rows(rows, cols) > 0


def fused_dropout(
    x: jnp.ndarray,
    seed: jax.Array,
    rate: float,
    *,
    residual: jnp.ndarray | None = None,
    interpret: bool | None = None,
    hw_rng: bool | None = None,
) -> jnp.ndarray:
    """The raw fused op: ``residual + where(keep, x/(1-rate), 0)`` in one
    Pallas pass, mask drawn in-kernel from ``seed``, backward recomputed
    from the same seed (no saved mask).  ``x`` is any >=2-D activation;
    ``residual`` (optional) must match its shape.  Callers wanting
    automatic impl/mesh dispatch use :func:`dropout` / :class:`Dropout`.
    """
    if not 0.0 < float(rate) < 1.0:
        raise ValueError(f"fused_dropout needs 0 < rate < 1, got {rate}")
    if residual is not None and residual.shape != x.shape:
        raise ValueError(
            f"residual shape {residual.shape} != activation shape {x.shape}"
        )
    if interpret is None:
        interpret = _default_interpret()
    if hw_rng is None:
        hw_rng = not interpret
    cols = x.shape[-1]
    rows = int(math.prod(x.shape[:-1]))
    block_rows = _pick_block_rows(rows, cols)
    if cols % LANES or not block_rows:
        raise ValueError(
            f"shape {x.shape} not fused-dropout tileable (cols % {LANES} == 0 "
            "and 8-aligned row blocks required); gate on fused_dropout_supported"
        )
    seed = jnp.asarray(seed, jnp.int32).reshape(())
    x2 = x.reshape(rows, cols)
    if residual is None:
        y2 = _fused(x2, seed, float(rate), block_rows, bool(hw_rng), bool(interpret))
    else:
        y2 = _fused_res(
            x2, residual.reshape(rows, cols).astype(x.dtype), seed,
            float(rate), block_rows, bool(hw_rng), bool(interpret),
        )
    return y2.reshape(x.shape)


def _xla_dropout(x, key, rate, residual=None):
    """Bit-identical to ``flax.linen.Dropout``: threefry/rbg bernoulli mask,
    divide-by-keep scaling — the reproducible reference path."""
    keep_prob = 1.0 - rate
    mask = jax.random.bernoulli(key, keep_prob, x.shape)
    y = jnp.where(mask, x / keep_prob, jnp.zeros_like(x))
    return y if residual is None else residual + y


def _shard_seed(seed, axes):
    """Fold the shard's position on every mesh axis into the seed so shards
    draw independent masks (program ids restart at 0 per shard)."""
    for ax in axes:
        seed = seed * jnp.int32(1000003) + jax.lax.axis_index(ax).astype(jnp.int32)
    return seed


def _fused_run(x, seed, rate, residual, mesh):
    """Run the kernel directly on one device, or per-shard under
    ``shard_map`` on a mesh — the same dispatch shape as
    ``ops.mha.flash_run`` (an opaque pallas call cannot be partitioned by
    GSPMD itself).  Activations are (batch, ..., features): batch over the
    (data, fsdp, expert) axes, lengths over ``sequence`` when it divides,
    features replicated.  Each shard folds its axis indices into the seed.
    Returns None when the mesh splits the shape unevenly (caller falls
    back to XLA)."""
    from jax.sharding import PartitionSpec as P

    from distributed_llms_example_tpu.parallel.activation import (
        BATCH_AXES,
        compat_shard_map,
    )

    if mesh is None or math.prod(mesh.devices.shape) == 1:
        return fused_dropout(x, seed, rate, residual=residual)
    if mesh.shape.get("tensor", 1) > 1:
        # megatron meshes shard some dropout inputs over ``tensor`` on the
        # FEATURE dim (the fc1/wi MLP intermediates) while others are
        # feature-replicated (the residual stream) — one spec cannot serve
        # both, and declaring features replicated would make GSPMD
        # all-gather the ffn-wide intermediates around every kernel call,
        # costing far more than the dropout tax saved.  Fall back to XLA
        # (elementwise, sharding-preserving) until the helper can see the
        # operand's actual sharding.
        return None
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.shape)
    batch_shards = math.prod(mesh.shape[a] for a in batch_axes)
    seq_shards = mesh.shape.get("sequence", 1)
    if x.shape[0] % max(batch_shards, 1):
        return None
    seq_axis = None
    if seq_shards > 1 and x.ndim >= 3 and x.shape[1] % seq_shards == 0:
        seq_axis = "sequence"
    spec = P(
        batch_axes or None,
        *([seq_axis] + [None] * (x.ndim - 2) if x.ndim >= 2 else []),
    )
    # per-shard supportability: the kernel sees LOCAL shapes
    local_rows = (
        x.shape[0] // max(batch_shards, 1)
        * int(math.prod(x.shape[1:-1]))
        // (seq_shards if seq_axis else 1)
    )
    if not fused_dropout_supported((local_rows, x.shape[-1]), rate=rate):
        return None
    fold_axes = batch_axes + (("sequence",) if seq_axis else ())

    def run(seed, x, *rest):
        s = _shard_seed(seed, fold_axes)
        return fused_dropout(x, s, rate, residual=rest[0] if rest else None)

    args = (seed, x)
    in_specs = (P(), spec)
    if residual is not None:
        args = (*args, residual)
        in_specs = (*in_specs, spec)
    return compat_shard_map(
        run, mesh=mesh, in_specs=in_specs, out_specs=spec, check_vma=False
    )(*args)


def dropout(
    x: jnp.ndarray,
    key: jax.Array,
    rate: float,
    *,
    residual: jnp.ndarray | None = None,
    impl: str | None = None,
) -> jnp.ndarray:
    """THE shared dropout entry point (functional form) — every dropout in
    ``models/`` and ``train/`` routes through here or :class:`Dropout`
    (enforced by scripts/repo_lint.py rule 5).

    Resolves the impl (``--dropout-impl``; ``auto`` = fused on TPU), then:
    ``fused`` runs the Pallas kernel — directly, or per-shard under the
    ambient mesh — with the key folded to the in-kernel seed; shapes or
    contexts the kernel cannot serve (uneven shard splits, sub-lane
    feature dims, the pipeline's partial-manual regions where no mesh
    context exists) silently use the XLA path, mirroring how attention
    falls back from flash.  ``rate<=0`` or ``rate>=1`` edge cases match
    ``nn.Dropout`` semantics."""
    if rate <= 0.0:
        return x if residual is None else residual + x
    if rate >= 1.0:
        z = jnp.zeros_like(x)
        return z if residual is None else residual + z
    if resolve_impl(impl) == "fused" and fused_dropout_supported(x.shape, rate=rate):
        from distributed_llms_example_tpu.parallel.activation import current_mesh

        mesh = current_mesh()
        if mesh is None and jax.device_count() > 1:
            # multi-device jit without a mesh context (e.g. inside the
            # pipeline's partial-manual shard_map): an opaque pallas call
            # would force GSPMD to gather — same rule as flash attention
            return _xla_dropout(x, key, rate, residual)
        out = _fused_run(x, seed_from_key(key), rate, residual, mesh)
        if out is not None:
            return out
    return _xla_dropout(x, key, rate, residual)


import flax.linen as nn  # noqa: E402  (after the kernel section on purpose)


class Dropout(nn.Module):
    """Drop-in for ``flax.linen.Dropout`` routed through the shared helper:
    same ``"dropout"`` rng collection, same no-param tree, same
    ``deterministic`` contract — plus ``residual`` for the fused
    residual-add (``dropout(h, residual=r)`` == ``r + dropout(h)``, in ONE
    kernel pass on the fused path).  ``impl=None`` follows the process
    default (``--dropout-impl``)."""

    rate: float
    impl: str | None = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True, *, residual=None):
        if deterministic or self.rate <= 0.0:
            return x if residual is None else residual + x
        return dropout(
            x, self.make_rng("dropout"), self.rate,
            residual=residual, impl=self.impl,
        )
