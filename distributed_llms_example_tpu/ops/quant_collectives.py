"""Quantized gradient collectives: int8 reduce-scatter with error feedback.

At multi-pod scale the cross-replica gradient reduction is the dominant
wire traffic (the obs comm account itemizes it per op), and the replica
(``data``) leg is the one that crosses DCN.  Following EQuARX
(arXiv:2506.17615), this module compresses that leg ~4x: per-block
symmetric int8 quantization with stochastic rounding, the reduction
performed over int-safe integer partial sums, and a per-worker
error-feedback buffer so the quantization error is carried into the next
step's gradient instead of being lost.

The wire protocol per gradient leaf (``quantized_tree_reduce``):

1. every replica group ("worker" — one index along ``GRAD_WORKER_AXES``)
   holds its own fp32 partial gradient, stacked as a ``(W, *shape)``
   tiled array whose inner dims keep the param's own PartitionSpec
   (``train/step.py`` produces it by vmapping ``value_and_grad`` over
   shard-local batch groups — the fsdp/tensor legs inside each group
   stay GSPMD's, in fp32, on ICI);
2. error feedback: each worker adds its residual from the previous step
   (``ef``, fp32, sharded exactly like the tiled gradients — the
   cross-replica-sharded weight-update discipline of arXiv:2004.13336);
3. per-block scales: block absmax along the last dim, maxed ACROSS
   workers (one tiny fp32 collective) so every worker quantizes against
   the SAME scale — the precondition for integer partial sums;
4. stochastic rounding driven by the step RNG (``floor(v + u)``,
   ``u ~ U[0,1)`` — unbiased for every v), clip to [-127, 127], int8;
5. the new residual ``ef' = compensated - scale*q`` is computed locally
   BEFORE the wire (each worker knows its own quantization error), so
   the applied updates telescope: sum of reduced gradients over steps
   equals the sum of true gradient sums up to the final residual;
6. reduce-scatter leg: the int8 tile stack is resharded so the worker
   dim gathers while the leading param dim scatters over the worker
   axes — an **s8 all-to-all** on the wire — and the tiles are summed
   in int32 (exact integer arithmetic: the result is bit-deterministic
   regardless of replica ordering, unlike a float reduction);
7. return leg: the reduced value is re-quantized (fresh scales, fresh
   stochastic rounding — unbiased, uncompensated by design) and
   **all-gathered as s8** back to the param layout, then dequantized.

Both wire legs carry 1-byte elements where the fp32 program carried 4 —
the ~4x the ir-lint census (``analysis/ir_lint.py
quantized_gradient_census``) and the obs comm account assert on the
compiled program.  Leaves too small to block-quantize (norm scales,
biases — under ``min_quant_elems``) and leaves whose leading dim the
worker split cannot divide take the fp32 fallback reduction; their EF
leaves stay zero.

Sharding pins: the quantized arrays are constrained to their SOURCE
layout, passed through ``optimization_barrier``, then constrained to the
TARGET layout — without the pin GSPMD is free to hoist the reshard above
the quantize and move fp32 (measured: it does exactly that).

Composition: stage>1 pipelines own their communication schedules
(composition row ``grad-compression-pipelined``); sequence/context
parallelism runs ring attention in manual regions that do not nest
inside the replica-tiled backward (row ``grad-compression-sequence``).
In-step grad accumulation composes: the scan accumulates fp32 TILED
partial sums and the quantized reduction runs once at the optimizer-step
boundary (row ``grad-compression-accum``).
"""

from __future__ import annotations

import math
from typing import Any, Mapping

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The mesh axes the compression tiles over: one "worker" per index along
# these axes.  ``data`` is the pure-replica axis (params replicated over
# it, batch sharded) — its gradient reduction is the cross-DCN leg the
# compression targets.  fsdp/tensor reductions happen INSIDE each worker
# group (GSPMD, fp32, ICI) and expert groups route tokens through the
# MoE all-to-all, which must keep crossing groups — neither is tiled.
GRAD_WORKER_AXES: tuple[str, ...] = ("data",)

# default quantization block (elements per shared scale along the last dim)
QUANT_BLOCK = 256

# leaves below this element count take the fp32 fallback reduction: norm
# scales and biases are a rounding error of the wire traffic, and blocking
# them would burn scale overhead for nothing
MIN_QUANT_ELEMS = 4096


def worker_count(mesh_axes: Mapping[str, int]) -> int:
    """Number of replica groups the compression tiles over."""
    n = 1
    for a in GRAD_WORKER_AXES:
        n *= max(1, int(mesh_axes.get(a, 1) or 1))
    return n


def tiled_spec(spec: P) -> P:
    """The PartitionSpec of a worker-tiled ``(W, *shape)`` array whose
    inner dims mirror the param spec: the worker dim rides the replica
    axes, every other entry is the param's own.  THE error-feedback /
    tiled-accumulator layout contract — ``analysis/spec_lint.py
    lint_error_feedback_mirror`` checks it leaf for leaf."""
    axes = GRAD_WORKER_AXES[0] if len(GRAD_WORKER_AXES) == 1 else GRAD_WORKER_AXES
    return P(axes, *spec)


def error_feedback_specs(param_spec_tree: Any) -> Any:
    """Tiled specs for every param leaf (device-free; the spec-lint and
    the shardings helper below both derive from this one function)."""
    return jax.tree.map(
        tiled_spec, param_spec_tree, is_leaf=lambda x: isinstance(x, P)
    )


def error_feedback_shardings(param_shardings: Any, mesh: Mesh) -> Any:
    """NamedShardings for the EF tree (and the tiled grad-accum carry):
    the param shardings with the worker dim prefixed."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, tiled_spec(s.spec)), param_shardings
    )


def zero_error_feedback(params: Any, workers: int) -> Any:
    """A fresh (all-zero) EF tree for a param tree: fp32 ``(W, *shape)``
    per leaf.  Zero is the contract for restore-less resume too: a
    checkpoint that predates compression (or was written with it off)
    resumes with a zero residual — the first step simply has no error to
    feed back, exactly like step 0.

    Allocates on the default device (fine for tests/bench scales); at
    model scale use :func:`sharded_zero_error_feedback`, which never
    materializes the W x params fp32 tree on one device."""
    return jax.tree.map(
        lambda p: jnp.zeros((int(workers),) + tuple(p.shape), jnp.float32), params
    )


def sharded_zero_error_feedback(params: Any, workers: int, shardings: Any) -> Any:
    """The zero EF tree allocated DIRECTLY into the tiled layout
    (``jit`` with ``out_shardings``): each device writes only its own
    shard, so the fp32 ``(W, *shape)`` tree never sits whole on one
    device — at 7B scale a single-device materialization before the
    device_put would be tens of GB on chip 0.  ``shardings`` is
    :func:`error_feedback_shardings` of the params' resolved layout."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [(int(workers),) + tuple(x.shape) for x in leaves]

    def make():
        return jax.tree_util.tree_unflatten(
            treedef, [jnp.zeros(s, jnp.float32) for s in shapes]
        )

    return jax.jit(make, out_shardings=shardings)()


def retile_error_feedback(ef: Any, new_workers: int, shardings: Any = None) -> Any:
    """Re-tile a ``(W_old, *shape)`` error-feedback tree onto
    ``new_workers`` worker groups after a topology change (elastic
    resharding restore, ISSUE 14).  Requires ``new_workers`` to divide
    the saved worker count: each new worker group absorbs the SUM of the
    residuals of the old groups it merges, which preserves the
    telescoping invariant (the total deferred quantization error —
    ``ef.sum(axis=0)`` — is unchanged, so nothing the compensation was
    owed is lost).  A worker count that grew, or does not divide, has no
    such mapping — callers zero-fill instead (step-0 semantics, one
    residual's worth of error dropped) and say so with a
    ``grad_compression_ef_reshaped`` event.

    ``shardings`` (the NEW mesh's :func:`error_feedback_shardings`)
    makes the result sharded at birth via ``jit`` ``out_shardings``,
    like :func:`sharded_zero_error_feedback`."""
    new_workers = int(new_workers)

    def one(x: jnp.ndarray) -> jnp.ndarray:
        w_old = int(x.shape[0])
        if w_old % new_workers:
            raise ValueError(
                f"cannot re-tile error feedback from {w_old} to "
                f"{new_workers} workers: the new count must divide the old"
            )
        return x.reshape((new_workers, w_old // new_workers) + x.shape[1:]).sum(
            axis=1, dtype=jnp.float32
        )

    fn = lambda t: jax.tree.map(one, t)  # noqa: E731
    if shardings is None:
        return fn(ef)
    return jax.jit(fn, out_shardings=shardings)(ef)


def attach_error_feedback(state: Any, state_sh: Any, mesh: Mesh, workers: int) -> tuple[Any, Any]:
    """Attach a zero EF tree (sharded at birth) and its shardings to a
    TrainState + its sharding tree — THE one recipe for turning an
    uncompressed state into an int8-ready one, shared by the trainer and
    bench so neither can regress to a device-0 materialization."""
    ef_sh = error_feedback_shardings(state_sh.params, mesh)
    return (
        state.replace(ef=sharded_zero_error_feedback(state.params, workers, ef_sh)),
        state_sh.replace(ef=ef_sh),
    )


def _spec_axes_size(entry: Any, mesh_axes: Mapping[str, int]) -> int:
    if entry is None:
        return 1
    axes = entry if isinstance(entry, tuple) else (entry,)
    n = 1
    for a in axes:
        n *= max(1, int(mesh_axes.get(a, 1) or 1))
    return n


def block_size_for(last_dim: int, last_dim_shards: int, block: int = QUANT_BLOCK) -> int:
    """Largest divisor of the last dim's PER-SHARD extent that is <=
    ``block`` — blocks must not cross shard boundaries (the scale array
    inherits the leaf's last-dim sharding on its block dim)."""
    per_shard = max(1, last_dim // max(1, last_dim_shards))
    for eff in range(min(block, per_shard), 0, -1):
        if per_shard % eff == 0 and last_dim % eff == 0:
            return eff
    return 1


def stochastic_round(v: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Unbiased integer rounding: ``floor(v + u)``, ``u ~ U[0,1)`` —
    ``E[result] = v`` for every real v, positive or negative."""
    u = jax.random.uniform(key, v.shape, jnp.float32)
    return jnp.floor(v + u)


def quantize_blocks(
    c: jnp.ndarray, key: jax.Array, *, block: int, shared_over_workers: bool = True
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-block int8 quantization of a tiled ``(W, *shape)``
    (or plain ``(*shape,)``) array: blocks along the last dim, scale =
    block absmax / 127 (maxed over the worker dim when
    ``shared_over_workers`` — integer partial sums need ONE scale per
    block), values stochastically rounded.  Returns ``(q, scale)`` with
    ``q`` int8 shaped like ``c`` and ``scale`` shaped like the block
    grid (without the worker dim when shared)."""
    *lead, last = c.shape
    nb = last // block
    blocks = c.reshape(*lead, nb, block)
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    if shared_over_workers and c.ndim >= 2:
        absmax = jnp.max(absmax, axis=0)  # shared scale: max across workers
    scale = jnp.maximum(absmax, 1e-30) / 127.0
    expand = scale[None] if (shared_over_workers and c.ndim >= 2) else scale
    v = blocks / expand[..., None]
    q = jnp.clip(stochastic_round(v, key), -127.0, 127.0)
    return q.astype(jnp.int8).reshape(c.shape), scale


def dequantize_blocks(q: jnp.ndarray, scale: jnp.ndarray, *, block: int) -> jnp.ndarray:
    """Inverse of :func:`quantize_blocks` (scale already worker-shared or
    per-array — caller passes the matching grid)."""
    *lead, last = q.shape
    nb = last // block
    blocks = q.astype(jnp.float32).reshape(*lead, nb, block)
    return (blocks * scale[..., None]).reshape(q.shape)


def _pin(x: jnp.ndarray, spec: P | None, mesh: Mesh | None) -> jnp.ndarray:
    if mesh is None or spec is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _reduce_one_leaf(
    g: jnp.ndarray,
    ef: jnp.ndarray,
    key: jax.Array,
    spec: P | None,
    *,
    mesh: Mesh | None,
    mesh_axes: Mapping[str, int],
    block: int,
    min_quant_elems: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One leaf of the quantized reduction: ``(W, *shape)`` tiled partial
    grads + EF -> (reduced grad in param layout, new EF)."""
    workers = int(g.shape[0])
    shape = tuple(g.shape[1:])
    spec = spec if spec is not None else P()
    inner = list(spec) + [None] * (len(shape) - len(spec))
    last_shards = _spec_axes_size(inner[-1] if inner else None, mesh_axes)
    eff = block_size_for(shape[-1] if shape else 1, last_shards, block)
    small = int(math.prod(shape)) < int(min_quant_elems) or eff < 8
    if small:
        # fp32 fallback: the leaf is wire noise; its EF stays zero
        return jnp.sum(g, axis=0), jnp.zeros_like(ef)

    t_spec = tiled_spec(P(*inner))
    c = _pin(g + ef, t_spec, mesh)
    q, scale = quantize_blocks(c, key, block=eff, shared_over_workers=True)
    # the residual is LOCAL — each worker knows its own quantization error
    new_ef = c - dequantize_blocks(q, scale[None], block=eff)

    # pin the s8 stack to the source layout, then reshard: without the
    # source pin GSPMD hoists the reshard above the quantize and the wire
    # carries fp32 (measured)
    q = _pin(q, t_spec, mesh)
    if mesh is not None:
        q = jax.lax.optimization_barrier(q)

    worker_axes = tuple(GRAD_WORKER_AXES)
    lead_entry = inner[0] if inner else None
    lead_axes = (
        () if lead_entry is None
        else (lead_entry if isinstance(lead_entry, tuple) else (lead_entry,))
    )
    lead_shards = _spec_axes_size(lead_entry, mesh_axes)
    can_scatter = (
        len(shape) >= 1 and shape[0] % (workers * max(1, lead_shards)) == 0
    )

    if can_scatter and mesh is not None:
        # reduce-scatter leg: worker dim gathers, the leading param dim
        # additionally scatters over the worker axes -> s8 all-to-all
        rs_inner = (tuple(worker_axes) + tuple(lead_axes)) or None
        rs_spec = P(None, rs_inner, *inner[1:])
        q = jax.lax.optimization_barrier(
            jax.lax.with_sharding_constraint(q, NamedSharding(mesh, rs_spec))
        )
        ssum = jnp.sum(q.astype(jnp.int32), axis=0)  # int-safe, order-free
        deq = dequantize_blocks(ssum, scale, block=eff)
        # return leg: requantize the reduced value (fresh scales, fresh
        # stochastic rounding — unbiased, uncompensated) and all-gather s8
        r_spec = P(rs_inner, *inner[1:])
        deq = _pin(deq, r_spec, mesh)
        q2, scale2 = quantize_blocks(
            deq, jax.random.fold_in(key, 1), block=eff, shared_over_workers=False
        )
        q2 = jax.lax.optimization_barrier(_pin(q2, r_spec, mesh))
        q2 = jax.lax.optimization_barrier(_pin(q2, P(*inner), mesh))
        # gather the (tiny) return-leg scales to the OUTPUT layout before
        # the dequantize multiply: with the scales left worker-sharded,
        # GSPMD computes the product on THEIR sharding and all-gathers the
        # f32 result — re-paying in f32 the bytes the s8 gather just saved
        # (measured: full-leaf f32 all-gathers next to the s8 ones)
        scale2 = _pin(scale2, P(*inner[:-1], None), mesh)
        out = dequantize_blocks(q2, scale2, block=eff)
        out = _pin(out, P(*inner), mesh)
    else:
        # all-gather leg (ragged leading dim, or no mesh): gather the s8
        # worker stack whole and integer-sum locally — still int-safe and
        # order-free, W x the census bytes of the scatter path
        if mesh is not None:
            q = jax.lax.optimization_barrier(
                jax.lax.with_sharding_constraint(
                    q, NamedSharding(mesh, P(None, *inner))
                )
            )
        ssum = jnp.sum(q.astype(jnp.int32), axis=0)
        out = _pin(dequantize_blocks(ssum, scale, block=eff), P(*inner), mesh)
    return out, _pin(new_ef, t_spec, mesh)


def quantized_tree_reduce(
    tiled_grads: Any,
    ef: Any,
    key: jax.Array,
    *,
    mesh: Mesh | None = None,
    param_specs: Any = None,
    block: int = QUANT_BLOCK,
    min_quant_elems: int = MIN_QUANT_ELEMS,
) -> tuple[Any, Any]:
    """The quantize-reduce-dequantize wrapper over a worker-tiled gradient
    tree: ``(W, *shape)`` partial sums per leaf -> (reduced fp32 gradients
    in param layout, new error-feedback tree).

    ``mesh=None`` runs the identical math without sharding pins (the
    pure-function path unit tests exercise); ``param_specs`` is the tree
    of param PartitionSpecs the inner dims mirror (None leaves =
    unsharded).  The sum of reduced gradients over steps telescopes to
    the sum of true gradient sums up to the final residual (plus the
    return leg's zero-mean stochastic-rounding noise).
    """
    mesh_axes = dict(mesh.shape) if mesh is not None else {}
    leaves, treedef = jax.tree_util.tree_flatten(tiled_grads)
    ef_leaves = jax.tree_util.tree_leaves(ef)
    if len(ef_leaves) != len(leaves):
        raise ValueError(
            f"error-feedback tree has {len(ef_leaves)} leaves for a "
            f"{len(leaves)}-leaf gradient tree — create it with "
            "zero_error_feedback(params, workers)"
        )
    if param_specs is None:
        spec_leaves: list[Any] = [None] * len(leaves)
    else:
        spec_leaves = jax.tree_util.tree_leaves(
            param_specs, is_leaf=lambda x: x is None or isinstance(x, P)
        )
    out_leaves: list[jnp.ndarray] = []
    new_ef_leaves: list[jnp.ndarray] = []
    for i, (g, e, s) in enumerate(zip(leaves, ef_leaves, spec_leaves)):
        r, ne = _reduce_one_leaf(
            g, e, jax.random.fold_in(key, i), s,
            mesh=mesh, mesh_axes=mesh_axes,
            block=block, min_quant_elems=min_quant_elems,
        )
        out_leaves.append(r)
        new_ef_leaves.append(ne)
    return (
        jax.tree_util.tree_unflatten(treedef, out_leaves),
        jax.tree_util.tree_unflatten(treedef, new_ef_leaves),
    )
