#!/usr/bin/env python
"""One-command real-weights ROUGE parity vs the reference recipe.

The reference fine-tunes ``facebook/bart-large-cnn`` on the SAMSum-style
``train.json``/``val.json`` (reference valohai.yaml:8-24) with AdamW 5e-5,
linear schedule, warmup 500, src 1024 / tgt 128, then reports ROUGE via
beam-search generation (reference train-accelerator.py:93-112).  This
script runs the SAME data and hyperparameters through this framework and
reports ROUGE, optionally next to a reference leg for a measured delta:

    # full parity run (needs egress or pre-staged inputs):
    python scripts/rouge_parity.py \
        --model-ckpt facebook/bart-large-cnn \
        --train-file train.json --val-file val.json --reference-run

    # air-gapped: pre-stage the checkpoint + tokenizer as a local dir
    # (config.json, model.safetensors, tokenizer.json...) and pass its
    # path as --model-ckpt; data files are plain local JSON.

    # compare against previously recorded reference scores instead of
    # re-running the torch leg:
    python scripts/rouge_parity.py ... --reference-scores ref_scores.json

    # CI smoke (no network, no weights): exercises the full plumbing on
    # the built-in tiny model + byte tokenizer with synthetic data:
    python scripts/rouge_parity.py --smoke

The download boundary is isolated in ``acquire_model``: everything after
it is local-only.  Both legs are scored with this repo's self-contained
ROUGE implementation so the delta measures the *pipelines*, not two
different metric packages.

Output: ONE JSON line ``{"ours": {...}, "reference": {...}|null,
"delta": {...}|null}``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def acquire_model(model_ckpt: str) -> str:
    """Resolve the checkpoint to a LOCAL directory — the only stage that
    may touch the network.  Air-gapped path: pre-stage the HF checkpoint
    directory and pass its path."""
    if os.path.isdir(model_ckpt):
        return model_ckpt
    try:
        from huggingface_hub import snapshot_download

        return snapshot_download(model_ckpt)
    except Exception as e:
        raise SystemExit(
            f"cannot acquire {model_ckpt!r}: not a local directory and the "
            f"download failed ({type(e).__name__}: {e}).  In air-gapped "
            "environments pre-stage the HF checkpoint (config.json + "
            "model.safetensors + tokenizer files) and pass the directory "
            "path as --model-ckpt."
        ) from None


def load_records(path: str):
    from distributed_llms_example_tpu.data.dataset import load_json_records

    return load_json_records(path)


def finetune_and_score_ours(args, model_dir: str, train_recs, val_recs) -> dict:
    """Our leg: the framework Trainer on the reference hyperparameters,
    final ROUGE from its end-of-training eval."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    cfg = TrainConfig(
        model_ckpt=model_dir,
        output_dir=args.output_dir,
        batch_size=args.batch_size,
        num_epochs=args.num_epochs,
        warmup_steps=args.warmup_steps,
        evaluation_steps=0,  # final eval only: the parity number
        learning_rate=args.learning_rate,
        max_source_length=1024,
        max_target_length=128,
        num_beams=args.num_beams,
        eval_max_new_tokens=128,
        tokenizer=args.tokenizer or "",
        log_every_steps=50,
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
    )
    trainer = Trainer(cfg, train_records=train_recs, val_records=val_recs)
    result = trainer.train()
    scores = {k: v for k, v in result["final_eval"].items() if k.startswith("rouge")}
    if not scores:  # e.g. evaluation disabled by mesh shape — rerun eval directly
        scores = {k: v for k, v in trainer.evaluate().items() if k.startswith("rouge")}
    return scores


def finetune_and_score_reference(args, model_dir: str, train_recs, val_recs) -> dict:
    """Reference leg: an independent torch/transformers fine-tune with the
    reference's hyperparameters (AdamW 5e-5, linear schedule with warmup,
    teacher forcing on tokenizer(text_target=...) labels, beam-search
    generation) — scored with the SAME self-contained ROUGE as our leg."""
    import torch
    from transformers import AutoModelForSeq2SeqLM, AutoTokenizer, get_linear_schedule_with_warmup

    from distributed_llms_example_tpu.evaluation import rouge

    tok = AutoTokenizer.from_pretrained(model_dir, local_files_only=True)
    model = AutoModelForSeq2SeqLM.from_pretrained(model_dir, local_files_only=True)
    device = "cuda" if torch.cuda.is_available() else "cpu"
    model.to(device).train()
    opt = torch.optim.AdamW(model.parameters(), lr=args.learning_rate)
    n_steps = max(1, (len(train_recs) // args.batch_size)) * args.num_epochs
    sched = get_linear_schedule_with_warmup(opt, args.warmup_steps, n_steps)

    def batches(recs):
        for i in range(0, len(recs) - args.batch_size + 1, args.batch_size):
            chunk = recs[i : i + args.batch_size]
            enc = tok([str(r["dialogue"]) for r in chunk], max_length=1024,
                      truncation=True, padding=True, return_tensors="pt")
            lab = tok(text_target=[str(r["summary"]) for r in chunk], max_length=128,
                      truncation=True, padding=True, return_tensors="pt")
            labels = lab["input_ids"].masked_fill(lab["input_ids"] == tok.pad_token_id, -100)
            yield {**{k: v.to(device) for k, v in enc.items()}, "labels": labels.to(device)}

    for _ in range(args.num_epochs):
        for batch in batches(train_recs):
            loss = model(**batch).loss
            loss.backward()
            torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
            opt.step()
            sched.step()
            opt.zero_grad()

    model.eval()
    preds, refs = [], []
    with torch.no_grad():
        for i in range(0, len(val_recs), args.batch_size):
            chunk = val_recs[i : i + args.batch_size]
            enc = tok([str(r["dialogue"]) for r in chunk], max_length=1024,
                      truncation=True, padding=True, return_tensors="pt").to(device)
            # length_penalty 1.0 matches the framework Evaluator's
            # default — the delta must measure the pipelines, not a
            # generation-hyperparameter mismatch
            out = model.generate(
                **enc, num_beams=args.num_beams, max_new_tokens=128, length_penalty=1.0
            )
            preds += tok.batch_decode(out, skip_special_tokens=True)
            refs += [str(r["summary"]) for r in chunk]
    return {k: v for k, v in rouge.compute(preds, refs).items() if k.startswith("rouge")}


def smoke_args(args) -> None:
    """CI mode: tiny built-in model, byte tokenizer, synthetic data —
    every stage after the download boundary runs for real."""
    import numpy as np

    rng = np.random.RandomState(0)
    recs = [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(rng.randint(8, 24))),
            "summary": " ".join(f"w{rng.randint(40)}" for _ in range(4)),
        }
        for _ in range(24)
    ]
    d = tempfile.mkdtemp(prefix="rouge_parity_smoke_")
    for name, part in (("train.json", recs[:16]), ("val.json", recs[16:])):
        with open(os.path.join(d, name), "w") as f:
            json.dump(part, f)
    args.model_ckpt = "t5-test"
    args.tokenizer = "byte"
    args.train_file = os.path.join(d, "train.json")
    args.val_file = os.path.join(d, "val.json")
    args.batch_size = 8
    args.warmup_steps = 0
    args.num_beams = 1
    args.reference_run = False


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model-ckpt", default="facebook/bart-large-cnn")
    p.add_argument("--train-file", default="train.json")
    p.add_argument("--val-file", default="val.json")
    p.add_argument("--output-dir", default="")
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--warmup-steps", type=int, default=500)
    p.add_argument("--learning-rate", type=float, default=5e-5)
    p.add_argument("--num-beams", type=int, default=2)
    p.add_argument("--tokenizer", default="", help="tokenizer path override; default = model dir")
    p.add_argument("--reference-run", action="store_true",
                   help="also fine-tune+score with an independent torch recipe")
    p.add_argument("--reference-scores", default="",
                   help="JSON file of recorded reference ROUGE scores to diff against")
    p.add_argument("--smoke", action="store_true",
                   help="no-network CI mode: tiny model + synthetic data")
    args = p.parse_args()

    if args.smoke:
        smoke_args(args)
    args.output_dir = args.output_dir or tempfile.mkdtemp(prefix="rouge_parity_")

    # registry names (t5-test etc.) resolve in-framework; only real HF
    # checkpoints cross the download boundary
    from distributed_llms_example_tpu.models.registry import (
        BART_CONFIGS,
        LLAMA_CONFIGS,
        T5_CONFIGS,
    )

    known = set(T5_CONFIGS) | set(BART_CONFIGS) | set(LLAMA_CONFIGS)
    local = args.model_ckpt in known or os.path.isdir(args.model_ckpt)
    if args.reference_run and args.model_ckpt in known and not os.path.isdir(args.model_ckpt):
        raise SystemExit(
            f"--reference-run needs a real HF checkpoint; {args.model_ckpt!r} is a "
            "framework registry name transformers cannot load"
        )
    model_dir = args.model_ckpt if local else acquire_model(args.model_ckpt)
    train_recs = list(load_records(args.train_file))
    val_recs = list(load_records(args.val_file))

    ours = finetune_and_score_ours(args, model_dir, train_recs, val_recs)
    reference = None
    if args.reference_scores:
        with open(args.reference_scores) as f:
            reference = {k: float(v) for k, v in json.load(f).items() if k.startswith("rouge")}
    elif args.reference_run:
        reference = finetune_and_score_reference(args, model_dir, train_recs, val_recs)
    delta = (
        {k: round(ours[k] - reference[k], 6) for k in ours if k in reference}
        if reference else None
    )
    print(json.dumps({"ours": ours, "reference": reference, "delta": delta}))


if __name__ == "__main__":
    main()
