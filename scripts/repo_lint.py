#!/usr/bin/env python
"""AST lint for repo conventions the type system cannot hold.

Seventeen rules, all born from real regressions at TPU scale:

1. **No host syncs in the train-step hot path.**  ``jax.device_get`` /
   ``.block_until_ready()`` inside ``train/step.py`` stall async dispatch —
   one stray sync in the step function serializes every device round-trip
   and the pipelining the whole module exists for is gone.

2. **No bare PartitionSpec literals outside the sharding layer.**  A
   ``P("tensro", ...)`` typo'd in some far-away module bypasses every rule
   check and surfaces as an opaque KeyError inside jax.  Axis-name specs
   belong in ``parallel/`` (the sharding/pipeline layer); the few
   historical exceptions are pinned in an explicit allowlist so NEW ones
   fail review here.

3. **No direct ``print(json.dumps(...))`` metric emission outside the
   sink layer.**  The JSON-lines stdout stream is a parsed platform
   contract (Valohai metadata) with one schema and one process gate —
   a rogue producer bypasses the ``--obs`` sink (its records never reach
   the JSONL file channel), skips ``schema_version`` stamping, and emits
   from every process.  Emission belongs in ``obs/`` and
   ``utils/jsonlog.py``; everyone else calls ``log_json``.

4. **No device→host conversions on step-cadence paths outside the
   log-cadence window.**  ``float(...)`` / ``.item()`` /
   ``jax.device_get`` on a value the step loop produced is a device sync
   — one per step serializes async dispatch, the exact invariant the
   health telemetry is designed around ("values ride the existing
   log-cadence fetch").  The files whose code runs at step cadence are
   enumerated in ``STEP_CADENCE_FILES`` with the functions that ARE the
   cadence window (summary emission, health resolve, recorder dump,
   build-time constructors) allowlisted by name; a conversion anywhere
   else in those files fails here.

5a. **No second gradient-accumulation layer in models/ and train/.**
   ``train/step.py`` owns in-step accumulation (the lax.scan with fp32
   accumulators sharded like the params, ONE optimizer apply per step)
   and the pipeline executors (parallel/) own their schedule-internal
   microbatching.  A manual ``acc += grads`` / ``tree.map(add, acc,
   grads)`` anywhere else in models/ or train/ is a rogue third layer:
   it would double-count against the step's scan, its accumulators would
   carry no sharding contract (a replicated fp32 param-tree per device),
   and the once-per-step optimizer census could no longer prove
   anything.  Flagged: augmented ``+=`` on grad-named values and
   tree-map calls combining an add with grad-named operands, outside
   ``train/step.py``.

5. **No raw dropout primitives in models/ and train/.**  ``nn.Dropout``
   or ``jax.random.bernoulli`` in a model or train file bypasses the
   shared dropout helper (``ops/fused_dropout.py``) — the call site would
   silently miss the fused Pallas path (``--dropout-impl``), its mask
   would be threefry-generated and HBM-materialized again, and the
   fused-vs-xla A/B in bench.py would no longer cover it.  Dropout goes
   through ``ops.fused_dropout.Dropout`` / ``dropout``; raw primitives
   are allowed only inside ``ops/`` (the helper and the attention
   reference path are the implementation).

6. **No bare orbax ``manager.save`` / ``manager.restore`` outside
   ``io/checkpoint.py``.**  The Checkpointer wrappers are where save
   retry-with-backoff, the checksum-manifest sidecar, and
   verify-before-restore-with-fallback live — a direct ``manager.save``
   skips the manifest (its checkpoint can never be verified) and a
   direct ``manager.restore`` trusts a possibly-corrupt highest step
   unconditionally, the exact crash the integrity layer exists to
   prevent.  Everything goes through ``Checkpointer.save`` /
   ``restore_latest`` / ``restore_before``.

7. **No Chrome-trace event emission outside ``obs/trace.py``.**  The
   Perfetto export's value is being the ONE merged timeline: a module
   that builds its own ``{"ph": ..., "ts": ...}`` event dicts (or a
   ``"traceEvents"`` container) produces a rogue trace file with its own
   clock epoch, no cross-rank step alignment, and no schema the report
   CLI knows — the same fragmentation the sink-bypass rule (3) exists to
   prevent on the metric channel.  Trace event construction lives in
   ``obs/trace.py``; everyone else emits spans through the span recorder
   and lets the exporter render them.

8. **No raw optimizer apply in models/ and train/ outside
   ``train/optim.py``.**  ``optax.apply_updates`` (or a hand-rolled
   ``p - lr*u`` tree-map) anywhere else bypasses the ``--optim-impl``
   dispatch in ``optimizer_apply_block``: the call site would silently
   miss the fused Pallas clip+AdamW path, its update would not ride the
   in-place/aliasing contract the IR census checks, and the fused-vs-xla
   bit-equivalence pin would no longer cover it — the optimizer twin of
   rules 5/5a.  The apply is owned by ``train.optim.optimizer_update``
   (xla impl) and ``fused_optimizer_apply`` (fused impl).

9. **No hand-rolled gradient collectives or gradient quantization in
   models/ and train/ outside ``train/step.py``.**  A raw ``lax.psum`` /
   ``psum_scatter`` / ``all_to_all`` over a gradient tree — or a manual
   ``grads.astype(int8)`` quantize/dequantize — bypasses the
   ``--grad-compression`` dispatch (``ops/quant_collectives.py``): the
   call site would silently miss the error-feedback buffer (its
   quantization error is LOST, not carried), its bytes would not ride
   the int-safe shared-scale wire protocol the census proves, and the
   off-path bit-identity pin would no longer cover it.  The compression
   layer is the one owner; the step (``train/step.py``) is the one
   caller.

10. **No raw int8 casts of KV-cache values outside the owning modules.**
   ``ops/flash_attention.py`` (quantize_kv/dequantize_kv + in-kernel
   dequant) and ``serving/cache_pool.py`` own the int8 KV cache's
   number format.  A stray ``k.astype(jnp.int8)`` in models/, serving/
   or evaluation/ forks the format: its values would quantize without
   the per-head per-position scale contract, the kernel and XLA decode
   paths would stop reconstructing identical K/V, and the token-parity
   pins (engine == static under int8) would no longer cover it.  In
   those dirs (plus ops/mha.py, the cache-write site) ANY
   ``.astype(int8/uint8)`` fails here — creation via ``jnp.zeros(...,
   jnp.int8)`` is allocation, not quantization, and stays legal.

11. **No mesh construction or ``jax.distributed`` lifecycle calls
   outside ``core/mesh.py``.**  Elastic training (ISSUE 14) makes the
   distributed bootstrap a thing that happens MID-RUN: the
   topology-change path shuts the client down and re-initializes it on
   the surviving slice, and the resharding restore assumes every mesh
   in the process came from the one constructor (axis names, ICI-aware
   device order, the gloo-on-CPU flag).  A stray ``Mesh(...)`` or
   ``jax.distributed.initialize/shutdown`` elsewhere forks that
   lifecycle: its mesh would skip topology-aware device ordering, and a
   second initializer would fight the re-init path's teardown ordering.
   ``build_mesh`` / ``initialize_distributed`` /
   ``reinitialize_distributed`` in ``core/mesh.py`` are the owners.

12. **No ad-hoc retry loops — ``time.sleep`` inside an ``except``
   handler — outside the designated backoff helper
   (``utils/backoff.py``).**  A hand-rolled sleep-in-except is a retry
   loop with its own (usually unbounded, uncapped) policy: invisible to
   the shared capped-exponential schedule, no ``*_retry`` event before
   the sleep, and in the serving tier it would block the router's
   single scheduler thread where the tick-unit backoff
   (``backoff_ticks``) is the sanctioned form.  Retry sleeps go through
   ``utils.backoff.sleep_backoff``; any call named ``sleep`` lexically
   inside an except handler elsewhere fails here.

13. **No bare rank conditionals — ``jax.process_index()`` /
   ``process_count()`` inside an ``if``/``while``/ternary/assert test —
   outside the whitelisted owners.**  A branch on raw rank identity is
   the seed of every pod-deadlock bug class this repo has shipped review
   fixes for (the one-rank walk-back, the p0-only verdict, the
   rank-varying retry ladder): the moment the branch reaches a
   collective, ranks disagree about the collective sequence.  The owners
   — ``core/mesh.py`` (bootstrap), ``obs/heartbeat.py`` (the agreement
   channel itself), ``io/checkpoint.py`` (the agreement helpers), and
   ``obs/sink.py`` (the p0 emission gate) — are where rank branching is
   the mechanism; everyone else routes decisions through the agreement
   helpers (``_agreed_ok``/``_agreed_step``/``_agreed_count``/
   ``gather_probe`` — the registry in ``analysis/divergence.py``) or
   annotates the line ``# pod-agreed: <mechanism>`` naming why the
   branch is pod-uniform (e.g. ``process_count() == 1`` fast paths: the
   count is the same number everywhere).  The taint-tracking twin of
   this lexical rule is the divergence pass (``analysis/divergence.py``),
   which follows rank-local values into collectives across assignments.

14. **No inline percentile/quantile computation outside ``obs/spans.py``.**
   The repo has ONE quantile definition — ``obs.spans.percentiles``
   (nearest-rank over sorted values) — and every tail-latency gate
   (ttft_p99, queue_delay_p99, the loadgen SLO curves) compares numbers
   produced by it.  A stray ``np.percentile(..., 99)`` (linear
   interpolation by default) or a hand-rolled ``sorted(xs)[int(0.99 *
   len(xs))]`` (off-by-one at the rank boundary) silently disagrees with
   the owner on small samples — exactly where serving p99s live — so
   two reports of the same run would gate differently.  Flagged: calls
   named ``percentile``/``quantile``/``nanpercentile``/``nanquantile``
   in any spelling, and subscripts of a ``sorted(...)`` result whose
   index arithmetic involves ``len``/a multiplication (the sorted-index
   idiom).  Everyone imports ``percentiles`` from the owner.

15. **No raw ``memory_stats()`` / ``live_buffers()`` reads outside the
   memory owners.**  ``obs/memprof.py`` (runtime watermarks, OOM
   forensics) and ``utils/memory_audit.py`` (the static audit CLI) own
   every HBM byte count.  A stray ``d.memory_stats()`` elsewhere forks
   the account the report gates on: its reading skips the
   absent-beats-zero contract (CPU PJRT returns nothing — a raw read
   happily stamps 0), its "peak" is the process-lifetime allocator
   high-water mark with no ``Watermark`` mark/delta semantics (every
   per-phase claim built on it is silently cumulative), and its numbers
   never reach the ``memory_window`` events the "Where did the bytes
   go" report renders.  Readers call ``memprof.hbm_stats()`` /
   ``Watermark`` — one read path, one semantics.

16. **No KV-block identity outside ``serving/cache_pool.py``.**  The
   chained content hash and the refcount ledger ARE the correctness
   argument for cross-request block sharing: a second hash definition
   in serving/ forks the identity (two prefixes collide, or identical
   prefixes stop matching), and a refcount poked from outside the
   owner breaks the refcount == live-references invariant its own
   ``ref_invariant_violations()`` audits.  Everyone else uses the
   public API: chain_hashes / match_chain / acquire / register / free
   / drop_warm.

17. **No speculative-decode acceptance math outside ``serving/spec.py``
   (+ the cache_pool span scatter).**  The acceptance rule IS the
   bit-identity contract — accept the longest draft == target-argmax
   prefix, emit the target's bonus token, rebuild the mask span.  An
   inline draft-vs-target compare or cumprod prefix fold in the engine
   or router is a second copy of that contract; the copies drift, and
   "spec output == greedy output" stops being one provable property.

Run: ``python scripts/repo_lint.py`` (nonzero exit on violations).  Wired
into the fast test suite (tests/test_analysis.py, tests/test_obs.py,
tests/test_health.py) next to the analysis-CLI smoke run.
"""

from __future__ import annotations

import ast
import os
import sys

PACKAGE = "distributed_llms_example_tpu"

# Files where .block_until_ready / jax.device_get would poison the async
# dispatch pipeline.
HOT_PATH_FILES = (
    os.path.join(PACKAGE, "train", "step.py"),
)

# Directories whose job IS axis-name specs.
SPEC_LAYER_DIRS = (
    os.path.join(PACKAGE, "parallel"),
)

# Pinned exceptions: (file, why).  Add here only with a comment-worthy
# reason — the point is that new bare specs fail loudly.
SPEC_LITERAL_ALLOWLIST = {
    # micro-batch sharding constraint for the grad-accum scan; the axis
    # tuple mirrors batch_sharding() and changing either means both
    os.path.join(PACKAGE, "train", "step.py"),
    # the MoE dispatch spec is part of the expert-parallel kernel contract
    os.path.join(PACKAGE, "ops", "moe.py"),
}

FORBIDDEN_SYNC_ATTRS = ("block_until_ready",)
FORBIDDEN_SYNC_CALLS = (("jax", "device_get"),)

# The sink layer: the only places allowed to print JSON lines directly.
JSON_EMIT_ALLOW_DIRS = (
    os.path.join(PACKAGE, "obs"),
)
JSON_EMIT_ALLOW_FILES = {
    os.path.join(PACKAGE, "utils", "jsonlog.py"),
}

# Files whose code runs at STEP cadence: device→host conversions
# (float(), .item(), jax.device_get) are forbidden outside the named
# functions — which are exactly the log-cadence window (summary/health
# resolve, dump paths) and build-time constructors.  Guards the
# zero-extra-syncs invariant the in-graph health telemetry depends on.
STEP_CADENCE_FILES: dict[str, frozenset] = {
    # the step function itself is all device-side; make_loss_fn/
    # make_train_step run once at build time (config floats)
    os.path.join(PACKAGE, "train", "step.py"): frozenset(
        {"make_loss_fn", "make_train_step"}
    ),
    # span() / step_complete() are per-step; summary() IS the cadence
    os.path.join(PACKAGE, "obs", "spans.py"): frozenset(
        {"__init__", "summary", "percentiles"}
    ),
    # record() is per-step; annotate()/dump() run at cadence / shutdown
    os.path.join(PACKAGE, "obs", "recorder.py"): frozenset(
        {"annotate", "dump", "_to_jsonable", "batch_fingerprint"}
    ),
    # the watchdog's one device_get lives in to_host (cadence only)
    os.path.join(PACKAGE, "obs", "health.py"): frozenset(
        {"__init__", "to_host", "_check_one", "_absorb", "check", "agree_and_emit"}
    ),
    # on_step appends pointers; everything that converts is cadenced
    os.path.join(PACKAGE, "obs", "__init__.py"): frozenset(
        {"__init__", "_health_cadence", "emit_window", "window_mfu",
         "startup_gauges", "finalize"}
    ),
}
CADENCE_SYNC_CALLS = (("jax", "device_get"),)

# Rule 5: directories whose dropout must route through the shared helper
# (ops/fused_dropout.py).  ops/ itself is the implementation layer and
# parallel/ hosts the pipeline shim that delegates to the helper.
DROPOUT_RULE_DIRS = (
    os.path.join(PACKAGE, "models"),
    os.path.join(PACKAGE, "train"),
)

# Rule 5a: gradient accumulation is owned by train/step.py (the in-step
# scan) and the pipeline executors (parallel/); a manual accumulator
# anywhere else in these dirs is a rogue second accumulation layer.
GRAD_ACCUM_RULE_DIRS = DROPOUT_RULE_DIRS
GRAD_ACCUM_OWNER = os.path.join(PACKAGE, "train", "step.py")
_GRAD_NAMES = ("grad", "grads", "gradient")

# Rule 6: checkpoint save/restore is owned by io/checkpoint.py — its
# wrappers carry the retry/backoff, checksum manifest, and
# verify-with-fallback contracts a bare manager call would skip.
CKPT_OWNER = os.path.join(PACKAGE, "io", "checkpoint.py")
_MANAGER_NAMES = ("manager", "_manager", "checkpoint_manager", "ckpt_manager")

# Rule 7: Chrome-trace/Perfetto event dicts are built only in the trace
# exporter — a second producer means a second clock epoch and no
# cross-rank alignment.
TRACE_OWNER = os.path.join(PACKAGE, "obs", "trace.py")

# rule 11: the ONE owner of mesh construction and the jax.distributed
# lifecycle (init/shutdown/reinit) — the elastic-recovery path re-enters
# both mid-run, so a second constructor/initializer elsewhere would fork
# the teardown ordering and the device-order contract
MESH_OWNER = os.path.join(PACKAGE, "core", "mesh.py")

# Rule 9: gradient collectives / quantization are owned by
# ops/quant_collectives.py, called only from train/step.py — a raw
# psum/psum_scatter/all_to_all (or int8 cast) over grad-named values
# anywhere else in models/ and train/ bypasses the --grad-compression
# dispatch and its error-feedback contract.
GRAD_COLLECTIVE_RULE_DIRS = DROPOUT_RULE_DIRS
GRAD_COLLECTIVE_OWNER = os.path.join(PACKAGE, "train", "step.py")
_GRAD_COLLECTIVE_FNS = ("psum", "psum_scatter", "pmean", "all_to_all")

# Rule 8: the optimizer apply is owned by train/optim.py — raw
# optax.apply_updates / manual p - lr*u tree-maps elsewhere in models/
# and train/ bypass the --optim-impl dispatch (fused Pallas apply,
# in-place contract, bit-equivalence pin).
OPTIM_RULE_DIRS = DROPOUT_RULE_DIRS
OPTIM_OWNER = os.path.join(PACKAGE, "train", "optim.py")
_LR_NAMES = ("lr", "learning_rate", "step_size")

# Rule 10: the int8 KV cache's number format is owned by
# ops/flash_attention.py (quantize_kv / dequantize_kv / in-kernel tile
# dequant) and serving/cache_pool.py.  Any raw astype-to-int8 in the
# dirs that touch cache values forks the format outside the scale
# contract; jnp.zeros(..., jnp.int8) allocation stays legal.
KV_CAST_RULE_DIRS = (
    os.path.join(PACKAGE, "models"),
    os.path.join(PACKAGE, "serving"),
    os.path.join(PACKAGE, "evaluation"),
)
KV_CAST_RULE_FILES = {os.path.join(PACKAGE, "ops", "mha.py")}
KV_CAST_OWNERS = {
    os.path.join(PACKAGE, "ops", "flash_attention.py"),
    os.path.join(PACKAGE, "serving", "cache_pool.py"),
}

# Rule 12: retry sleeps are owned by utils/backoff.py (capped
# exponential schedule, one definition); a sleep inside an except
# handler anywhere else is an ad-hoc retry loop.
BACKOFF_OWNER = os.path.join(PACKAGE, "utils", "backoff.py")

# Rule 13: bare rank conditionals live only where rank branching IS the
# mechanism — the bootstrap, the agreement channel, the agreement
# helpers, and the p0 emission gate.  Everyone else goes through the
# agreement helpers or carries a `# pod-agreed: <mechanism>` pragma.
RANK_CONDITIONAL_OWNERS = {
    os.path.join(PACKAGE, "core", "mesh.py"),
    os.path.join(PACKAGE, "obs", "heartbeat.py"),
    os.path.join(PACKAGE, "io", "checkpoint.py"),
    os.path.join(PACKAGE, "obs", "sink.py"),
}
_RANK_CALLS = ("process_index", "process_count")
_POD_AGREED_PRAGMA = "# pod-agreed:"

# Rule 14: the quantile definition is owned by obs/spans.py
# (`percentiles`, nearest-rank) — every tail-latency gate compares its
# numbers, so a second definition (np.percentile's interpolation, a
# sorted-index one-liner) disagrees exactly on the small samples where
# serving p99s live.
PERCENTILE_OWNER = os.path.join(PACKAGE, "obs", "spans.py")
_PERCENTILE_FNS = ("percentile", "quantile", "nanpercentile", "nanquantile")

# Rule 15: HBM byte counts have two owners — the runtime side
# (obs/memprof.py: hbm_stats/Watermark/postmortems) and the static audit
# (utils/memory_audit.py).  A raw memory_stats()/live_buffers() read
# anywhere else forks the absent-beats-zero and watermark-delta
# semantics the report's memory gates are built on.
MEMSTATS_OWNERS = {
    os.path.join(PACKAGE, "obs", "memprof.py"),
    os.path.join(PACKAGE, "utils", "memory_audit.py"),
}
_MEMSTATS_FNS = ("memory_stats", "live_buffers")

# Rule 16: KV-block identity is owned by serving/cache_pool.py — the
# chained content hash and the refcount ledger ARE the correctness
# argument for cross-request block sharing.  A second hash definition
# (or a refcount poked from outside the owner) silently breaks the
# "refcount == live references" invariant the pool's own
# ref_invariant_violations() audits, and a divergent hash chain makes
# two different prefixes collide into one block.  Everyone else goes
# through the owner's public API: chain_hashes / match_chain / acquire
# / register / free / drop_warm.
PREFIX_IDENTITY_OWNER = os.path.join(PACKAGE, "serving", "cache_pool.py")
_PREFIX_LEDGER_ATTRS = ("_ref", "_hash_of", "_index", "_lru")
_PREFIX_HASH_MODULE = "hashlib"
PREFIX_HASH_RULE_DIRS = (os.path.join(PACKAGE, "serving"),)

# Rule 17: speculative-decode acceptance/rollback math is owned by
# serving/spec.py (the acceptance rule IS the bit-identity contract:
# accept the longest draft == target-argmax prefix, then the target's
# own bonus token) and serving/cache_pool.py (the span scatter whose
# sentinel discipline keeps speculative writes inside owned blocks).  A
# second acceptance expression inline in the engine or router — a
# draft-vs-target token compare, or the cumprod longest-prefix fold —
# forks the contract: the two copies drift, and "spec output ==
# greedy output" silently stops being one provable property.
SPEC_DECODE_OWNERS = {
    os.path.join(PACKAGE, "serving", "spec.py"),
    PREFIX_IDENTITY_OWNER,
}
SPEC_DECODE_RULE_DIRS = (os.path.join(PACKAGE, "serving"),)
_SPEC_DRAFT_NAMES = ("draft", "drafts", "proposed", "spec_toks")
_SPEC_TARGET_NAMES = ("target", "argmax", "verified")


def _names_contain_lr(node: ast.AST) -> bool:
    return any(
        any(t == name or name.endswith("_" + t) or name.startswith(t + "_")
            for t in _LR_NAMES)
        for name in _names_in(node)
    )


def _optim_apply_violations(tree: ast.AST, rel: str) -> list[str]:
    violations: list[str] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and (
                (isinstance(node.func, ast.Attribute) and node.func.attr == "apply_updates")
                or (isinstance(node.func, ast.Name) and node.func.id == "apply_updates")
            )
        ):
            violations.append(
                f"{rel}:{node.lineno}: raw apply_updates(...) outside "
                "train/optim.py bypasses the --optim-impl dispatch (fused "
                "Pallas clip+AdamW, in-place aliasing, bit-equivalence pin) "
                "— route through train.optim.optimizer_update / "
                "optimizer_apply_block"
            )
        elif (
            isinstance(node, ast.Call)
            and (
                (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("map", "tree_map", "tree_multimap")
                )
                or (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("tree_map", "tree_multimap")
                )
            )
            and node.args
            and isinstance(node.args[0], ast.Lambda)
            and any(
                isinstance(n, ast.BinOp)
                and isinstance(n.op, (ast.Sub, ast.Add))
                and any(
                    isinstance(side, ast.BinOp)
                    and isinstance(side.op, ast.Mult)
                    and _names_contain_lr(side)
                    for side in (n.left, n.right)
                )
                for n in ast.walk(node.args[0].body)
            )
        ):
            violations.append(
                f"{rel}:{node.lineno}: manual 'p - lr*u' tree-map optimizer "
                "apply outside train/optim.py — a hand-rolled update skips "
                "clip/AdamW/health AND the --optim-impl dispatch; use "
                "optimizer_apply_block (train/step.py)"
            )
    return violations


def _is_int8_node(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute):
        return node.attr in ("int8", "uint8")
    if isinstance(node, ast.Constant):
        return node.value in ("int8", "uint8")
    if isinstance(node, ast.Name):
        return node.id in ("int8", "uint8")
    return False


def _grad_collective_violations(tree: ast.AST, rel: str) -> list[str]:
    violations: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (
            fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name)
            else None
        )
        if name in _GRAD_COLLECTIVE_FNS and any(
            _is_grad_named(a) for a in list(node.args) + [
                kw.value for kw in node.keywords
            ]
        ):
            violations.append(
                f"{rel}:{node.lineno}: raw {name}(...) over a gradient "
                "tree outside train/step.py bypasses the "
                "--grad-compression dispatch (ops/quant_collectives.py: "
                "error feedback, shared-scale int8 wire, off-path "
                "bit-identity pin) — the step owns the gradient "
                "reduction"
            )
        elif (
            name == "astype"
            and isinstance(fn, ast.Attribute)
            and _is_grad_named(fn.value)
            and any(
                _is_int8_node(a)
                for a in list(node.args) + [kw.value for kw in node.keywords]
            )
        ):
            violations.append(
                f"{rel}:{node.lineno}: manual int8 cast of a gradient "
                "value outside train/step.py — hand-rolled gradient "
                "quantization loses its error to nowhere (no "
                "error-feedback buffer) and skips the shared-scale "
                "int-safe wire protocol; route through "
                "ops.quant_collectives.quantized_tree_reduce"
            )
    return violations


def _kv_cast_violations(tree: ast.AST, rel: str) -> list[str]:
    violations: list[str] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and any(
                _is_int8_node(a)
                for a in list(node.args) + [kw.value for kw in node.keywords]
            )
        ):
            violations.append(
                f"{rel}:{node.lineno}: raw .astype(int8) outside the KV "
                "quantization owners (ops/flash_attention.py, "
                "serving/cache_pool.py) — a hand-rolled int8 cast of cache "
                "values forks the number format away from the per-head "
                "per-position scale contract and breaks the kernel/XLA "
                "dequant identity; route through "
                "ops.flash_attention.quantize_kv / dequantize_kv"
            )
    return violations


def _retry_sleep_violations(tree: ast.AST, rel: str) -> list[str]:
    """Rule 12: any call named ``sleep`` (``time.sleep``, an aliased
    ``sleep``, a method ``.sleep``) lexically inside an ``except``
    handler, outside utils/backoff.py."""
    violations: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        for inner in ast.walk(node):
            if not isinstance(inner, ast.Call):
                continue
            fn = inner.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name)
                else None
            )
            if name == "sleep":
                violations.append(
                    f"{rel}:{inner.lineno}: sleep(...) inside an except "
                    "handler outside utils/backoff.py is an ad-hoc retry "
                    "loop — no capped schedule, no retry event, and it "
                    "would block the serving router's scheduler thread; "
                    "route wall-clock retry waits through "
                    "utils.backoff.sleep_backoff (tick-based paths use "
                    "backoff_ticks)"
                )
    return violations


def _rank_conditional_violations(
    tree: ast.AST, rel: str, src: str,
) -> list[str]:
    """Rule 13: a ``jax.process_index()`` / ``process_count()`` call
    inside the TEST of an ``if``/``while``/ternary/``assert``, outside
    the whitelisted owners, without a ``# pod-agreed:`` pragma on the
    call line or the statement line."""
    pragma_lines = {
        i for i, line in enumerate(src.splitlines(), start=1)
        if _POD_AGREED_PRAGMA in line
    }
    violations: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            test = node.test
        else:
            continue
        for inner in ast.walk(test):
            if not isinstance(inner, ast.Call):
                continue
            fn = inner.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name)
                else None
            )
            if name not in _RANK_CALLS:
                continue
            if node.lineno in pragma_lines or inner.lineno in pragma_lines:
                continue
            violations.append(
                f"{rel}:{inner.lineno}: bare `{name}()` conditional "
                "outside the rank-branching owners (core/mesh.py, "
                "obs/heartbeat.py, io/checkpoint.py, obs/sink.py) — a "
                "branch on raw rank identity feeding a collective "
                "deadlocks the pod; route the decision through an "
                "agreement helper (_agreed_ok/_agreed_step/_agreed_count/"
                "gather_probe — see analysis/divergence.py SANITIZERS) "
                "or annotate the line `# pod-agreed: <mechanism>` naming "
                "why the branch is pod-uniform"
            )
    return violations


def _percentile_violations(tree: ast.AST, rel: str) -> list[str]:
    """Rule 14: calls named percentile/quantile (any qualifier) and
    sorted-index quantile idioms — ``sorted(xs)[<arith with len/mult>]``
    — outside obs/spans.py."""
    violations: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name)
                else None
            )
            if name in _PERCENTILE_FNS:
                violations.append(
                    f"{rel}:{node.lineno}: {name}(...) outside obs/spans.py "
                    "forks the quantile definition (interpolation vs the "
                    "owner's nearest-rank) — tail-latency gates comparing "
                    "the two disagree on small samples; import "
                    "obs.spans.percentiles"
                )
        elif (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Name)
            and node.value.func.id == "sorted"
            and any(
                (isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Name)
                 and n.func.id == "len")
                or (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult))
                for n in ast.walk(node.slice)
            )
        ):
            violations.append(
                f"{rel}:{node.lineno}: sorted(...)[...] rank-index "
                "quantile idiom outside obs/spans.py — hand-rolled rank "
                "math is off-by-one at the boundary vs the owner's "
                "nearest-rank definition; import obs.spans.percentiles"
            )
    return violations


def _memstats_violations(tree: ast.AST, rel: str) -> list[str]:
    """Rule 15: calls named memory_stats/live_buffers (any qualifier)
    outside the memory owners (obs/memprof.py, utils/memory_audit.py)."""
    violations: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (
            fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name)
            else None
        )
        if name in _MEMSTATS_FNS:
            violations.append(
                f"{rel}:{node.lineno}: raw {name}(...) outside the memory "
                "owners (obs/memprof.py, utils/memory_audit.py) forks the "
                "HBM account — no absent-beats-zero contract (CPU PJRT "
                "stamps 0), no Watermark mark/delta semantics (per-phase "
                "peaks read as process-lifetime), invisible to the "
                "memory_window events the report gates on; read through "
                "memprof.hbm_stats()/Watermark"
            )
    return violations


def _prefix_identity_violations(tree: ast.AST, rel: str) -> list[str]:
    """Rule 16: the block-identity ledger (``._ref``/``._hash_of``/
    ``._index``/``._lru`` attribute access) anywhere outside the owner,
    and hashlib (import or call) anywhere in serving/ outside the owner
    — a second block-hash computation forks the chained-hash identity
    the pool's dedup is keyed on."""
    violations: list[str] = []
    in_serving = any(
        rel.startswith(d + os.sep) for d in PREFIX_HASH_RULE_DIRS
    )
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _PREFIX_LEDGER_ATTRS
        ):
            violations.append(
                f"{rel}:{node.lineno}: .{node.attr} access outside "
                "serving/cache_pool.py pokes the block-identity ledger "
                "directly — refcounts mutated outside the owner break the "
                "refcount == live-references invariant "
                "(ref_invariant_violations); go through acquire/register/"
                "free/match_chain/drop_warm"
            )
        elif in_serving and isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = (
                node.module if isinstance(node, ast.ImportFrom)
                else ",".join(a.name for a in node.names)
            )
            if mod and _PREFIX_HASH_MODULE in mod.split(","):
                violations.append(
                    f"{rel}:{node.lineno}: hashlib in serving/ outside "
                    "cache_pool.py — a second block-hash definition forks "
                    "the chained content identity (two prefixes can "
                    "collide, or identical prefixes stop matching); use "
                    "cache_pool.block_hash/chain_hashes"
                )
    return violations


def _spec_decode_violations(tree: ast.AST, rel: str) -> list[str]:
    """Rule 17: speculative acceptance/rollback math in serving/ outside
    its owners — a ``cumprod`` call (the longest-accepted-prefix fold)
    or an Eq compare whose one side is draft-named and other side
    target-named (the acceptance comparison itself)."""
    if not any(rel.startswith(d + os.sep) for d in SPEC_DECODE_RULE_DIRS):
        return []
    violations: list[str] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and (
            (isinstance(node.func, ast.Attribute) and node.func.attr == "cumprod")
            or (isinstance(node.func, ast.Name) and node.func.id == "cumprod")
        ):
            violations.append(
                f"{rel}:{node.lineno}: cumprod in serving/ outside "
                "serving/spec.py — the longest-accepted-prefix fold is "
                "the speculative acceptance rule, owned by "
                "spec.acceptance_lengths; a second copy drifts from the "
                "bit-identity contract"
            )
        elif isinstance(node, ast.Compare) and any(
            isinstance(op, ast.Eq) for op in node.ops
        ):
            sides = [node.left] + list(node.comparators)
            names = [_names_in(s) for s in sides]
            drafty = any(
                any(any(d in n for d in _SPEC_DRAFT_NAMES) for n in ns)
                for ns in names
            )
            targety = any(
                any(any(t in n for t in _SPEC_TARGET_NAMES) for n in ns)
                for ns in names
            )
            if drafty and targety:
                violations.append(
                    f"{rel}:{node.lineno}: draft-vs-target token compare "
                    "in serving/ outside serving/spec.py — inline "
                    "acceptance logic forks the bit-identity contract; "
                    "call spec.acceptance_lengths"
                )
    return violations


def _trace_emit_violations(tree: ast.AST, rel: str) -> list[str]:
    violations: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = {
            k.value
            for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        if "traceEvents" in keys or {"ph", "ts"} <= keys:
            violations.append(
                f"{rel}:{node.lineno}: Chrome-trace event dict "
                "('traceEvents' container or 'ph'+'ts' keys) outside "
                "obs/trace.py — a rogue trace producer has its own clock "
                "epoch and no cross-rank step alignment; record spans "
                "through obs/spans.py and let obs/trace.py export them"
            )
    return violations


def _mesh_ownership_violations(tree: ast.AST, rel: str) -> list[str]:
    """Rule 11: ``Mesh(...)`` construction (``jax.sharding.Mesh`` /
    imported ``Mesh`` — ``AbstractMesh`` and mesh-SHAPED helpers are
    fine) and any ``jax.distributed.*`` call outside core/mesh.py."""
    violations: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # Mesh(...) / jax.sharding.Mesh(...) / sharding.Mesh(...)
        name = (
            func.id if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute)
            else None
        )
        if name == "Mesh":
            violations.append(
                f"{rel}:{node.lineno}: raw Mesh(...) construction outside "
                "core/mesh.py skips the topology-aware device ordering and "
                "the elastic-recovery lifecycle — build meshes through "
                "core.mesh.build_mesh"
            )
            continue
        # jax.distributed.initialize/shutdown(...) in any spelling that
        # goes through an attribute chain ending `.distributed.<fn>`
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "distributed"
        ):
            violations.append(
                f"{rel}:{node.lineno}: jax.distributed.{func.attr}(...) "
                "outside core/mesh.py forks the distributed lifecycle the "
                "topology-change path owns (teardown ordering, rendezvous "
                "facts, the gloo-on-CPU flag) — go through "
                "core.mesh.initialize_distributed / reinitialize_distributed"
            )
    return violations


def _ckpt_manager_violations(tree: ast.AST, rel: str) -> list[str]:
    violations: list[str] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("save", "restore")
        ):
            continue
        base = node.func.value
        name = (
            base.attr if isinstance(base, ast.Attribute)
            else base.id if isinstance(base, ast.Name)
            else None
        )
        if name in _MANAGER_NAMES:
            violations.append(
                f"{rel}:{node.lineno}: bare {name}.{node.func.attr}(...) "
                "outside io/checkpoint.py bypasses the verified checkpoint "
                "wrappers (save retry/backoff, checksum manifest, "
                "verify-before-restore with fallback) — go through "
                "Checkpointer.save / restore_latest / restore_before"
            )
    return violations


def _names_in(node: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id.lower())
        elif isinstance(n, ast.Attribute):
            out.add(n.attr.lower())
    return out


def _is_grad_named(node: ast.AST) -> bool:
    return any(
        any(g in name for g in _GRAD_NAMES) for name in _names_in(node)
    )


def _is_add_fn(node: ast.AST) -> bool:
    """jnp.add / np.add / operator.add / a bare ``add`` / an add-lambda."""
    if isinstance(node, ast.Attribute) and node.attr == "add":
        return True
    if isinstance(node, ast.Name) and node.id == "add":
        return True
    if isinstance(node, ast.Lambda) and isinstance(node.body, ast.BinOp):
        return isinstance(node.body.op, ast.Add)
    return False


def _grad_accum_violations(tree: ast.AST, rel: str) -> list[str]:
    violations: list[str] = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.AugAssign)
            and isinstance(node.op, ast.Add)
            and (_is_grad_named(node.target) or _is_grad_named(node.value))
        ):
            violations.append(
                f"{rel}:{node.lineno}: manual '+=' gradient accumulator "
                "outside train/step.py — the compiled step owns in-step "
                "accumulation (sharded fp32 carry, one optimizer apply per "
                "step) and the pipeline executors own their microbatching; "
                "a third layer double-accumulates with no sharding contract"
            )
        elif (
            isinstance(node, ast.Call)
            and (
                (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("map", "tree_map", "tree_multimap")
                )
                or (
                    # `from jax.tree_util import tree_map` must not evade
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("tree_map", "tree_multimap")
                )
            )
            and node.args
            and _is_add_fn(node.args[0])
            and any(_is_grad_named(a) for a in node.args[1:])
        ):
            violations.append(
                f"{rel}:{node.lineno}: tree-map(add, ..., grads) "
                "accumulator outside train/step.py — use "
                "make_train_step(..., grad_accum_steps=N); the step owns "
                "accumulation (sharded fp32 carry, one optimizer apply)"
            )
    return violations


def _is_json_dumps_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "dumps"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "json"
    )


def _spec_call_has_str_literal(node: ast.Call) -> bool:
    def holds_str(n: ast.AST) -> bool:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            return True
        if isinstance(n, ast.Tuple):
            return any(holds_str(e) for e in n.elts)
        return False

    return any(holds_str(a) for a in node.args)


def _cadence_violations(tree: ast.AST, rel: str, allowed: frozenset) -> list[str]:
    """Rule 4: device→host conversions in a step-cadence file outside the
    allowlisted log-cadence-window functions."""
    violations: list[str] = []

    def describe(node: ast.Call) -> str | None:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "float":
            return "float(...)"
        if isinstance(fn, ast.Attribute) and fn.attr == "item" and not node.args:
            return ".item()"
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and (fn.value.id, fn.attr) in CADENCE_SYNC_CALLS
        ):
            return f"{fn.value.id}.{fn.attr}(...)"
        return None

    def visit(node: ast.AST, func: str | None) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        elif isinstance(node, ast.Call) and (func is None or func not in allowed):
            what = describe(node)
            if what is not None:
                violations.append(
                    f"{rel}:{node.lineno}: {what} on a step-cadence path "
                    f"(outside the log-cadence window functions "
                    f"{sorted(allowed)}) — a per-step device sync breaks "
                    "the zero-extra-syncs health-telemetry invariant; "
                    "convert only inside the cadenced window (or pin a "
                    "new window function in scripts/repo_lint.py with a "
                    "reason)"
                )
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, None)
    return violations


def lint_file(path: str, rel: str) -> list[str]:
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [f"{rel}: syntax error: {e}"]
    violations: list[str] = []
    hot = rel in HOT_PATH_FILES
    dropout_ruled = any(rel.startswith(d + os.sep) for d in DROPOUT_RULE_DIRS)
    in_spec_layer = any(rel.startswith(d + os.sep) for d in SPEC_LAYER_DIRS)
    allowed_spec = rel in SPEC_LITERAL_ALLOWLIST
    json_emit_ok = rel in JSON_EMIT_ALLOW_FILES or any(
        rel.startswith(d + os.sep) for d in JSON_EMIT_ALLOW_DIRS
    )
    if rel in STEP_CADENCE_FILES:
        violations.extend(_cadence_violations(tree, rel, STEP_CADENCE_FILES[rel]))
    if rel != GRAD_ACCUM_OWNER and any(
        rel.startswith(d + os.sep) for d in GRAD_ACCUM_RULE_DIRS
    ):
        violations.extend(_grad_accum_violations(tree, rel))
    if rel != OPTIM_OWNER and any(
        rel.startswith(d + os.sep) for d in OPTIM_RULE_DIRS
    ):
        violations.extend(_optim_apply_violations(tree, rel))
    if rel != GRAD_COLLECTIVE_OWNER and any(
        rel.startswith(d + os.sep) for d in GRAD_COLLECTIVE_RULE_DIRS
    ):
        violations.extend(_grad_collective_violations(tree, rel))
    if rel not in KV_CAST_OWNERS and (
        rel in KV_CAST_RULE_FILES
        or any(rel.startswith(d + os.sep) for d in KV_CAST_RULE_DIRS)
    ):
        violations.extend(_kv_cast_violations(tree, rel))
    if rel != CKPT_OWNER:
        violations.extend(_ckpt_manager_violations(tree, rel))
    if rel != MESH_OWNER:
        violations.extend(_mesh_ownership_violations(tree, rel))
    if rel != TRACE_OWNER:
        violations.extend(_trace_emit_violations(tree, rel))
    if rel != BACKOFF_OWNER:
        violations.extend(_retry_sleep_violations(tree, rel))
    if rel not in RANK_CONDITIONAL_OWNERS:
        violations.extend(_rank_conditional_violations(tree, rel, src))
    if rel != PERCENTILE_OWNER:
        violations.extend(_percentile_violations(tree, rel))
    if rel not in MEMSTATS_OWNERS:
        violations.extend(_memstats_violations(tree, rel))
    if rel != PREFIX_IDENTITY_OWNER:
        violations.extend(_prefix_identity_violations(tree, rel))
    if rel not in SPEC_DECODE_OWNERS:
        violations.extend(_spec_decode_violations(tree, rel))
    # rule 5: does this file import Dropout from the shared helper?
    helper_dropout_import = any(
        isinstance(n, ast.ImportFrom)
        and n.module
        and n.module.endswith("ops.fused_dropout")
        and any(a.name == "Dropout" for a in n.names)
        for n in ast.walk(tree)
    )

    for node in ast.walk(tree):
        if (
            not json_emit_ok
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and any(_is_json_dumps_call(a) for a in node.args)
        ):
            violations.append(
                f"{rel}:{node.lineno}: print(json.dumps(...)) outside "
                "obs//utils/jsonlog.py bypasses the metric sink (no "
                "schema_version, no process gate, invisible to --obs "
                "jsonl) — emit through utils.jsonlog.log_json"
            )
        if dropout_ruled and isinstance(node, ast.Call):
            fn = node.func
            # match the ATTRIBUTE NAME regardless of qualifier so aliased
            # imports (linen.Dropout, flax.linen.Dropout, random.bernoulli)
            # can't slip past; a bare `Dropout(...)` is fine only when the
            # file imports it from the shared helper (helper_dropout_import)
            if isinstance(fn, ast.Attribute) and fn.attr == "Dropout":
                violations.append(
                    f"{rel}:{node.lineno}: raw {ast.unparse(fn)}(...) in "
                    "models//train/ bypasses the shared fused-dropout helper "
                    "— use ops.fused_dropout.Dropout (same contract, routes "
                    "through --dropout-impl)"
                )
            if (
                isinstance(fn, ast.Name)
                and fn.id == "Dropout"
                and not helper_dropout_import
            ):
                violations.append(
                    f"{rel}:{node.lineno}: Dropout(...) in models//train/ "
                    "without importing it from ops.fused_dropout — only the "
                    "shared helper's Dropout routes through --dropout-impl"
                )
            if (isinstance(fn, ast.Attribute) and fn.attr == "bernoulli") or (
                isinstance(fn, ast.Name) and fn.id == "bernoulli"
            ):
                violations.append(
                    f"{rel}:{node.lineno}: bernoulli(...) in models//train/ "
                    "hand-rolls a dropout mask outside the shared helper — "
                    "use ops.fused_dropout.dropout (the fused path never "
                    "materializes the mask)"
                )
        if hot and isinstance(node, ast.Attribute) and node.attr in FORBIDDEN_SYNC_ATTRS:
            violations.append(
                f"{rel}:{node.lineno}: .{node.attr}() in the train-step hot "
                "path stalls async dispatch"
            )
        if hot and isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and (fn.value.id, fn.attr) in FORBIDDEN_SYNC_CALLS
            ):
                violations.append(
                    f"{rel}:{node.lineno}: {fn.value.id}.{fn.attr}() in the "
                    "train-step hot path forces a device sync"
                )
        if (
            not in_spec_layer
            and not allowed_spec
            and isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("P", "PartitionSpec")
            and _spec_call_has_str_literal(node)
        ):
            violations.append(
                f"{rel}:{node.lineno}: bare PartitionSpec with literal axis "
                "names outside parallel/ — route it through "
                "parallel/sharding.py rules (or pin an allowlist entry in "
                "scripts/repo_lint.py with a reason)"
            )
    return violations


def main(argv: list[str] | None = None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    violations: list[str] = []
    pkg_root = os.path.join(root, PACKAGE)
    for dirpath, _, files in os.walk(pkg_root):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            violations.extend(lint_file(path, rel))
    for v in violations:
        print(v)
    if not violations:
        print("repo_lint: clean")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
