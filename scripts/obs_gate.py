#!/usr/bin/env python
"""Tier-1-adjacent dispatch-efficiency gate over a run's obs JSONL.

Thin, pinned-flags wrapper around ``obs.report --strict
--min-dispatch-efficiency`` so CI (and the bench driver) gate the
trainer-loop dispatch efficiency with ONE command whose floor is
recorded here instead of re-typed per pipeline:

    python scripts/obs_gate.py <output_dir> [--min-dispatch-efficiency 0.90]

Exit 0 when the run's wall-weighted ``dispatch_efficiency`` (from its
``step_budget`` events) meets the floor AND the report is otherwise
strict-clean (valid schema, no organic faults); nonzero otherwise —
including when NO step_budget records exist (a missing measurement must
never read as a pass).  The default floor 0.90 is the ROADMAP
trainer-loop attack's bar rounded down one notch: ``vs_synthetic_step
>= 0.95`` needs the host-stall share (1 − efficiency) under ~10% on the
measured configs.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_FLOOR = 0.90


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/obs_gate.py", description=__doc__
    )
    p.add_argument("output_dir", help="a run's --output-dir (containing obs/)")
    p.add_argument(
        "--min-dispatch-efficiency", type=float, default=DEFAULT_FLOOR,
        help=f"wall-weighted dispatch_efficiency floor (default {DEFAULT_FLOOR})",
    )
    p.add_argument(
        "--max-gradient-bytes-per-step", type=float, default=0.0,
        help="optional compression gate: fail when the startup gauges' "
             "gradient byte account exceeds this ceiling, or when no "
             "account was emitted — a round that silently loses "
             "--grad-compression (flag ignored, partitioner folded back "
             "to fp32) fails instead of passing on wall-clock luck "
             "(0 = off)",
    )
    p.add_argument(
        "--min-overlap-frac", type=float, default=0.0,
        help="optional device-account floor: fail when a profiled window "
             "shows collective time with overlap_frac below this, or when "
             "a profile was captured but no device_account was emitted "
             "(0 = the device gate is off)",
    )
    p.add_argument(
        "--max-request-retry-rate", type=float, default=-1.0,
        help="optional serving gate: fail when the router_summary's "
             "request_retry_rate exceeds this ceiling, or when no "
             "router_summary was emitted — a serve-router round whose "
             "pool is retry-storming fails instead of passing on "
             "wall-clock luck (-1 = off; 0 means any retry fails)",
    )
    p.add_argument(
        "--min-serve-goodput-frac", type=float, default=0.0,
        help="optional serving gate: fail when the router_summary's "
             "goodput_frac (requests completed within the TTFT SLO over "
             "requests submitted) falls below this floor, or when no "
             "router_summary was emitted (0 = off)",
    )
    p.add_argument(
        "--min-slo-attainment", type=float, default=0.0,
        help="optional open-loop loadgen gate: fail when the QPS sweep's "
             "best per-point slo_attainment (loadgen_point events) falls "
             "below this floor, or when NO loadgen measurement was "
             "emitted — a round that silently skips the open-loop sweep "
             "fails instead of passing on the closed-loop numbers "
             "(0 = off)",
    )
    p.add_argument(
        "--max-p99-ttft-ms", type=float, default=0.0,
        help="optional open-loop loadgen gate: fail when the QPS sweep's "
             "lowest measured per-point p99 TTFT (from arrival) exceeds "
             "this ceiling, or when no point measured one (0 = off)",
    )
    p.add_argument(
        "--min-prefix-hit-rate", type=float, default=0.0,
        help="optional prefix-cache gate: fail when the prefix cache's "
             "hit rate (router aggregate when one exists, else the last "
             "prefix-enabled serve_summary) falls below this floor, or "
             "when NO prefix-enabled summary was emitted — a round that "
             "silently loses --prefix-cache fails instead of passing "
             "unmeasured (0 = off)",
    )
    p.add_argument(
        "--min-acceptance-rate", type=float, default=0.0,
        help="optional speculative-decode gate: fail when the draft "
             "acceptance rate (router aggregate when one exists, else "
             "the last spec-enabled serve_summary) falls below this "
             "floor, or when NO spec-enabled summary was emitted — a "
             "round that silently loses --spec-tokens fails instead of "
             "passing unmeasured (0 = off)",
    )
    p.add_argument(
        "--max-peak-hbm-frac", type=float, default=0.0,
        help="optional memory gate: fail when the measured HBM peak "
             "(runtime memory_window where sampled, else the static "
             "account's compiled peak) exceeds this fraction of the "
             "--hbm-budget-gib ceiling, or when NO memory measurement "
             "exists (0 = off)",
    )
    p.add_argument(
        "--min-hbm-headroom-gib", type=float, default=0.0,
        help="optional memory gate: fail when any memory account's "
             "hbm_headroom_gib falls below this floor, or when no "
             "account was emitted (0 = off)",
    )
    args = p.parse_args(argv)
    from distributed_llms_example_tpu.obs.report import main as report_main

    flags = [
        args.output_dir,
        "--strict",
        "--min-dispatch-efficiency", str(args.min_dispatch_efficiency),
        "--json",
    ]
    if args.min_overlap_frac > 0:
        flags += ["--min-overlap-frac", str(args.min_overlap_frac)]
    if args.max_gradient_bytes_per_step > 0:
        flags += [
            "--max-gradient-bytes-per-step",
            str(args.max_gradient_bytes_per_step),
        ]
    if args.max_request_retry_rate >= 0:
        flags += [
            "--max-request-retry-rate", str(args.max_request_retry_rate),
        ]
    if args.min_serve_goodput_frac > 0:
        flags += [
            "--min-serve-goodput-frac", str(args.min_serve_goodput_frac),
        ]
    if args.min_slo_attainment > 0:
        flags += ["--min-slo-attainment", str(args.min_slo_attainment)]
    if args.max_p99_ttft_ms > 0:
        flags += ["--max-p99-ttft-ms", str(args.max_p99_ttft_ms)]
    if args.min_prefix_hit_rate > 0:
        flags += ["--min-prefix-hit-rate", str(args.min_prefix_hit_rate)]
    if args.min_acceptance_rate > 0:
        flags += ["--min-acceptance-rate", str(args.min_acceptance_rate)]
    if args.max_peak_hbm_frac > 0:
        flags += ["--max-peak-hbm-frac", str(args.max_peak_hbm_frac)]
    if args.min_hbm_headroom_gib > 0:
        flags += ["--min-hbm-headroom-gib", str(args.min_hbm_headroom_gib)]
    return report_main(flags)


if __name__ == "__main__":
    sys.exit(main())
