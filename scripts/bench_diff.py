#!/usr/bin/env python
"""Field-by-field comparison of two bench rounds (``BENCH_*.json``).

The bench artifacts are nested JSON records (tokens/sec, TTFT, budget
components, the device-account entries) whose round-over-round deltas
today are read by eye.  This script makes the comparison a CI gate:

    python scripts/bench_diff.py OLD.json NEW.json \
        [--default-threshold 0.05] [--threshold ttft_p95_ms=0.10 ...] \
        [--markdown-out DELTA.md]

Every numeric leaf present in BOTH files is compared on its dot-path.
Fields whose names carry a known direction are **gated**: a relative
change in the bad direction beyond the threshold is a REGRESSION and the
exit code is nonzero (CI red).  Direction comes from the leaf name:

- higher is better: ``*tokens_per_sec*``, ``*_per_sec*``, ``*efficiency*``,
  ``mfu``, ``goodput*``, ``slo_attainment``, ``overlap_frac``,
  ``accounted_frac``, ``*speedup*``, ``*occupancy*``, ``*utilization*``,
  ``achieved_bytes_per_sec``
- lower is better: ``*_ms``, ``ttft*``, ``*_s`` / ``*_seconds`` walls,
  ``*overhead*``, ``exposed_*``, ``unattributed*``, ``data_wait*``,
  ``steps_lost*``
- everything else (counts, configs, byte accounts) is reported
  informationally and never gates.

Thresholds are relative (``0.05`` = 5%); ``--threshold name=frac``
overrides per leaf name or per full dot-path (most specific wins).  A
markdown delta table is printed (or written with ``--markdown-out``) so
the diff can be stamped into a PR or the bench artifact directory.

Pure stdlib + json — runs anywhere the artifacts are mounted.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Iterator

DEFAULT_THRESHOLD = 0.05

_HIGHER_BETTER = (
    "tokens_per_sec", "_per_sec", "efficiency", "mfu", "goodput",
    "slo_attainment", "overlap_frac", "accounted_frac", "speedup",
    "occupancy", "utilization", "vs_synthetic", "vs_baseline",
    "achieved_bytes_per_sec", "continuous_vs_static",
    # serving capacity (PR 13): sustained concurrency per chip and the
    # int8/f32 footprint ratio are the levers the capacity block measures
    "max_sustained_slots", "token_match_rate", "cache_bytes_ratio",
    # open-loop load sweep (serving/loadgen.py): a knee moving RIGHT is
    # more offered load served before saturation; goodput/attainment at
    # the SLO are the curve's quality axes ("goodput"/"slo_attainment"
    # above already cover goodput_at_slo lexically — named here so the
    # direction survives a tuple reshuffle)
    "knee_qps", "achieved_qps", "goodput_qps", "goodput_at_slo",
    # HBM attribution (obs/memprof.py): more headroom under the budget
    # is strictly better
    "hbm_headroom_gib",
    # prefix cache (serving/cache_pool.py): more reuse is the whole
    # point — a higher hit rate / saved fraction means less prefill work
    "hit_rate", "prefill_tokens_saved",
    # speculative decode (serving/spec.py): more drafts surviving the
    # target's argmax and more tokens per verify round mean fewer decode
    # dispatches per emitted token — tok/s leaves are covered above
    "acceptance_rate", "accepted_tokens_per_step", "vs_plain",
)
_LOWER_BETTER = (
    "_ms", "ttft", "wall_s", "_seconds", "overhead", "exposed_",
    "unattributed", "data_wait", "steps_lost",
    # wire traffic: fewer gradient bytes per step is the whole point of
    # --grad-compression (PR 12); the generic byte-account leaves stay
    # informational (activation bytes move with config, not quality)
    "gradient_bytes_per_step", "gradient_wire_bytes",
    # cache footprint per live token: what the int8/paged knobs shrink
    "cache_bytes_per_token", "bytes_per_live_token",
    "admit_deferrals",
    # open-loop tail latency and queueing delay ("_ms"/"ttft" above
    # already cover these lexically — named for the same reason as
    # knee_qps)
    "p99_ttft_ms", "ttft_p99_ms", "queue_delay_p99_ms",
    # HBM attribution (obs/memprof.py): a peak or live-bytes move UP is
    # a memory regression — the static account's bucket leaves and the
    # watermark readings end in bytes_in_use / peak_hbm_*
    "peak_hbm", "bytes_in_use", "watermark_delta_bytes",
    "peak_frac_of_budget",
)
# config knobs stamped INTO the artifact (not measurements): changing a
# setting between rounds must never read as a perf regression — the
# same fix ttft_slo_ms needed in PR 11; grad_compression is a mode
# switch, so flipping it between rounds is information, not regression.
# The decode-capacity knobs (kv_cache_dtype, prefill_buckets, pool
# sizing) are the same class: flag flips, never regressions.
_CONFIG_LEAVES = (
    "ttft_slo_ms", "threshold", "slo_ms", "grad_compression",
    "kv_cache_dtype", "prefill_buckets", "pool_blocks", "kv_block_size",
    "paged_kv",
    # the open-loop sweep's offered-QPS grid and schedule knobs are the
    # experiment's x-axis and shape, not measurements: widening the grid
    # or retuning the arrival process between rounds must never read as
    # a perf regression (max_wall_s would otherwise match "wall_s")
    "qps_grid", "offered_qps", "requests_per_point", "burst_size",
    "ramp_start_frac", "track_tol", "max_wall_s",
    # the HBM budget is the gate's ceiling, not a measurement: raising
    # it between rounds (new chip generation) must never read as a
    # regression ("hbm_budget_gib" would otherwise match nothing, but
    # "hbm_budget_bytes" must not match "_bytes_in_use"-adjacent rules)
    "hbm_budget",
    # the warm-retention byte budget is an LRU ceiling, not a
    # measurement: growing it between rounds is a config change
    "prefix_cache_budget",
    # speculative-decode knobs: the draft count and draft-model choice
    # are experiment settings — retuning k between rounds is
    # information, never a regression ("spec_tokens" matches only the
    # config leaf; the drafted/accepted LEDGER leaves are
    # spec_drafted_tokens / spec_accepted_tokens, which it does not
    # substring-match)
    "spec_tokens", "spec_draft_model",
)


def direction_of(path: str) -> int:
    """+1 = higher is better, -1 = lower is better, 0 = informational.

    Matched on the LEAF name first; a leaf with no signal inherits its
    parent map's direction (``device_account.buckets_ms.attn``: the leaf
    is a bucket name, the ``buckets_ms`` parent carries the unit).
    Config knobs the artifact stamps (SLO settings, thresholds) are
    always informational."""
    leaf = path.lower().rsplit(".", 1)[-1]
    if any(c in leaf for c in _CONFIG_LEAVES):
        return 0
    segments = path.lower().rsplit(".", 2)
    for name in reversed(segments[-2:] if len(segments) > 1 else segments):
        if any(n in name for n in _HIGHER_BETTER):
            return 1
        if any(n in name for n in _LOWER_BETTER):
            return -1
    return 0


def flatten(doc: Any, prefix: str = "") -> Iterator[tuple[str, float]]:
    """Numeric leaves of a nested JSON record as (dot.path, value).
    bools are config, not measurements — skipped."""
    if isinstance(doc, dict):
        for k, v in doc.items():
            yield from flatten(v, f"{prefix}.{k}" if prefix else str(k))
    elif isinstance(doc, bool):
        return
    elif isinstance(doc, (int, float)):
        yield prefix, float(doc)


def resolve_threshold(
    path: str, overrides: dict[str, float], default: float
) -> float:
    """Most specific override wins: full dot-path, then leaf name."""
    if path in overrides:
        return overrides[path]
    leaf = path.rsplit(".", 1)[-1]
    return overrides.get(leaf, default)


def compare(
    old: dict, new: dict, *,
    overrides: dict[str, float] | None = None,
    default_threshold: float = DEFAULT_THRESHOLD,
) -> list[dict]:
    """Rows for every numeric leaf present in both records, verdict-ed.

    verdict ∈ {"regressed", "improved", "ok", "info"}; a row regresses
    when the relative change moves in the bad direction past its
    threshold.  Returned in path order, regressions first within none —
    callers sort/filter as needed."""
    overrides = overrides or {}
    old_flat = dict(flatten(old))
    new_flat = dict(flatten(new))
    rows: list[dict] = []
    for path in sorted(old_flat.keys() & new_flat.keys()):
        a, b = old_flat[path], new_flat[path]
        rel = (b - a) / abs(a) if a != 0 else (0.0 if b == 0 else float("inf"))
        d = direction_of(path)
        threshold = resolve_threshold(path, overrides, default_threshold)
        if d == 0:
            verdict = "info"
        elif d * rel < -threshold:
            verdict = "regressed"
        elif d * rel > threshold:
            verdict = "improved"
        else:
            verdict = "ok"
        rows.append({
            "field": path, "old": a, "new": b,
            "rel_change": round(rel, 4) if rel != float("inf") else None,
            "direction": {1: "higher_better", -1: "lower_better", 0: "info"}[d],
            "threshold": threshold,
            "verdict": verdict,
        })
    return rows


def render_markdown(rows: list[dict], old_path: str, new_path: str) -> str:
    regressions = [r for r in rows if r["verdict"] == "regressed"]
    improved = [r for r in rows if r["verdict"] == "improved"]
    lines = [
        f"# bench diff — `{old_path}` → `{new_path}`",
        "",
        f"{len(rows)} shared numeric fields · "
        f"{len(regressions)} regression(s) · {len(improved)} improvement(s)",
        "",
        "| field | old | new | Δ | verdict |",
        "|---|---|---|---|---|",
    ]

    def fmt(v: float) -> str:
        return f"{v:.6g}"

    # regressions first (the reason anyone reads this table), then
    # improvements, then the quiet rows
    order = {"regressed": 0, "improved": 1, "ok": 2, "info": 3}
    for r in sorted(rows, key=lambda r: (order[r["verdict"]], r["field"])):
        rel = r["rel_change"]
        delta = f"{rel * 100:+.1f}%" if rel is not None else "new≠0"
        mark = {"regressed": "**REGRESSED**", "improved": "improved",
                "ok": "ok", "info": ""}[r["verdict"]]
        lines.append(
            f"| {r['field']} | {fmt(r['old'])} | {fmt(r['new'])} | "
            f"{delta} | {mark} |"
        )
    return "\n".join(lines) + "\n"


def parse_threshold_arg(spec: str) -> tuple[str, float]:
    name, _, frac = spec.partition("=")
    if not name or not frac:
        raise argparse.ArgumentTypeError(
            f"--threshold takes FIELD=FRAC, got {spec!r}"
        )
    return name, float(frac)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/bench_diff.py", description=__doc__
    )
    p.add_argument("old", help="baseline BENCH_*.json")
    p.add_argument("new", help="candidate BENCH_*.json")
    p.add_argument(
        "--default-threshold", type=float, default=DEFAULT_THRESHOLD,
        help=f"relative regression tolerance (default {DEFAULT_THRESHOLD})",
    )
    p.add_argument(
        "--threshold", action="append", default=[], type=parse_threshold_arg,
        metavar="FIELD=FRAC",
        help="per-field override, by leaf name or full dot-path "
             "(repeatable; most specific wins)",
    )
    p.add_argument(
        "--markdown-out", default="",
        help="write the delta table here instead of stdout",
    )
    p.add_argument(
        "--json", action="store_true", help="emit the rows as JSON instead"
    )
    args = p.parse_args(argv)
    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    rows = compare(
        old, new,
        overrides=dict(args.threshold),
        default_threshold=args.default_threshold,
    )
    if not rows:
        print("bench_diff: no shared numeric fields", file=sys.stderr)
        return 2
    md = render_markdown(rows, args.old, args.new)
    if args.json:
        print(json.dumps(rows))
    if args.markdown_out:
        with open(args.markdown_out, "w") as f:
            f.write(md)
        if not args.json:
            print(f"bench_diff: wrote {args.markdown_out}")
    elif not args.json:
        print(md, end="")
    regressions = [r for r in rows if r["verdict"] == "regressed"]
    for r in regressions:
        print(
            f"bench_diff: REGRESSED {r['field']}: {r['old']:.6g} → "
            f"{r['new']:.6g} ({r['rel_change'] * 100 if r['rel_change'] is not None else float('nan'):+.1f}% "
            f"past the {r['threshold'] * 100:.0f}% threshold)",
            file=sys.stderr,
        )
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
