"""Pipeline parallelism (stage axis) correctness.

Same bar as ring attention: exact forward and gradient parity against the
sequential computation on the 8-device CPU mesh, then full train-step
equivalence for the pipelined LLaMA path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_blocks,
    unstack_blocks,
)


@pytest.fixture(scope="module")
def pp_mesh():
    """stage=4 × data=2: pipeline composed with data parallelism."""
    return build_mesh(MeshConfig(stage=4, data=2, fsdp=1, sequence=1, tensor=1))


def _toy_layer(p, h, ex):
    """One 'layer': affine + nonlinearity + per-example extra."""
    return jnp.tanh(h @ p["w"] + p["b"]) + ex["shift"]


def _toy_stack(n_layers=8, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(n_layers, d, d).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(n_layers, d).astype(np.float32) * 0.1),
    }


def _sequential(stacked, h, ex):
    for i in range(jax.tree.leaves(stacked)[0].shape[0]):
        h = _toy_layer(jax.tree.map(lambda x: x[i], stacked), h, ex)
    return h


@pytest.mark.parametrize("num_micro", [2, 4])
def test_forward_parity(pp_mesh, num_micro):
    stacked = _toy_stack()
    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(8, 4, 16).astype(np.float32))
    ex = {"shift": jnp.asarray(rng.randn(8, 4, 16).astype(np.float32) * 0.01)}
    out = pipeline_apply(
        _toy_layer, stacked, h, ex, mesh=pp_mesh, num_microbatches=num_micro
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stacked, h, ex)), atol=1e-6, rtol=1e-6
    )


def test_gradient_parity(pp_mesh):
    """Grads wrt stacked params AND input must match the sequential program
    — the reverse pipeline (ppermute transpose through the scan) is exact."""
    stacked = _toy_stack(n_layers=4, d=8)
    rng = np.random.RandomState(2)
    h = jnp.asarray(rng.randn(8, 2, 8).astype(np.float32))
    ex = {"shift": jnp.zeros((1, 1), np.float32)}  # replicated constant

    def piped(p, h):
        return jnp.sum(
            pipeline_apply(_toy_layer, p, h, ex, mesh=pp_mesh, num_microbatches=4) ** 2
        )

    def seq(p, h):
        return jnp.sum(_sequential(p, h, ex) ** 2)

    gp_p, gh_p = jax.grad(piped, argnums=(0, 1))(stacked, h)
    gp_s, gh_s = jax.grad(seq, argnums=(0, 1))(stacked, h)
    np.testing.assert_allclose(np.asarray(gh_p), np.asarray(gh_s), atol=1e-5, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(gp_p), jax.tree.leaves(gp_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_stage1_is_plain_scan():
    mesh1 = build_mesh(
        MeshConfig(stage=1, data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1]
    )
    stacked = _toy_stack(n_layers=4, d=8)
    h = jnp.asarray(np.random.RandomState(3).randn(4, 2, 8).astype(np.float32))
    ex = {"shift": jnp.zeros((1, 1), np.float32)}
    out = pipeline_apply(_toy_layer, stacked, h, ex, mesh=mesh1, num_microbatches=1)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(stacked, h, ex)), atol=1e-6, rtol=1e-6
    )


def test_stack_unstack_roundtrip():
    # 12 layers: lexicographic sorting would order block_10 before block_2
    params = {"embed": np.ones((4, 3), np.float32)}
    for i in range(12):
        params[f"block_{i}"] = {"w": np.full((2, 2), float(i), np.float32)}
    stacked = stack_blocks(params)
    # numeric (not lexicographic) layer order
    assert jax.tree.leaves(stacked["stacked_blocks"])[0].shape == (12, 2, 2)
    np.testing.assert_array_equal(
        np.asarray(stacked["stacked_blocks"]["w"][:, 0, 0]), np.arange(12.0)
    )
    back = unstack_blocks(stacked)
    assert set(back) == set(params)
    np.testing.assert_array_equal(back["block_10"]["w"], params["block_10"]["w"])
    # non-contiguous layer indices are a hard error, not silent renumbering
    with pytest.raises(ValueError, match="contiguous"):
        stack_blocks({"block_0": {"w": np.zeros(2)}, "block_2": {"w": np.zeros(2)}})


def test_validation_errors(pp_mesh):
    stacked = _toy_stack(n_layers=6)  # 6 % 4 != 0
    h = jnp.zeros((8, 4, 16), np.float32)
    with pytest.raises(ValueError, match="pipeline stages"):
        pipeline_apply(_toy_layer, stacked, h, {"shift": h}, mesh=pp_mesh, num_microbatches=2)
    stacked = _toy_stack(n_layers=8)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(_toy_layer, stacked, h, {"shift": h}, mesh=pp_mesh, num_microbatches=3)


# tiny_llama4 now lives in tests/conftest.py (shared with test_interleave.py);
# note the conftest fixture is function-scoped where this module's was
# module-scoped — params are tiny, the re-init cost is noise.


def test_pipelined_llama_logits_parity(pp_mesh, tiny_llama4):
    """PipelinedLlama must produce the standard module's logits exactly
    (the pipeline only reorders microbatches, never the math)."""
    from distributed_llms_example_tpu.models.llama import PipelinedLlama

    cfg, module, params = tiny_llama4
    rng = np.random.RandomState(5)
    ids = rng.randint(2, cfg.vocab_size, (8, 16)).astype(np.int32)
    mask = np.ones((8, 16), np.int32)
    mask[:4, -5:] = 0
    ref = module.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask))

    piped = PipelinedLlama(cfg, pp_mesh, num_microbatches=2)
    from distributed_llms_example_tpu.parallel.pipeline import stack_blocks

    pparams = stack_blocks(params)
    out = piped.apply({"params": pparams}, jnp.asarray(ids), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_pipelined_train_step_equals_single_device(pp_mesh, tiny_llama4):
    """Full train step through the pipeline (stage=4 × data=2) == the
    standard module on one device: loss, grad-norm, updated params."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(11)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}

    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    # single-device reference with the standard module
    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    sh = state_shardings(state, mesh1)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
    step, _ = build(state)
    _, ref_metrics = step(state, put_batch(batch, mesh1))

    # pipelined on stage=4 × data=2
    from distributed_llms_example_tpu.parallel.pipeline import stack_blocks, unstack_blocks

    piped = PipelinedLlama(cfg, pp_mesh, num_microbatches=2)
    pparams = stack_blocks(params0)
    rules = pipeline_rules()
    build_p = make_train_step(
        piped, cfg, tx, schedule, pp_mesh, rules=rules, donate=False, is_seq2seq=False
    )
    state_p = create_train_state(shard_params(pparams, pp_mesh, rules), tx)
    sh_p = state_shardings(state_p, pp_mesh, rules)
    state_p = jax.tree.map(lambda x, s: jax.device_put(x, s), state_p, sh_p)
    step_p, _ = build_p(state_p)
    new_state_p, metrics_p = step_p(state_p, put_batch(batch, pp_mesh))

    assert float(metrics_p["loss"]) == pytest.approx(float(ref_metrics["loss"]), rel=1e-5)
    assert float(metrics_p["grad_norm"]) == pytest.approx(float(ref_metrics["grad_norm"]), rel=1e-4)
    # stacked params sharded over stage: each device holds 1 of 4 layers
    stacked_leaf = new_state_p.params["stacked_blocks"]["self_attn"]["q_proj"]["kernel"]
    assert {s.data.shape[0] for s in stacked_leaf.addressable_shards} == {1}


def test_pipelined_stage_x_tensor_equals_single_device(tiny_llama4):
    """stage=2 × tensor=2 × data=2 — the standard 7B+ topology.  The
    pipeline shard_map is manual over ``stage`` only, so GSPMD partitions
    the stacked kernels' megatron splits over ``tensor`` inside each
    stage; the result must equal the single-device standard module."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.pipeline import stack_blocks
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(5)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :3] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    _, ref_metrics = step(state, put_batch(batch, mesh1))

    mesh_st = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=1, tensor=2))
    piped = PipelinedLlama(cfg, mesh_st, num_microbatches=2)
    rules = pipeline_rules()
    pparams = shard_params(stack_blocks(params0), mesh_st, rules)
    state_p = create_train_state(pparams, tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_st, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_st, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    new_state_p, metrics_p = step_p(state_p, put_batch(batch, mesh_st))

    assert float(metrics_p["loss"]) == pytest.approx(float(ref_metrics["loss"]), rel=1e-5)
    assert float(metrics_p["grad_norm"]) == pytest.approx(float(ref_metrics["grad_norm"]), rel=1e-4)
    # stacked q_proj kernel (L, d, heads·hd): L=4 over stage=2 AND the
    # output dim over tensor=2 — stage × tensor really compose
    leaf = new_state_p.params["stacked_blocks"]["self_attn"]["q_proj"]["kernel"]
    L, d = cfg.num_hidden_layers, cfg.hidden_size
    assert {s.data.shape for s in leaf.addressable_shards} == {(L // 2, d, d // 2)}


def test_trainer_pipelined_end_to_end(tmp_path):
    """Trainer on a stage=2 × data=2 mesh: stacks the blocks, trains through
    the pipeline, disables eval, exports the standard per-layer layout."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(0)
    records = [
        {
            "dialogue": " ".join(f"w{rng.randint(50)}" for _ in range(rng.randint(5, 20))),
            "summary": "w1 w2",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="llama-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=0,
        learning_rate=1e-3,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=1,
        mesh=MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:4])
    assert trainer.pipelined
    assert trainer.evaluator is not None  # eval runs on unstacked params
    result = trainer.train()
    assert result["steps"] == trainer.total_steps
    assert "rougeL" in result["final_eval"]  # eval really ran under stage>1
    # stage-sharded teacher-forced eval (no unstacking) always reports
    assert np.isfinite(result["final_eval"]["val_loss"])
    # exported artifact is an HF checkpoint in the standard per-layer
    # layout — it round-trips through the loader
    from distributed_llms_example_tpu.models.registry import load_model

    reloaded = load_model(os.path.join(str(tmp_path), "model"))
    assert reloaded.params is not None
    assert "block_0" in reloaded.params and "block_1" in reloaded.params
    assert "stacked_blocks" not in reloaded.params


def test_decay_mask_on_stacked_params():
    """Weight decay must not hit norm scales just because stacking gave
    them a leading layer dim (rank-only masks get this wrong)."""
    from distributed_llms_example_tpu.train.optim import decay_mask

    params = {
        "stacked_blocks": {
            "attn_norm": {"scale": np.ones((4, 32), np.float32)},
            "self_attn": {"q_proj": {"kernel": np.ones((4, 32, 32), np.float32)}},
        },
        "final_norm": {"scale": np.ones((32,), np.float32)},
    }
    mask = decay_mask(params)
    assert mask["stacked_blocks"]["attn_norm"]["scale"] is False
    assert mask["stacked_blocks"]["self_attn"]["q_proj"]["kernel"] is True
    assert mask["final_norm"]["scale"] is False


def test_pipelined_bart_logits_parity():
    """PipelinedBart (twin pipelines, stage=2 × data=2 × tensor=2) must
    reproduce the standard BartForConditionalGeneration logits."""
    from distributed_llms_example_tpu.models.bart import (
        BartConfig,
        BartForConditionalGeneration,
        PipelinedBart,
    )
    from distributed_llms_example_tpu.parallel.pipeline import stack_for_family

    cfg = BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        dropout_rate=0.0,
    )
    module = BartForConditionalGeneration(cfg)
    rng = np.random.RandomState(3)
    ids = rng.randint(4, 128, (8, 12)).astype(np.int32)
    mask = np.ones((8, 12), np.int32)
    mask[:2, -4:] = 0
    dec = rng.randint(4, 128, (8, 6)).astype(np.int32)
    params = jax.device_get(
        module.init(jax.random.PRNGKey(0), jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))["params"]
    )
    ref = module.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))

    mesh = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=1, tensor=2))
    piped = PipelinedBart(cfg, mesh, num_microbatches=2, remat=False)
    pparams = stack_for_family("bart", params)
    out = piped.apply({"params": pparams}, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_pipelined_t5_logits_parity():
    """PipelinedT5 (twin pipelines + out-of-pipeline relative-position
    bias) must reproduce the standard T5ForConditionalGeneration logits,
    and the bias tables must still receive gradient."""
    from distributed_llms_example_tpu.models.t5 import (
        T5Config,
        T5ForConditionalGeneration,
        PipelinedT5,
    )
    from distributed_llms_example_tpu.parallel.pipeline import stack_for_family

    cfg = T5Config(vocab_size=128, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                   num_heads=4, dropout_rate=0.0)
    module = T5ForConditionalGeneration(cfg)
    rng = np.random.RandomState(4)
    ids = rng.randint(4, 128, (8, 10)).astype(np.int32)
    mask = np.ones((8, 10), np.int32)
    mask[:3, -3:] = 0
    dec = rng.randint(4, 128, (8, 5)).astype(np.int32)
    params = jax.device_get(
        module.init(jax.random.PRNGKey(1), jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))["params"]
    )
    ref = module.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))

    mesh = build_mesh(MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1))
    piped = PipelinedT5(cfg, mesh, num_microbatches=2, remat=False)
    pparams = stack_for_family("t5", params)
    out = piped.apply({"params": pparams}, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    # relative-position bias tables get gradient through the pipelined path
    def loss(p):
        lg = piped.apply({"params": p}, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))
        return jnp.sum(lg.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(pparams)
    for stack in ("encoder", "decoder"):
        gt = np.asarray(g[stack]["relative_attention_bias"]["embedding"])
        assert np.abs(gt).sum() > 0, stack


def test_trainer_pipelined_bart_end_to_end(tmp_path):
    """Trainer with bart-test on stage=2: twin pipelines end-to-end,
    pipelined val_loss, live dropout (bart default 0.1, rng threaded
    through the stage loop), HF export back in per-layer layout."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(1)
    records = [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(rng.randint(5, 16))),
            "summary": "w3 w4",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="bart-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=0,
        learning_rate=1e-3,
        max_source_length=64,
        max_target_length=32,
        pad_to_multiple=32,
        log_every_steps=1,
        num_beams=1,
        eval_max_new_tokens=8,
        mesh=MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:4])
    # bart-test's default dropout (0.1) is live under the pipeline: the
    # key is folded per microbatch/stage/layer inside the stage loop
    assert trainer.pipelined and trainer.use_dropout
    result = trainer.train()
    assert result["steps"] == trainer.total_steps
    assert np.isfinite(result["final_eval"]["val_loss"])
    assert "rougeL" in result["final_eval"]
    reloaded = load_model(str(tmp_path / "model"))
    assert "encoder_block_0" in reloaded.params and "decoder_block_1" in reloaded.params


def test_pipelined_dropout_real_and_key_deterministic():
    """Dropout through the pipeline: same key → identical logits
    (reproducible), different key → different logits, deterministic mode →
    different again and equal to the standard module (masks really fire
    inside the stage loop, not just at the embeddings)."""
    import dataclasses

    from distributed_llms_example_tpu.models.bart import (
        BartConfig,
        BartForConditionalGeneration,
        PipelinedBart,
    )
    from distributed_llms_example_tpu.parallel.pipeline import stack_for_family

    cfg = BartConfig(
        vocab_size=128, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        dropout_rate=0.3,
    )
    module = BartForConditionalGeneration(cfg)
    rng = np.random.RandomState(9)
    ids = rng.randint(4, 128, (8, 12)).astype(np.int32)
    mask = np.ones((8, 12), np.int32)
    dec = rng.randint(4, 128, (8, 6)).astype(np.int32)
    params = jax.device_get(
        module.init(jax.random.PRNGKey(0), jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec))["params"]
    )
    det_cfg = dataclasses.replace(cfg, dropout_rate=0.0)
    mesh = build_mesh(MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1))
    piped = PipelinedBart(cfg, mesh, num_microbatches=2, remat=False)
    pparams = stack_for_family("bart", params)

    det = np.asarray(piped.apply({"params": pparams}, ids, mask, dec, deterministic=True))
    a = np.asarray(piped.apply(
        {"params": pparams}, ids, mask, dec,
        deterministic=False, rngs={"dropout": jax.random.PRNGKey(7)},
    ))
    b = np.asarray(piped.apply(
        {"params": pparams}, ids, mask, dec,
        deterministic=False, rngs={"dropout": jax.random.PRNGKey(7)},
    ))
    c = np.asarray(piped.apply(
        {"params": pparams}, ids, mask, dec,
        deterministic=False, rngs={"dropout": jax.random.PRNGKey(8)},
    ))
    np.testing.assert_array_equal(a, b)  # same key → bit-identical
    assert np.abs(a - det).max() > 1e-3  # masks actually fired
    assert np.abs(a - c).max() > 1e-3  # key really seeds the masks
    # deterministic pipelined == standard module (dropout off path intact)
    ref = np.asarray(
        BartForConditionalGeneration(det_cfg).apply(
            {"params": params}, jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(dec)
        )
    )
    np.testing.assert_allclose(det, ref, atol=2e-5, rtol=2e-5)


def test_unstack_resharded_layers_are_fsdp_sharded():
    """unstack_for_family_resharded must hand back per-layer params ON the
    default FSDP/TP shardings (not replicated): the eval-memory contract."""
    from distributed_llms_example_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from distributed_llms_example_tpu.parallel.pipeline import (
        stack_for_family,
        unstack_for_family_resharded,
    )
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    module = LlamaForCausalLM(cfg)
    params = jax.device_get(module.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
    mesh = build_mesh(MeshConfig(stage=2, data=1, fsdp=2, sequence=1, tensor=2))
    stacked = shard_params(stack_for_family("llama", params), mesh, pipeline_rules())

    out = unstack_for_family_resharded("llama", stacked, mesh)
    q = out["block_0"]["self_attn"]["q_proj"]["kernel"]  # (32, 32)
    # default rules: P("fsdp", "tensor") → (16, 16) per device, NOT (32, 32)
    assert {s.data.shape for s in q.addressable_shards} == {(16, 16)}
    # values round-trip exactly
    np.testing.assert_allclose(
        np.asarray(jax.device_get(q)),
        params["block_0"]["self_attn"]["q_proj"]["kernel"],
        atol=0, rtol=0,
    )


def test_pipelined_grad_accum_equals_full_batch(pp_mesh, tiny_llama4):
    """Gradient accumulation (lax.scan microbatching) composed WITH the
    pipeline must still equal the single-device full-batch step — the
    token-weighted accumulation is exact, not approximate."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.pipeline import stack_blocks
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(21)
    b, src = 16, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :5] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    _, ref = step(state, put_batch(batch, mesh1))

    piped = PipelinedLlama(cfg, pp_mesh, num_microbatches=2)
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stack_blocks(params0), pp_mesh, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, pp_mesh, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, pp_mesh, rules=rules, donate=False,
        is_seq2seq=False, grad_accum_steps=2,
    )
    step_p, _ = build_p(state_p)
    _, got = step_p(state_p, put_batch(batch, pp_mesh))

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)


def test_pure_stage_mesh_skips_generation_rouge(tmp_path):
    """On a pure-stage mesh (fsdp*tensor == 1 — the canonical config for a
    model too big to replicate) the Trainer must auto-skip generation ROUGE:
    the resharded unstack would resolve every layer to fully replicated,
    one whole-model copy per device.  val_loss (stage-sharded, no
    unstacking) still reports."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(3)
    records = [
        {
            "dialogue": " ".join(f"w{rng.randint(50)}" for _ in range(rng.randint(5, 20))),
            "summary": "w1 w2",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="llama-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=0,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=1,
        mesh=MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:4])
    assert trainer.pipelined
    # flag default is True, but the mesh makes generation eval unsafe
    assert cfg.pipeline_eval_rouge and not trainer._pipeline_rouge_ok
    scores = trainer.evaluate(epoch=0)
    assert np.isfinite(scores["val_loss"])
    assert not any(k.startswith("rouge") for k in scores)


def test_fsdp_stage_mesh_keeps_generation_rouge(tmp_path):
    """Counter-case: with fsdp*tensor > 1 the unstacked eval params land on
    real FSDP/TP shardings, so the default keeps generation ROUGE on."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    records = [{"dialogue": "a b c d", "summary": "a b"} for _ in range(8)]
    cfg = TrainConfig(
        model_ckpt="llama-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        mesh=MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:4])
    assert trainer.pipelined and trainer._pipeline_rouge_ok


@pytest.mark.parametrize("stages,micro", [(2, 4), (4, 2)])
def test_1f1b_train_step_equals_single_device(tiny_llama4, stages, micro):
    """1F1B is a SCHEDULE-only change: interleaving backward microbatches
    with forward must reproduce the single-device loss, grad norm, and the
    gpipe path's metrics exactly (same math, different order)."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(13)
    b, src = 16, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    mask = np.ones((b, src), np.int32)
    mask[:2, -3:] = 0
    batch = {"input_ids": ids, "attention_mask": mask, "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    ref_state, ref = step(state, put_batch(batch, mesh1))

    mesh_p = build_mesh(MeshConfig(stage=stages, data=8 // stages, fsdp=1, sequence=1, tensor=1))
    piped = PipelinedLlama(cfg, mesh_p, num_microbatches=micro, schedule="1f1b")
    assert piped.pipeline_schedule == "1f1b"
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stack_blocks(params0), mesh_p, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_p, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_p, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    new_state_p, got = step_p(state_p, put_batch(batch, mesh_p))

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    assert float(got["target_tokens"]) == float(ref["target_tokens"])
    # updated params match layer-for-layer after unstacking
    upd = unstack_blocks(jax.device_get(new_state_p.params))
    ref_upd = jax.device_get(ref_state.params)
    for lyr in ("block_0", f"block_{cfg.num_hidden_layers - 1}"):
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(upd[lyr])[0]),
            np.asarray(jax.tree.leaves(ref_upd[lyr])[0]),
            atol=1e-5, rtol=1e-4,
        )
    np.testing.assert_allclose(
        np.asarray(upd["lm_head"]["kernel"]),
        np.asarray(ref_upd["lm_head"]["kernel"]),
        atol=1e-5, rtol=1e-4,
    )


def test_1f1b_composes_with_tensor_parallel(tiny_llama4):
    """1F1B on stage=2 × tensor=2 × data=2: the chunk vjps run under GSPMD
    auto-partitioning over tensor, same as the gpipe body."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(17)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :6] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    _, ref = step(state, put_batch(batch, mesh1))

    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=1, tensor=2))
    piped = PipelinedLlama(cfg, mesh_p, num_microbatches=2, schedule="1f1b")
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stack_blocks(params0), mesh_p, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_p, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_p, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    _, got = step_p(state_p, put_batch(batch, mesh_p))
    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)


def test_trainer_1f1b_end_to_end(tmp_path):
    """Trainer with --pipeline-schedule 1f1b on stage=2 × data=4: trains,
    evaluates (stage-sharded val loss), exports per-layer HF layout."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(7)
    records = [
        {
            "dialogue": " ".join(f"w{rng.randint(50)}" for _ in range(rng.randint(5, 20))),
            "summary": "w1 w2",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="llama-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=0,
        learning_rate=1e-3,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=1,
        mesh=MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
        pipeline_schedule="1f1b",
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:4])
    assert trainer.pipelined
    assert trainer.model.pipeline_schedule == "1f1b"
    result = trainer.train()
    assert result["steps"] == trainer.total_steps
    assert np.isfinite(result["final_eval"]["val_loss"])
    from distributed_llms_example_tpu.models.registry import load_model

    reloaded = load_model(os.path.join(str(tmp_path), "model"))
    assert "block_0" in reloaded.params


def test_pipelined_moe_equals_grad_accum_single_device():
    """stage=2 × expert=2 × data=2 with a Mixtral-class MoE model: the
    load-balance aux loss rides OUT of the pipeline as an explicit scan
    output (sown collections can't cross the shard_map).  Reference:
    the standard module on one device with grad_accum = num_microbatches —
    the same per-microbatch aux statistics the pipeline computes, so loss
    and grad norm must match exactly."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("mixtral-test")
    cfg, module = lm.config, lm.module
    assert cfg.num_experts > 0 and cfg.moe_aux_weight > 0
    params0 = jax.device_get(lm.init_params(0))
    M = 2
    rng = np.random.RandomState(23)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    # uniform loss mask across examples: the pipelined aux is a plain
    # microbatch mean, exact vs grad-accum only when tokens/microbatch
    # are equal
    labels[:, :4] = LABEL_PAD
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(
        module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False, grad_accum_steps=M
    )
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    _, ref = step(state, put_batch(batch, mesh1))

    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, expert=2, sequence=1, tensor=1))
    piped = PipelinedLlama(cfg, mesh_p, num_microbatches=M)
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stack_blocks(params0), mesh_p, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_p, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_p, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    _, got = step_p(state_p, put_batch(batch, mesh_p))

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)


def test_pipelined_moe_aux_actually_contributes():
    """The aux loss must actually reach the pipelined objective: zeroing
    the router weights' aux coefficient changes the loss."""
    import dataclasses as dc

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.activation import activation_mesh
    from distributed_llms_example_tpu.parallel.pipeline import stack_blocks as _stack
    from distributed_llms_example_tpu.train.step import make_loss_fn

    lm = load_model("mixtral-test")
    rng = np.random.RandomState(3)
    ids = rng.randint(2, lm.config.vocab_size, (8, 16)).astype(np.int32)
    labels = ids.copy(); labels[:, :4] = LABEL_PAD
    batch = {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.asarray(np.ones((8, 16), np.int32)),
        "labels": jnp.asarray(labels),
    }
    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, expert=2, sequence=1, tensor=1))
    params = _stack(jax.device_get(lm.init_params(0)))
    piped = PipelinedLlama(lm.config, mesh_p, num_microbatches=2)
    with activation_mesh(mesh_p):
        with_aux = make_loss_fn(piped, lm.config, is_seq2seq=False)(params, batch)
        cfg0 = dc.replace(lm.config, moe_aux_weight=0.0)
        piped0 = PipelinedLlama(cfg0, mesh_p, num_microbatches=2)
        without = make_loss_fn(piped0, cfg0, is_seq2seq=False)(params, batch)
    assert float(with_aux[0]) != pytest.approx(float(without[0]), rel=1e-9)
    # aux is positive (load-balance penalty) so the objective only grows
    assert float(with_aux[0]) > float(without[0])


def test_pipelined_stage_x_sequence_logits_parity(tiny_llama4):
    """stage=2 × sequence=2 × data=2: ONE manual region over both axes,
    ring attention inside the pipeline body (RoPE offset to global
    positions, padding bias riding the ring with K/V) — logits must match
    the standard sequential module."""
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.pipeline import stack_blocks

    cfg, module, params = tiny_llama4
    rng = np.random.RandomState(23)
    ids = rng.randint(2, cfg.vocab_size, (8, 16)).astype(np.int32)
    mask = np.ones((8, 16), np.int32)
    mask[:4, -5:] = 0  # padding spanning the second sequence shard
    ref = module.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask))

    mesh_sp = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=2, tensor=1))
    piped = PipelinedLlama(cfg, mesh_sp, num_microbatches=2)
    out = piped.apply({"params": stack_blocks(params)}, jnp.asarray(ids), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("pp_schedule", ["gpipe", "1f1b", "interleaved"])
def test_pipelined_stage_x_sequence_train_step(tiny_llama4, pp_schedule):
    """Full train step on stage=2 × sequence=2 × data=2 == single device:
    autodiff through the combined manual region (pipeline transpose AND the
    ring's rotated-K/V transpose in one backward) is exact.  On 1f1b the
    schedule owns the backward — per-chunk vjps with the ring inside, and
    the cross-shard next-token label shift (``_seq_shift_labels``).  On
    interleaved the same composition runs with v=2 virtual chunks per
    device (table-driven schedule, interleaved storage order)."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    cfg, module, params0 = tiny_llama4
    rng = np.random.RandomState(29)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD
    mask = np.ones((b, src), np.int32)
    mask[:3, -6:] = 0
    batch = {"input_ids": ids, "attention_mask": mask, "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    ref_state, ref = step(state, put_batch(batch, mesh1))

    mesh_sp = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=2, tensor=1))
    kw = {"virtual_stages": 2} if pp_schedule == "interleaved" else {}
    piped = PipelinedLlama(cfg, mesh_sp, num_microbatches=2, schedule=pp_schedule, **kw)
    stacked = stack_blocks(params0)
    if pp_schedule == "interleaved":
        from distributed_llms_example_tpu.parallel.interleave import interleave_tree

        stacked["stacked_blocks"] = interleave_tree(stacked["stacked_blocks"], 2, 2)
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stacked, mesh_sp, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_sp, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_sp, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    new_state_p, got = step_p(state_p, put_batch(batch, mesh_sp, sequence_sharded=True))

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    assert float(got["target_tokens"]) == float(ref["target_tokens"])
    upd_tree = jax.device_get(new_state_p.params)
    if pp_schedule == "interleaved":
        from distributed_llms_example_tpu.parallel.interleave import uninterleave_tree

        upd_tree["stacked_blocks"] = uninterleave_tree(upd_tree["stacked_blocks"], 2, 2)
    upd = unstack_blocks(upd_tree)
    ref_upd = jax.device_get(ref_state.params)
    for lyr in ("block_0", f"block_{cfg.num_hidden_layers - 1}"):
        np.testing.assert_allclose(
            np.asarray(jax.tree.leaves(upd[lyr])[0]),
            np.asarray(jax.tree.leaves(ref_upd[lyr])[0]),
            atol=1e-5, rtol=1e-4,
        )


def test_stage_x_sequence_validation():
    """MoE does not compose with the sequence axis — loud errors, not
    silent wrong numbers."""
    from distributed_llms_example_tpu.models.llama import LlamaConfig, PipelinedLlama

    mesh_sp = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, sequence=2, tensor=1))
    moe_cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=4, num_attention_heads=2,
        num_experts=2, moe_aux_weight=0.01,
    )
    with pytest.raises(ValueError, match="MoE"):
        PipelinedLlama(moe_cfg, mesh_sp, num_microbatches=2)
    # a forced non-ring impl inside the manual region must raise, not be
    # silently overridden to ring
    from distributed_llms_example_tpu.ops.mha import MultiHeadAttention
    from distributed_llms_example_tpu.parallel.activation import manual_sequence

    mha = MultiHeadAttention(
        num_heads=2, head_dim=8, model_dim=16, causal=True, attention_impl="xla"
    )
    x = jnp.zeros((2, 8, 16), jnp.float32)
    variables = mha.init(jax.random.PRNGKey(0), x)
    with manual_sequence("sequence", 2):
        with pytest.raises(ValueError, match="manual sequence region"):
            mha.apply(variables, x)


def test_moe_1f1b_equals_grad_accum_single_device():
    """MoE through the FUSED 1f1b schedule (stage=2 × expert=2 × data=2):
    the load-balance aux rides each chunk as an explicit output whose
    cotangent is the constant objective coefficient (moe_weight·tokens /
    (L·M)), so one per-chunk vjp covers CE and router gradients together.
    Reference: grad_accum = num_microbatches on one device — identical
    per-microbatch aux statistics, so loss and grad norm match exactly
    (the same contract as the gpipe MoE test)."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("mixtral-test")
    cfg, module = lm.config, lm.module
    assert cfg.num_experts > 0 and cfg.moe_aux_weight > 0
    params0 = jax.device_get(lm.init_params(0))
    M = 2
    rng = np.random.RandomState(29)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD  # uniform tokens/microbatch (see gpipe test)
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(
        module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False, grad_accum_steps=M
    )
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    ref_state, ref = step(state, put_batch(batch, mesh1))

    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, expert=2, sequence=1, tensor=1))
    piped = PipelinedLlama(cfg, mesh_p, num_microbatches=M, schedule="1f1b")
    rules = pipeline_rules()
    state_p = create_train_state(shard_params(stack_blocks(params0), mesh_p, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_p, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_p, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    new_state_p, got = step_p(state_p, put_batch(batch, mesh_p))

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    # router (gate) weights must receive the aux gradient: compare an
    # updated router kernel layer-for-layer against the reference step
    upd = unstack_blocks(jax.device_get(new_state_p.params))
    ref_upd = jax.device_get(ref_state.params)
    for lyr in ("block_0", f"block_{cfg.num_hidden_layers - 1}"):
        np.testing.assert_allclose(
            np.asarray(upd[lyr]["mlp"]["router"]["kernel"]),
            np.asarray(ref_upd[lyr]["mlp"]["router"]["kernel"]),
            atol=1e-5, rtol=1e-4,
        )


def test_moe_interleaved_equals_grad_accum_single_device():
    """MoE through the INTERLEAVED virtual-stage schedule (stage=2 × v=2
    chunks × expert=2 × data=2, 4-layer mixtral): same aux contract as the
    1f1b executor — chunk aux sums + the constant objective coefficient as
    each chunk vjp's aux cotangent — through the table-driven executor and
    the interleaved storage permutation."""
    import optax

    from distributed_llms_example_tpu.data.batching import LABEL_PAD
    from distributed_llms_example_tpu.models.llama import PipelinedLlama
    from distributed_llms_example_tpu.models.registry import load_model
    from distributed_llms_example_tpu.parallel.interleave import interleave_tree
    from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
    from distributed_llms_example_tpu.train.step import (
        create_train_state,
        make_train_step,
        put_batch,
        state_shardings,
    )

    lm = load_model("mixtral-test-4l")
    cfg, module = lm.config, lm.module
    assert cfg.num_experts > 0 and cfg.moe_aux_weight > 0
    params0 = jax.device_get(lm.init_params(0))
    M = 2
    rng = np.random.RandomState(31)
    b, src = 8, 16
    ids = rng.randint(2, cfg.vocab_size, (b, src)).astype(np.int32)
    labels = ids.copy()
    labels[:, :4] = LABEL_PAD  # uniform tokens/microbatch
    batch = {"input_ids": ids, "attention_mask": np.ones((b, src), np.int32), "labels": labels}
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    mesh1 = build_mesh(MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1])
    build = make_train_step(
        module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=False, grad_accum_steps=M
    )
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    _, ref = step(state, put_batch(batch, mesh1))

    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=1, expert=2, sequence=1, tensor=1))
    piped = PipelinedLlama(
        cfg, mesh_p, num_microbatches=M, schedule="interleaved", virtual_stages=2
    )
    rules = pipeline_rules()
    stacked = stack_blocks(params0)
    stacked["stacked_blocks"] = interleave_tree(stacked["stacked_blocks"], 2, 2)
    state_p = create_train_state(shard_params(stacked, mesh_p, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_p, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_p, rules=rules, donate=False, is_seq2seq=False
    )
    step_p, _ = build_p(state_p)
    new_state_p, got = step_p(state_p, put_batch(batch, mesh_p))

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    # per-layer router updates too: a row-permuted aux gradient would leave
    # loss AND the global grad norm unchanged — unstack through the
    # INTERLEAVED storage order and compare layer-for-layer
    from distributed_llms_example_tpu.parallel.interleave import uninterleave_order

    ref_state2, _ = step(state, put_batch(batch, mesh1))
    upd = unstack_blocks(
        jax.device_get(new_state_p.params),
        row_order=uninterleave_order(cfg.num_hidden_layers, 2, 2),
    )
    ref_upd = jax.device_get(ref_state2.params)
    for lyr in (f"block_{i}" for i in range(cfg.num_hidden_layers)):
        np.testing.assert_allclose(
            np.asarray(upd[lyr]["mlp"]["router"]["kernel"]),
            np.asarray(ref_upd[lyr]["mlp"]["router"]["kernel"]),
            atol=1e-5, rtol=1e-4,
        )
