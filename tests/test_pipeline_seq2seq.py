"""Twin-pipeline fused 1F1B for the seq2seq families (BART/T5).

The fused schedule is a SCHEDULE-only change: interleaving encoder and
decoder chunk forwards/backwards across the stage ring must reproduce the
single-device loss, token counts, grad norm, and per-layer parameter
updates exactly (same math, different order).  These tests pin the
``pipeline_value_and_grad_seq2seq`` executor + both family adapters
against the plain flax modules — the same contract the LLaMA 1F1B tests
enforce (tests/test_pipeline.py::test_1f1b_*).
"""

import os

import jax
import numpy as np
import optax
import pytest

from distributed_llms_example_tpu.core.config import MeshConfig
from distributed_llms_example_tpu.core.mesh import build_mesh
from distributed_llms_example_tpu.data.batching import LABEL_PAD
from distributed_llms_example_tpu.parallel.pipeline import stack_for_family, unstack_for_family
from distributed_llms_example_tpu.parallel.sharding import pipeline_rules, shard_params
from distributed_llms_example_tpu.train.step import (
    create_train_state,
    make_train_step,
    put_batch,
    state_shardings,
)

jax.config.update("jax_default_matmul_precision", "highest")


def _tiny_bart(layers=4, dropout=0.0):
    import jax.numpy as jnp

    from distributed_llms_example_tpu.models.bart import BartConfig, BartForConditionalGeneration

    cfg = BartConfig(
        vocab_size=96, d_model=32, encoder_layers=layers, decoder_layers=layers,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        dropout_rate=dropout,
    )
    module = BartForConditionalGeneration(cfg)
    params = jax.device_get(
        module.init(
            jax.random.PRNGKey(0),
            jnp.ones((1, 8), jnp.int32), jnp.ones((1, 8), jnp.int32),
            jnp.ones((1, 4), jnp.int32),
        )["params"]
    )
    return cfg, module, params


def _tiny_t5(layers=4, dropout=0.0, tied=True):
    import jax.numpy as jnp

    from distributed_llms_example_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    cfg = T5Config(
        vocab_size=96, d_model=32, d_kv=8, d_ff=64, num_layers=layers,
        num_heads=4, dropout_rate=dropout, tie_word_embeddings=tied,
        feed_forward_proj="relu" if tied else "gated-gelu",
    )
    module = T5ForConditionalGeneration(cfg)
    params = jax.device_get(
        module.init(
            jax.random.PRNGKey(0),
            jnp.ones((1, 8), jnp.int32), jnp.ones((1, 8), jnp.int32),
            jnp.ones((1, 4), jnp.int32),
        )["params"]
    )
    return cfg, module, params


def _seq2seq_batch(vocab, b=16, src=16, tgt=8, seed=3):
    rng = np.random.RandomState(seed)
    ids = rng.randint(2, vocab, (b, src)).astype(np.int32)
    mask = np.ones((b, src), np.int32)
    mask[: b // 4, -5:] = 0
    labels = rng.randint(2, vocab, (b, tgt)).astype(np.int32)
    labels[: b // 2, -3:] = LABEL_PAD
    return {"input_ids": ids, "attention_mask": mask, "labels": labels}


def _run_ref(module, cfg, params0, batch, tx, schedule):
    mesh1 = build_mesh(
        MeshConfig(data=1, fsdp=1, sequence=1, tensor=1), devices=jax.devices()[:1]
    )
    build = make_train_step(module, cfg, tx, schedule, mesh1, donate=False, is_seq2seq=True)
    state = create_train_state(shard_params(params0, mesh1), tx)
    state = jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh1))
    step, _ = build(state)
    return step(state, put_batch(batch, mesh1))


def _run_fused(Adapter, family, cfg, params0, batch, tx, schedule, mesh_cfg, micro):
    mesh_p = build_mesh(mesh_cfg)
    piped = Adapter(cfg, mesh_p, num_microbatches=micro, schedule="1f1b")
    assert piped.pipeline_schedule == "1f1b"
    rules = pipeline_rules()
    stacked = stack_for_family(family, params0)
    state_p = create_train_state(shard_params(stacked, mesh_p, rules), tx)
    state_p = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state_p, state_shardings(state_p, mesh_p, rules)
    )
    build_p = make_train_step(
        piped, cfg, tx, schedule, mesh_p, rules=rules, donate=False, is_seq2seq=True
    )
    step_p, _ = build_p(state_p)
    return step_p(state_p, put_batch(batch, mesh_p))


@pytest.mark.parametrize("stages,micro", [(2, 4), (4, 2)])
def test_bart_1f1b_equals_single_device(stages, micro):
    cfg, module, params0 = _tiny_bart()
    from distributed_llms_example_tpu.models.bart import PipelinedBart

    batch = _seq2seq_batch(cfg.vocab_size)
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    ref_state, ref = _run_ref(module, cfg, params0, batch, tx, schedule)
    new_state_p, got = _run_fused(
        PipelinedBart, "bart", cfg, params0, batch, tx, schedule,
        MeshConfig(stage=stages, data=8 // stages, fsdp=1, sequence=1, tensor=1), micro,
    )

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["target_tokens"]) == float(ref["target_tokens"])
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    # updated params match layer-for-layer after unstacking — first/last of
    # BOTH stacks, plus every out-of-pipeline group (embeds, tied head)
    upd = unstack_for_family("bart", jax.device_get(new_state_p.params))
    ref_upd = jax.device_get(ref_state.params)
    for lyr in (
        "encoder_block_0", f"encoder_block_{cfg.encoder_layers - 1}",
        "decoder_block_0", f"decoder_block_{cfg.decoder_layers - 1}",
        "shared", "encoder_embed_positions", "decoder_embed_positions",
        "encoder_layernorm_embedding", "final_logits_bias",
    ):
        got_l, ref_l = jax.tree.leaves(upd[lyr]), jax.tree.leaves(ref_upd[lyr])
        for g, r in zip(got_l, ref_l):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("tied", [True, False])
def test_t5_1f1b_equals_single_device(tied):
    """T5 exercises the executor's seam (encoder final-norm between the
    pipelines) and diff_extras (learned relative-position bias tables) —
    both must receive exact gradients."""
    cfg, module, params0 = _tiny_t5(tied=tied)
    from distributed_llms_example_tpu.models.t5 import PipelinedT5

    batch = _seq2seq_batch(cfg.vocab_size, seed=11)
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    ref_state, ref = _run_ref(module, cfg, params0, batch, tx, schedule)
    new_state_p, got = _run_fused(
        PipelinedT5, "t5", cfg, params0, batch, tx, schedule,
        MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1), 4,
    )

    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["target_tokens"]) == float(ref["target_tokens"])
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)
    upd = unstack_for_family("t5", jax.device_get(new_state_p.params))
    ref_upd = jax.device_get(ref_state.params)

    def check(path_got, path_ref):
        for g, r in zip(jax.tree.leaves(path_got), jax.tree.leaves(path_ref)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=2e-5, rtol=1e-4)

    for stack in ("encoder", "decoder"):
        check(upd[stack]["block_0"], ref_upd[stack]["block_0"])
        check(upd[stack][f"block_{cfg.num_layers - 1}"], ref_upd[stack][f"block_{cfg.num_layers - 1}"])
        # the seam norm (encoder) / tail norm (decoder) and the learned
        # relative-position bias tables
        check(upd[stack]["final_norm"], ref_upd[stack]["final_norm"])
        check(upd[stack]["relative_attention_bias"], ref_upd[stack]["relative_attention_bias"])
    check(upd["shared"], ref_upd["shared"])
    if not tied:
        check(upd["lm_head"], ref_upd["lm_head"])


def test_bart_1f1b_composes_with_tensor_parallel():
    cfg, module, params0 = _tiny_bart()
    from distributed_llms_example_tpu.models.bart import PipelinedBart

    batch = _seq2seq_batch(cfg.vocab_size, seed=17)
    tx = optax.sgd(1e-2)
    schedule = lambda s: 1e-2  # noqa: E731

    _, ref = _run_ref(module, cfg, params0, batch, tx, schedule)
    _, got = _run_fused(
        PipelinedBart, "bart", cfg, params0, batch, tx, schedule,
        MeshConfig(stage=2, data=2, fsdp=1, sequence=1, tensor=2), 2,
    )
    assert float(got["loss"]) == pytest.approx(float(ref["loss"]), rel=1e-5)
    assert float(got["grad_norm"]) == pytest.approx(float(ref["grad_norm"]), rel=1e-4)


def test_bart_1f1b_dropout_runs_and_is_key_deterministic():
    """With dropout live the fused path can't match gpipe key-for-key
    (different fold layout) — but it must run, produce finite metrics, and
    be a deterministic function of the rng key."""
    cfg, module, params0 = _tiny_bart(dropout=0.1)
    from distributed_llms_example_tpu.models.bart import PipelinedBart

    mesh_p = build_mesh(MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1))
    piped = PipelinedBart(cfg, mesh_p, num_microbatches=2, schedule="1f1b")
    batch = _seq2seq_batch(cfg.vocab_size, seed=23)
    tx = optax.sgd(1e-2)
    rules = pipeline_rules()
    stacked = stack_for_family("bart", params0)
    state = create_train_state(shard_params(stacked, mesh_p, rules), tx)
    state = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state, state_shardings(state, mesh_p, rules)
    )
    build = make_train_step(
        piped, cfg, tx, lambda s: 1e-2, mesh_p, rules=rules, donate=False,
        is_seq2seq=True, with_dropout=True,
    )
    step, _ = build(state)
    key = jax.random.PRNGKey(5)
    _, m1 = step(state, put_batch(batch, mesh_p), key)
    _, m2 = step(state, put_batch(batch, mesh_p), key)
    _, m3 = step(state, put_batch(batch, mesh_p), jax.random.PRNGKey(6))
    assert np.isfinite(float(m1["loss"]))
    assert float(m1["loss"]) == float(m2["loss"])
    assert float(m1["loss"]) != float(m3["loss"])


def test_trainer_bart_1f1b_end_to_end(tmp_path):
    """Trainer with --pipeline-schedule 1f1b on a BART config: trains,
    reports the stage-sharded val loss, exports the per-layer HF layout."""
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(7)
    records = [
        {
            "dialogue": " ".join(f"w{rng.randint(50)}" for _ in range(rng.randint(5, 20))),
            "summary": "w1 w2",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="bart-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        warmup_steps=0,
        learning_rate=1e-3,
        max_source_length=64,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=1,
        mesh=MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
        pipeline_microbatches=2,
        pipeline_schedule="1f1b",
    )
    trainer = Trainer(cfg, train_records=records, val_records=records[:4])
    assert trainer.pipelined
    assert trainer.model.pipeline_schedule == "1f1b"
    result = trainer.train()
    assert result["steps"] == trainer.total_steps
    assert np.isfinite(result["final_eval"]["val_loss"])
    from distributed_llms_example_tpu.models.registry import load_model

    reloaded = load_model(os.path.join(str(tmp_path), "model"))
    assert "encoder_block_0" in reloaded.params


def test_interleaved_still_rejected_for_seq2seq(tmp_path):
    from distributed_llms_example_tpu.core.config import CheckpointConfig, TrainConfig
    from distributed_llms_example_tpu.train.trainer import Trainer

    records = [{"dialogue": "a b c", "summary": "a"} for _ in range(8)]
    cfg = TrainConfig(
        model_ckpt="bart-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=1,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=16,
        mesh=MeshConfig(stage=2, data=4, fsdp=1, sequence=1, tensor=1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        tokenizer="byte",
    )
    with pytest.raises(ValueError, match="interleaved"):
        Trainer(cfg.replace(pipeline_schedule="interleaved"), train_records=records)


def test_bart_1f1b_rejects_fsdp():
    """stage×fsdp with the twin 1f1b is guarded at construction: the XLA
    partitioner SIGABRTs (no diagnostic) compiling the chunk-pair program
    with dim-0-fsdp-sharded block params — under both dispatch modes and
    with the param gather hoisted out of the branches.  gpipe remains the
    fsdp×stage path for seq2seq; the guard turns a compiler crash into an
    actionable startup error."""
    cfg, _, _ = _tiny_bart()
    from distributed_llms_example_tpu.models.bart import PipelinedBart

    mesh_p = build_mesh(MeshConfig(stage=2, data=2, fsdp=2, sequence=1, tensor=1))
    with pytest.raises(ValueError, match="fsdp"):
        PipelinedBart(cfg, mesh_p, num_microbatches=2, schedule="1f1b")
    # gpipe on the same mesh constructs fine
    PipelinedBart(cfg, mesh_p, num_microbatches=2, schedule="gpipe")
