"""Device-time attribution (ISSUE 11): profiler traces → device account.

Pins: the fixture-pinned trace parse (hand-written trace-viewer JSON with
known durations/op_names → EXACT per-bucket times, overlap and idle);
the achieved-bandwidth join against a hand byte account (exact numbers);
the shared op_name→bucket mapping (analysis/ir_lint.py) between param
paths and HLO scopes; the fake-capture end-to-end (fixture trace →
TrainerObs parse → device_account in the JSONL → report tables FROM THE
JSONL ALONE → Perfetto device lanes beside the host spans); the
``--profile-on-anomaly`` trigger arming; the schema round-trip for
``device_account``/``profile_captured``; and the strict
``--min-overlap-frac`` gate (including captures that produced no
account).  The REAL CPU profile round-trip on the 8-device mesh rides
the slow tier (jax's profiler session init dominates).
"""

from __future__ import annotations

import gzip
import json
import os
import shutil

import pytest

from distributed_llms_example_tpu.analysis.ir_lint import (
    base_collective_op,
    classify_op_scope,
    module_bucket_of,
    op_bucket_index,
)
from distributed_llms_example_tpu.core.config import (
    CheckpointConfig,
    MeshConfig,
    TrainConfig,
)
from distributed_llms_example_tpu.obs import TrainerObs, sink as sink_mod
from distributed_llms_example_tpu.obs.devprof import (
    DEVICE_BUCKETS,
    build_account,
    classify_event,
    device_account_from_dir,
    device_op_events,
    find_trace_files,
    join_collective_bandwidth,
)
from distributed_llms_example_tpu.obs.report import (
    build_report,
    load_jsonl,
    render_markdown,
)


@pytest.fixture(autouse=True)
def _default_sink():
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))
    yield
    sink_mod.install_sink(sink_mod.build_sink("stdout", ""))


# ---------------------------------------------------------------------------
# the shared op_name→bucket mapping (analysis/ir_lint.py)
# ---------------------------------------------------------------------------


def test_module_bucket_table_matches_param_buckets():
    # the same table serves param paths (train/step.py bucket_of_path)
    # and device op scopes — spot-check both spellings
    assert module_bucket_of("encoder/block_0/self_attn/q_proj") == "attn"
    assert module_bucket_of("model/decoder/layers/3/mlp/wi") == "mlp"
    assert module_bucket_of("shared/embedding") == "embed"
    assert module_bucket_of("lm_head/kernel") == "head"
    assert module_bucket_of("final_norm/scale") is None  # caller decides


def test_classify_op_scope_optimizer_and_modules():
    assert classify_op_scope(
        "jit(train_step)/jit(main)/Model/encoder/block_0/self_attn/dot_general"
    ) == "attn"
    assert classify_op_scope("jit(train_step)/jit(main)/adamw/mul") == "optimizer"
    assert classify_op_scope("jit(f)/jit(main)/clip_by_global_norm/div") == "optimizer"
    assert classify_op_scope("jit(f)/jit(main)/reduce_sum") is None


def test_base_collective_op_forms():
    assert base_collective_op("all-reduce") == "all-reduce"
    assert base_collective_op("all-reduce-start.3") == "all-reduce"
    assert base_collective_op("reduce-scatter.12") == "reduce-scatter"
    assert base_collective_op("collective-permute-done.1") == "collective-permute"
    assert base_collective_op("dot.1") is None
    assert base_collective_op("fusion.clone") is None


def test_op_bucket_index_from_hlo_metadata():
    text = "\n".join([
        "HloModule jit_train_step",
        "ENTRY %main () -> f32[] {",
        '  %dot.1 = f32[8,8]{1,0} dot(%a, %b), metadata={op_name="jit(f)/jit(main)/M/encoder/block_0/self_attn/q_proj/dot_general" source_file="m.py" source_line=10}',
        '  %fusion.2 = f32[8]{0} fusion(%dot.1), kind=kLoop, metadata={op_name="jit(f)/jit(main)/M/encoder/block_0/mlp/wi/dot_general"}',
        "  %all-reduce.3 = f32[8]{0} all-reduce(%fusion.2), replica_groups={{0,1}}, to_apply=%add",
        '  %copy.4 = f32[8]{0} copy(%all-reduce.3), metadata={op_name="jit(f)/jit(main)/adamw/update"}',
        '  %embed.5 = f32[16]{0} gather(%c, %d), metadata={op_name="jit(f)/jit(main)/M/shared/take"}',
        '  %slice.6 = f32[4]{0} slice(%embed.5), metadata={op_name="jit(f)/jit(main)/reduce_sum"}',
        "  %rs.7 = f32[4]{0} reduce-scatter(%slice.6), replica_groups={{0,1}}, to_apply=%add",
        "}",
    ])
    idx = op_bucket_index(text)
    assert idx["dot.1"] == "attn"
    assert idx["fusion.2"] == "mlp"
    assert idx["all-reduce.3"] == "collective"
    assert idx["copy.4"] == "optimizer"
    assert idx["embed.5"] == "embed"
    assert idx["slice.6"] == "other"  # scope with no module signal
    assert idx["rs.7"] == "collective"


def test_classify_event_precedence():
    idx = {"fusion.1": "attn"}
    # collective opcode beats everything, with or without an index
    assert classify_event("all-reduce.9", "all-reduce.9", idx) == "collective"
    assert classify_event("all-gather-start.2", "", None) == "collective"
    assert classify_event("outfeed.1", "outfeed.1", idx) == "infeed"
    # instruction-name join (CPU traces)
    assert classify_event("fusion.1", "fusion.1", idx) == "attn"
    # scope-named events (TPU device lanes) classify directly
    assert classify_event("M/decoder/layers/0/mlp/wo/dot", "", None) == "mlp"
    # nothing known → other
    assert classify_event("dot.7", "dot.7", idx) == "other"
    assert classify_event("dot.7", "dot.7", None) == "other"


# ---------------------------------------------------------------------------
# fixture-pinned parse: known durations → exact account
# ---------------------------------------------------------------------------

# one hand-written trace-viewer session: timings in µs, chosen so every
# derived number below is exact decimal arithmetic
_FIXTURE_OP_BUCKETS = {"fusion.1": "attn", "fusion.2": "mlp"}


def _fixture_events() -> list[dict]:
    def x(name, ts, dur, tid):
        return {
            "ph": "X", "pid": 1, "tid": tid, "ts": float(ts),
            "dur": float(dur), "name": name,
            "args": {"hlo_module": "jit_train_step", "hlo_op": name},
        }

    return [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
         "args": {"name": "tf_XLAEigen/7"}},
        # host-side python noise: no hlo_op, no /device: pid → excluded
        {"ph": "X", "pid": 1, "tid": 99, "ts": 0.0, "dur": 9500.0,
         "name": "PjitFunction(train_step)"},
        x("fusion.1", 0, 4000, 7),        # attn   [0, 4000)
        x("fusion.2", 4000, 2000, 7),     # mlp    [4000, 6000)
        x("all-reduce.3", 5000, 2000, 8),  # comm  [5000, 7000) — 1 ms under compute
        x("dot.4", 8000, 1000, 7),        # other  [8000, 9000) after 1 ms idle
    ]


def _write_fixture_trace(dir_path: str, events: list[dict]) -> str:
    session = os.path.join(dir_path, "plugins", "profile", "2026_08_04_00_00_00")
    os.makedirs(session, exist_ok=True)
    path = os.path.join(session, "host.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump({"displayTimeUnit": "ns", "traceEvents": events}, f)
    return path


def test_fixture_trace_exact_account(tmp_path):
    _write_fixture_trace(str(tmp_path), _fixture_events())
    acct = device_account_from_dir(
        str(tmp_path), op_buckets=_FIXTURE_OP_BUCKETS
    )
    assert acct is not None and acct["events"] == 4
    assert acct["span_ms"] == 9.0
    # busy union [0,7000)∪[8000,9000) = 8 ms; exposed idle = 1 ms
    assert acct["busy_ms"] == 8.0
    assert acct["exposed_idle_ms"] == 1.0
    b = acct["buckets_ms"]
    assert b["attn"] == 4.0 and b["mlp"] == 2.0
    assert b["collective"] == 2.0 and b["other"] == 1.0
    assert b["embed"] == b["head"] == b["optimizer"] == b["infeed"] == 0.0
    # per-bucket sums cover the measured device span entirely (the
    # acceptance bar is ≥ 90%; an attributed-total parse hits 100%+)
    assert sum(b.values()) >= 0.9 * acct["busy_ms"]
    assert acct["bucket_frac"]["attn"] == pytest.approx(4.0 / 9.0, abs=1e-4)
    assert acct["collectives"] == {
        "all-reduce": {"count": 1, "time_ms": 2.0, "wall_ms": 2.0}
    }
    ov = acct["overlap"]
    # compute [0,6000)∪[8000,9000) = 7 ms; comm [5000,7000) = 2 ms;
    # intersection [5000,6000) = 1 ms → half the comm hid under compute
    assert ov["compute_ms"] == 7.0 and ov["collective_ms"] == 2.0
    assert ov["overlapped_ms"] == 1.0 and ov["exposed_collective_ms"] == 1.0
    assert ov["overlap_frac"] == 0.5
    # lanes: one merged slice per bucket, start-ordered, ms-relative
    assert acct["lanes"] == [
        ["attn", 0.0, 4.0], ["mlp", 4.0, 2.0],
        ["collective", 5.0, 2.0], ["other", 8.0, 1.0],
    ]


def test_fixture_bandwidth_join_exact(tmp_path):
    """Known collective durations + the static byte account reproduce
    hand-computed achieved-bandwidth numbers exactly."""
    _write_fixture_trace(str(tmp_path), _fixture_events())
    acct = device_account_from_dir(str(tmp_path), op_buckets=_FIXTURE_OP_BUCKETS)
    comm = {
        "all-reduce": {"count": 1, "gradient_bytes": 600, "activation_bytes": 400},
        "total_bytes": 1000,  # rollup keys must be ignored by the join
    }
    join_collective_bandwidth(acct, comm, window_steps=2)
    slot = acct["collectives"]["all-reduce"]
    assert slot["bytes_per_step"] == 1000
    # 1000 B/step × 2 steps over 2 ms of device time = 1,000,000 B/s
    assert slot["achieved_bytes_per_sec"] == 1_000_000.0
    # no byte row for the op → time stays, no bandwidth claim
    acct2 = device_account_from_dir(str(tmp_path), op_buckets=_FIXTURE_OP_BUCKETS)
    join_collective_bandwidth(acct2, {"reduce-scatter": {"gradient_bytes": 8}}, 2)
    assert "achieved_bytes_per_sec" not in acct2["collectives"]["all-reduce"]


def test_bandwidth_uses_cross_lane_wall_not_summed_time(tmp_path):
    """On a multi-device host every participant emits its own collective
    event; the bandwidth denominator must be the cross-lane WALL (union),
    not the lane-summed device·time — else achieved bytes/sec reads N×
    too low on an N-device host."""
    events = [
        # 4 participants run the same 2 ms all-reduce concurrently
        {"ph": "X", "pid": 1, "tid": 10 + i, "ts": 1000.0, "dur": 2000.0,
         "name": "all-reduce.1", "args": {"hlo_op": "all-reduce.1"}}
        for i in range(4)
    ]
    _write_fixture_trace(str(tmp_path), events)
    acct = device_account_from_dir(str(tmp_path))
    slot = acct["collectives"]["all-reduce"]
    assert slot["count"] == 4
    assert slot["time_ms"] == 8.0   # summed device·time (4 lanes × 2 ms)
    assert slot["wall_ms"] == 2.0   # the wire was busy for 2 ms of wall
    join_collective_bandwidth(
        acct, {"all-reduce": {"gradient_bytes": 1000, "activation_bytes": 0}}, 2
    )
    # 1000 B/step × 2 steps over 2 ms WALL = 1,000,000 B/s — the
    # lane-summed time would have claimed a quarter of that
    assert slot["achieved_bytes_per_sec"] == 1_000_000.0


def test_device_pid_aggregate_lanes_excluded():
    """TPU-style traces stack 'XLA Modules'/'Steps' lanes under each
    device pid — whole-step slices enclosing every op.  Counting them
    would balloon 'other' and pin overlap_frac at 1.0, so only the
    per-op lanes survive normalization."""
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 7, "tid": 3, "name": "thread_name",
         "args": {"name": "Steps"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0.0, "dur": 100.0,
         "name": "model/encoder/block_0/mlp/wi/dot"},
        {"ph": "X", "pid": 7, "tid": 2, "ts": 0.0, "dur": 1000.0,
         "name": "jit_train_step"},
        {"ph": "X", "pid": 7, "tid": 3, "ts": 0.0, "dur": 1000.0,
         "name": "step 5"},
    ]
    ops = device_op_events(events)
    assert [e["name"] for e in ops] == ["model/encoder/block_0/mlp/wi/dot"]
    acct = build_account(ops)
    assert acct["buckets_ms"]["mlp"] == 0.1
    assert acct["buckets_ms"]["other"] == 0.0


def test_truncated_capture_clamps_window(tmp_path, capsys):
    """A run that dies inside the profile window reports the steps it
    actually captured — the scheduled stop would inflate every per-step
    consumer (the bandwidth join multiplies bytes/step by window steps)."""
    from distributed_llms_example_tpu.obs.profile import ProfileController

    ctl = ProfileController(
        steps_spec="5:10", output_dir=str(tmp_path), start_step=0
    )
    seen = []
    ctl.on_capture = lambda d, w, t: seen.append((w, t))
    ctl.before_step(5)
    assert ctl.active
    # the run ends after step 6 — four scheduled steps never happen
    ctl.finalize(None, last_step=6)
    assert seen == [((5, 6), True)]
    lines = _json_lines(capsys.readouterr().out)
    cap = next(r for r in lines if r.get("event") == "profile_captured")
    assert cap["window"] == [5, 6] and cap["steps"] == 2
    assert cap["truncated"] is True


def _json_lines(text):
    out = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def test_find_trace_files_newest_session_and_empty(tmp_path):
    assert device_account_from_dir(str(tmp_path / "nothing")) is None
    old = _write_fixture_trace(str(tmp_path), _fixture_events())
    # a newer session with one tiny event must win the session pick
    newer = os.path.join(
        str(tmp_path), "plugins", "profile", "2026_08_04_11_11_11"
    )
    os.makedirs(newer)
    with open(os.path.join(newer, "host.trace.json"), "w") as f:
        json.dump({"traceEvents": [{
            "ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 500.0,
            "name": "dot.1", "args": {"hlo_op": "dot.1"},
        }]}, f)
    os.utime(old, (1, 1))  # the gz is the OLD session now
    acct = device_account_from_dir(str(tmp_path))
    assert acct is not None and acct["events"] == 1
    assert acct["span_ms"] == 0.5
    # an empty-events trace parses to None, not a zero account
    shutil.rmtree(os.path.join(str(tmp_path), "plugins"))
    _write_fixture_trace(str(tmp_path), [
        {"ph": "M", "pid": 1, "name": "process_name", "args": {"name": "x"}},
    ])
    assert device_account_from_dir(str(tmp_path)) is None


def test_account_lane_cap_counts_drops(tmp_path):
    events = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": float(i * 10), "dur": 4.0,
         "name": f"dot.{i}", "args": {"hlo_op": f"dot.{i}"}}
        for i in range(50)
    ]
    _write_fixture_trace(str(tmp_path), events)
    normalized = device_op_events(
        json.load(gzip.open(find_trace_files(str(tmp_path))[0], "rt"))["traceEvents"]
    )
    acct = build_account(normalized, max_lane_slices=8)
    assert len(acct["lanes"]) == 8
    assert acct["lane_slices_dropped"] == 42  # counted, never silent


# ---------------------------------------------------------------------------
# fake-capture end-to-end: TrainerObs parse → JSONL → report → Perfetto
# ---------------------------------------------------------------------------


def _obs_with_fixture_capture(tmp_path) -> TrainerObs:
    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", log_every_steps=1,
        health="off",
    )
    obs = TrainerObs(cfg, start_step=0)
    assert obs.budget is not None
    # what startup_gauges would have supplied (gauges are off here: no
    # AOT compile in a fast test)
    obs._op_buckets = dict(_FIXTURE_OP_BUCKETS)
    obs._comm_account = {
        "all-reduce": {"count": 1, "gradient_bytes": 600, "activation_bytes": 400},
    }
    capture_dir = os.path.join(str(tmp_path), "capture")
    _write_fixture_trace(capture_dir, _fixture_events())
    # drive three steps so the trace export has host step marks around
    # the capture window [2, 3]
    for step in (1, 2):
        with obs.step_span():
            pass
        obs.on_step(step, 0, {})
    # the capture "lands" after step 3's work, before its cadence close
    obs._on_profile_captured(capture_dir, (2, 3))
    with obs.step_span():
        pass
    obs.on_step(3, 0, {})
    sink_mod.emit({
        "event": "profile_captured", "path": capture_dir,
        "window": [2, 3], "steps": 2,
    }, all_processes=True)
    obs.finalize(3, 0)
    sink_mod.current_sink().close()
    return obs


def test_fake_capture_roundtrip_jsonl_report_trace(tmp_path):
    obs = _obs_with_fixture_capture(tmp_path)
    # in-process: bench's read surface
    assert obs.budget.last_device_account is not None
    assert obs.budget.last_device_account["window"] == [2, 3]

    # schema round-trip: device_account + profile_captured parse back
    # through the report loader schema-checked
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records, errors = load_jsonl(path)
    assert errors == []
    events = {r.get("event", "metric") for r in records}
    assert {"device_account", "profile_captured", "step_budget"} <= events
    acct = next(r for r in records if r.get("event") == "device_account")
    assert acct["window"] == [2, 3] and acct["window_steps"] == 2
    assert acct["buckets_ms"]["attn"] == 4.0
    # the runtime join already stamped achieved bandwidth (gauges' comm)
    assert acct["collectives"]["all-reduce"]["achieved_bytes_per_sec"] == 1_000_000.0

    # the report renders bucket + bandwidth + overlap from JSONL ALONE:
    # remove the trace files first to prove it
    shutil.rmtree(os.path.join(str(tmp_path), "capture"))
    report = build_report(str(tmp_path))
    assert report["schema_errors"] == []
    device = report["device"]
    assert device["accounts"] == 1 and set(device["ranks"]) == {"0"}
    assert device["captures"][0]["window"] == [2, 3]
    md = render_markdown(report)
    assert "Device account (profiled windows)" in md
    assert "all-reduce" in md and "1.0 MB/s achieved" in md
    assert "overlap_frac 0.5" in md

    # Perfetto: device lanes beside the host spans, end-aligned on the
    # capture window's closing step ordinal
    from distributed_llms_example_tpu.obs.trace import build_trace

    trace = build_trace(str(tmp_path))
    dev = [e for e in trace["traceEvents"]
           if str(e.get("name", "")).startswith("dev:")]
    assert {e["name"] for e in dev} == {
        "dev:attn", "dev:mlp", "dev:collective", "dev:other"
    }
    marks = {
        int(s): t for r in records if r.get("event") == "trace_spans"
        for s, t in r.get("steps", [])
    }
    assert 3 in marks  # the closing step has a host mark
    t_end_us = marks[3] * 1e6
    for e in dev:
        assert e["ts"] + e["dur"] <= t_end_us + 1.0  # end-aligned at step 3
    # the attn slice spans [t_end - span, t_end - span + 4ms]
    attn = next(e for e in dev if e["name"] == "dev:attn")
    assert attn["dur"] == pytest.approx(4000.0)
    assert attn["ts"] == pytest.approx(t_end_us - 9000.0, abs=1.0)


def test_strict_min_overlap_frac_gate(tmp_path, capsys):
    from distributed_llms_example_tpu.obs.report import main as report_main

    _obs_with_fixture_capture(tmp_path)
    # overlap_frac 0.5: a 0.9 floor fails, a 0.3 floor passes
    rc = report_main([
        str(tmp_path), "--strict", "--min-overlap-frac", "0.9", "--json",
    ])
    assert rc == 1
    assert "overlap_frac 0.5 below" in capsys.readouterr().err
    assert report_main([
        str(tmp_path), "--strict", "--min-overlap-frac", "0.3", "--json",
    ]) == 0
    # and without the floor the same run is strict-green
    assert report_main([str(tmp_path), "--strict", "--json"]) == 0


def test_strict_fails_on_capture_without_account(tmp_path, capsys):
    from distributed_llms_example_tpu.obs.report import main as report_main

    obs_dir = os.path.join(str(tmp_path), "obs")
    os.makedirs(obs_dir)
    with open(os.path.join(obs_dir, "metrics-p000.jsonl"), "w") as f:
        f.write(json.dumps({
            "schema_version": 1, "event": "profile_captured",
            "path": "/tmp/x", "window": [2, 3], "steps": 2,
        }) + "\n")
    # a capture landed but no device_account: the gate must not pass
    rc = report_main([
        str(tmp_path), "--strict", "--min-overlap-frac", "0.1", "--json",
    ])
    assert rc == 1
    assert "no device_account" in capsys.readouterr().err
    # without the device floor this is not gated (budget-only runs)
    assert report_main([str(tmp_path), "--strict", "--json"]) == 0


def test_obs_gate_min_overlap_passthrough(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_gate",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "obs_gate.py"),
    )
    obs_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_gate)

    _obs_with_fixture_capture(tmp_path)
    # dispatch efficiency floor 0 disables that gate; the overlap floor
    # rides through to report --strict
    assert obs_gate.main([
        str(tmp_path), "--min-dispatch-efficiency", "0",
        "--min-overlap-frac", "0.3",
    ]) == 0
    assert obs_gate.main([
        str(tmp_path), "--min-dispatch-efficiency", "0",
        "--min-overlap-frac", "0.9",
    ]) == 1


# ---------------------------------------------------------------------------
# --profile-on-anomaly: an agreed anomaly arms the trigger machinery
# ---------------------------------------------------------------------------


def test_profile_on_anomaly_arms_trigger(tmp_path):
    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", health="on",
        log_every_steps=2, recorder_steps=8, profile_on_anomaly=True,
    )
    obs = TrainerObs(cfg, start_step=0)
    trigger = os.path.join(str(tmp_path), "obs", "profile.trigger")
    assert obs._trigger == trigger
    with obs.step_span():
        pass
    assert obs.on_step(
        1, 0, {"loss": 2.0, "grad_norm": 1.0, "nonfinite_count": 0.0}
    ) == "ok"
    assert not os.path.exists(trigger)  # healthy window: not armed
    with obs.step_span():
        pass
    action = obs.on_step(
        2, 0, {"loss": float("nan"), "grad_norm": 1.0, "nonfinite_count": 1.0}
    )
    assert action == "warn"
    # the anomaly armed the profiler's OWN trigger file (the same file an
    # operator would touch), so the NEXT before_step opens a capture
    assert os.path.exists(trigger)
    with open(trigger) as f:
        assert int(f.read()) >= 1
    sink_mod.current_sink().close()
    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records, errors = load_jsonl(path)
    assert errors == []
    armed = next(r for r in records if r.get("event") == "profile_trigger_armed")
    assert armed["reason"] == "anomaly:nonfinite" and armed["step"] == 2


def test_profile_on_anomaly_off_by_default(tmp_path):
    cfg = TrainConfig(
        output_dir=str(tmp_path), obs="jsonl", health="on",
        log_every_steps=1, recorder_steps=8,
    )
    obs = TrainerObs(cfg, start_step=0)
    with obs.step_span():
        pass
    obs.on_step(1, 0, {"loss": float("nan"), "grad_norm": 1.0,
                       "nonfinite_count": 1.0})
    assert not os.path.exists(
        os.path.join(str(tmp_path), "obs", "profile.trigger")
    )
    sink_mod.current_sink().close()


# ---------------------------------------------------------------------------
# the real thing: CPU-captured profile round-trip on the 8-device mesh
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~45s: jax profiler session init + a real t5-test train
def test_e2e_profiled_window_device_account(tmp_path):
    """The acceptance run: an 8-device CPU-mesh trainer with a profiled
    window emits device_account events whose bucket sums cover ≥ 90% of
    the measured device span, obs.report renders the tables from the
    JSONL alone (trace files deleted first), and the Perfetto export
    carries device lanes.  --profile-on-anomaly rides the same run
    through the poison-step hook and arms a SECOND capture."""
    import numpy as np

    from distributed_llms_example_tpu.train.trainer import Trainer

    rng = np.random.RandomState(0)
    recs = [
        {
            "dialogue": " ".join(f"w{rng.randint(40)}" for _ in range(12)),
            "summary": f"w{rng.randint(40)}",
        }
        for _ in range(16)
    ]
    cfg = TrainConfig(
        model_ckpt="t5-test",
        output_dir=str(tmp_path),
        batch_size=8,
        num_epochs=4,  # 2 steps/epoch → 8 steps
        warmup_steps=1,
        evaluation_steps=0,
        max_source_length=32,
        max_target_length=16,
        pad_to_multiple=32,
        log_every_steps=1,
        num_beams=1,
        tokenizer="byte",
        mesh=MeshConfig(data=-1),
        checkpoint=CheckpointConfig(save_every_steps=0, resume=False, async_save=False),
        obs="jsonl",
        obs_gauges="on",  # the op_name index + byte account for the join
        health="on",
        on_anomaly="warn",
        recorder_steps=8,
        profile_steps="2:3",  # the profiled window
        profile_on_anomaly=True,
    )
    trainer = Trainer(cfg, train_records=recs)
    trainer.save_final = lambda: None
    trainer._poison_nan_at_step = 5  # detected at 5 → arms capture of 6-8
    result = trainer.train()
    assert result["steps"] == 8

    path = os.path.join(str(tmp_path), "obs", "metrics-p000.jsonl")
    records, errors = load_jsonl(path)
    assert errors == []
    captured = [r for r in records if r.get("event") == "profile_captured"]
    assert len(captured) >= 2  # the window capture AND the anomaly capture
    assert captured[0]["window"] == [2, 3]
    accounts = [r for r in records if r.get("event") == "device_account"]
    assert accounts, "no device_account emitted for the profiled window"
    acct = accounts[0]
    assert acct["window"] == [2, 3] and acct["window_steps"] == 2
    # the acceptance bar: per-bucket device times sum to ≥ 90% of the
    # window's measured device span (busy union) — nothing unattributed
    total = sum(acct["buckets_ms"].values())
    assert total >= 0.9 * acct["busy_ms"] > 0
    assert set(acct["buckets_ms"]) == set(DEVICE_BUCKETS)
    # the 8-way data-parallel step all-reduces its grads: collective
    # device time must be measured and the byte join must land
    assert "all-reduce" in acct["collectives"]
    ar = acct["collectives"]["all-reduce"]
    assert ar["time_ms"] > 0
    assert ar.get("bytes_per_step", 0) > 0
    assert ar.get("achieved_bytes_per_sec", 0) > 0
    assert "overlap" in acct and acct["overlap"]["collective_ms"] > 0

    # report renders the tables from the JSONL alone — trace dirs gone
    shutil.rmtree(os.path.join(str(tmp_path), "obs", "profile"))
    report = build_report(str(tmp_path))
    assert report["schema_errors"] == []
    assert report["device"] is not None and report["device"]["ranks"]
    md = render_markdown(report)
    assert "Device account (profiled windows)" in md
    assert "all-reduce" in md and "achieved" in md

    # Perfetto export: host and device lanes on the shared step ordinals
    from distributed_llms_example_tpu.obs.trace import export_chrome_trace

    out = os.path.join(str(tmp_path), "trace.json")
    export_chrome_trace(str(tmp_path), out)
    trace = json.load(open(out))
    names = {str(e.get("name", "")) for e in trace["traceEvents"]}
    assert any(n.startswith("dev:") for n in names)
    assert any(n.startswith("step ") for n in names)
